# Developer entry points. `check` is the static gate (reference CI parity:
# mypy + flake8 per .circleci/config.yml:33-38): the dependency-free AST
# lint + thivelint analyzer always run; mypy/ruff run when installed
# (absent from this image).
.PHONY: check lint analysis analysis-fast lockcheck test bench probe metrics-smoke decode-smoke alerts-smoke chaos-smoke serving-smoke serving-mesh-smoke trace-smoke prefix-smoke spec-smoke serving-chaos-smoke quant-smoke history-smoke tier-smoke usage-smoke agent-smoke

check: lint analysis
	@command -v ruff >/dev/null 2>&1 && ruff check . || echo "ruff not installed; skipped (tools/lint.py covered the always-on subset)"
	@command -v mypy >/dev/null 2>&1 && mypy || echo "mypy not installed; skipped (tools/lint.py covered the always-on subset)"

lint:
	python tools/lint.py

# the multi-pass static analyzer (docs/STATIC_ANALYSIS.md): lock discipline,
# exception hygiene, blocking calls, JAX host-sync, plus the flow-aware
# families (TH-JIT recompile hazards, TH-DON donation discipline, TH-REF
# refcount pairing) and the TH-X cross-artifact contract pass — `lint` is
# an alias that runs the same passes; this target exists for the pinned CI
# gate order
analysis:
	python -m tools.analysis

# pre-commit speed: analyze only files changed vs HEAD (staged + unstaged +
# untracked). Cross-artifact contracts (TH-X) still run — a docs drift must
# not slip through a code-only diff. The full walk stays the CI gate.
analysis-fast:
	python -m tools.analysis --changed-only

# the interprocedural deadlock pass alone (docs/STATIC_ANALYSIS.md
# "TH-LOCK"), then both serving smokes re-run with the runtime lock
# witness on: zero observed ABBA inversions and every observed order edge
# must exist in the static graph — a green run is an executable proof the
# static model over-approximates the program it claims to describe
lockcheck:
	python -m tools.analysis --select TH-LOCK
	TPUHIVE_LOCK_WITNESS=1 python tools/trace_smoke.py
	TPUHIVE_LOCK_WITNESS=1 python tools/serving_chaos_smoke.py

test:
	python -m pytest tests/ -q

bench:
	python bench.py

# boots the WSGI app in-process on an ephemeral port and scrapes
# /api/metrics over HTTP (Prometheus text-format smoke test)
metrics-smoke:
	python tools/metrics_smoke.py

# CPU-backend tiny-config generate round-trip over the decode fast path
# (donated in-place cache + bucketed prefill): prints tokens/s and the
# compile counter, fails on round-trip or executable-count regressions
decode-smoke:
	python tools/decode_smoke.py

# boots the WSGI app with a deliberately dead daemon service: /api/readyz
# must flip to 503 naming it, the service_down rule must fire exactly once
# through the sink fan-out, then resolve once the service starts
alerts-smoke:
	python tools/alerts_smoke.py

# deterministic fault-injection walk (docs/ROBUSTNESS.md): kill a fake host
# -> breaker opens after N seeded failures, fan-out + queue scheduling skip
# it, readyz degrades -> revive -> half-open probe closes it, alert
# fires/resolves exactly once; fake clock + seeded rng, zero real waiting
chaos-smoke:
	python tools/chaos_smoke.py

# continuous-batching gateway on the CPU tiny model: >= 8 mixed-length
# requests join/leave one running batch, zero decode recompiles after
# warmup, batched throughput >= 2x the serial path, queue metrics present,
# one admission rejection when over capacity (docs/SERVING.md)
serving-smoke:
	python tools/serving_smoke.py

# multi-chip serving on 8 forced host devices (the MULTICHIP dryrun trick):
# a mesh_dp=2 x mesh_tp=2 engine must emit tokens identical to the 1x1
# engine, recompile nothing after warmup, scale slot capacity by dp at
# equal per-chip HBM, and the 1x1 config must roll back to the single-chip
# executables fingerprint-identically (docs/SERVING.md "Multi-chip serving")
serving-mesh-smoke:
	python tools/serving_mesh_smoke.py

# request tracing + on-demand profiling over real HTTP: one streamed
# /api/generate request must land in /api/admin/requests with sanely
# ordered phase timings and request_id-labelled spans, a profile capture
# must write a real artifact on the CPU backend, and the queue-wait
# histogram + per-device HBM gauge must be scrapeable (docs/OBSERVABILITY.md
# "Request tracing & profiling")
trace-smoke:
	python tools/trace_smoke.py

# radix prefix cache + chunked prefill on the CPU tiny model: cache-hit
# TTFT below miss TTFT at equal tokens, shared-prefix fan-in admits
# strictly > 2.5x the contiguous concurrency at equal HBM, the running
# batch emits a token every tick while a long prompt chunk-prefills, zero
# post-warmup recompiles (docs/SERVING.md "Prefix cache & chunked prefill")
prefix-smoke:
	python tools/prefix_smoke.py

# speculative decoding lane over a real socket: the spec-on stream must be
# token-identical to the spec-off stream, acceptance counters scrapeable,
# ledger rows carrying draftTokens/acceptedTokens, zero post-warmup
# recompiles across speculative ticks (docs/SERVING.md "Speculative
# decoding")
spec-smoke:
	python tools/spec_smoke.py

# serving data-plane chaos (docs/ROBUSTNESS.md "Serving data plane"):
# seeded ServingFaultPlan over a real socket — kill a step mid-stream ->
# the client gets the terminal error chunk within its deadline (zero hung
# streams), the supervisor auto-restores token-identically, a forced
# crash loop trips the breaker (503 + reason, engine_crash_loop fires)
# and recovery resolves it, drain/resume close and reopen admission
serving-chaos-smoke:
	python tools/serving_chaos_smoke.py

# int8 KV pages over a real socket (docs/SERVING.md "Quantized KV pages"):
# a kv_quant=on stream's greedy tokens must match the f32 reference at the
# gated rate, the int8 pool must admit >= 1.8x the f32 pool's concurrent
# sequences at EQUAL HBM bytes, zero post-warmup recompiles across page
# assignment + scale updates, kv_bytes gauges scrapeable
quant-smoke:
	python tools/quant_smoke.py

# time-aware telemetry over a real socket (docs/OBSERVABILITY.md "History,
# SLOs & flight recorder"): a 0.05 s HistoryService must land >= 2
# queue-depth samples served by /api/admin/history, the SLO engine must
# export a tpuhive_slo_burn_rate gauge once traffic flowed, the live
# flightrec ring must stamp the served work, and one injected fatal must
# leave exactly one crash dump whose last tick shows the fault
history-smoke:
	python tools/history_smoke.py

# KV-page tiering over a real socket (docs/SERVING.md "KV-page tiering"):
# a cold miss, pool-pressure demotion to host RAM, then a host-tier hit
# must emit IDENTICAL tokens with a LOWER TTFT than the miss, the ledger
# must carry hostHitPages/promoteMs, the host_kv counters and byte gauges
# must be scrapeable, zero post-warmup recompiles across the round trip
tier-smoke:
	python tools/tier_smoke.py

# tenant attribution over a real socket (docs/OBSERVABILITY.md "Tenant
# accounting"): two tenants stream concurrently -> /api/admin/usage share
# fractions sum to 1.0 with the heavier tenant ahead, ?user= isolates one
# tenant on both the usage rollup and the request ledger, the scrape holds
# <= top_k_tenants+1 tenant children, zero post-warmup recompiles
usage-smoke:
	python tools/usage_smoke.py

# host membership plane over a real socket (docs/ROBUSTNESS.md "Host
# membership & leases"): dynamic agent join -> live with zero SSH
# round-trips, silence walks suspect -> expired within 3x heartbeat with
# host_lease_expired firing exactly once, the preempted host's job is
# reaped without crashing the scheduling tick, re-join restores service
agent-smoke:
	python tools/agent_smoke.py

probe:
	$(MAKE) -C tensorhive_tpu/native
