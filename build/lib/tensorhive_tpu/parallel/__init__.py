"""SPMD parallelism layer: device meshes, sharding rules, ring attention.

This subsystem has no counterpart in the reference — TensorHive only
*launches* distributed trainings and leaves intra-job parallelism to the user
program (SURVEY.md §2.6: "TP / PP / EP / CP / SP: NO — the launched user
program owns intra-job parallelism"). The TPU rebuild ships that missing
layer as a first-class library so the workloads it schedules (the
t2t_transformer / Llama acceptance configs in BASELINE.json) are themselves
TPU-native: shardings over a ``jax.sharding.Mesh``, XLA collectives over
ICI, ring attention for sequence parallelism.
"""
from .mesh import (
    MeshRules,
    batch_sharding,
    best_mesh_shape,
    make_mesh,
    param_sharding,
)
from .ring import ring_attention

__all__ = [
    "MeshRules",
    "make_mesh",
    "best_mesh_shape",
    "param_sharding",
    "batch_sharding",
    "ring_attention",
]
