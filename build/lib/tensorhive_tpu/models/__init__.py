"""Model zoo: the workloads the cluster manager schedules onto slices.

The flagship is the decoder-only transformer LM (models/transformer.py) —
the TPU-native analog of the reference's ``t2t_transformer`` acceptance
workload (examples/t2t_transformer/README.md points at an external
tensor2tensor benchmark; BASELINE.json config 3 makes it the headline
benchmark of this rebuild).
"""
from .transformer import (
    TransformerConfig,
    TransformerLM,
    PRESETS,
)

__all__ = ["TransformerConfig", "TransformerLM", "PRESETS"]
