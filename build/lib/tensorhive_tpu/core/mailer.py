"""SMTP mailer + message templating.

Reference: tensorhive/core/utils/mailer.py — ``Message`` MIME builder (:11),
``MessageBodyTemplater.fill_in`` with {gpus}/{intruder_username}/... slots
(:51), ``Mailer`` SMTP(+STARTTLS) wrapper (:64). Same shape, TPU-flavored
template variables ({chips} instead of {gpus}).
"""
from __future__ import annotations

import logging
import smtplib
from email.mime.multipart import MIMEMultipart
from email.mime.text import MIMEText
from typing import Dict, List, Optional

from ..config import MailbotConfig

log = logging.getLogger(__name__)


class Message:
    """One MIME email (reference mailer.py:11-48)."""

    def __init__(self, author: str, to: List[str], subject: str, body: str) -> None:
        self.author = author
        self.to = list(to)
        self.subject = subject
        self.body = body

    def as_mime(self) -> MIMEMultipart:
        mime = MIMEMultipart("alternative")
        mime["From"] = self.author
        mime["To"] = ", ".join(self.to)
        mime["Subject"] = self.subject
        mime.attach(MIMEText(self.body, "html"))
        return mime


class MessageBodyTemplater:
    """Fill named slots in an HTML template (reference mailer.py:51-61)."""

    def __init__(self, template: str) -> None:
        self.template = template

    def fill_in(self, values: Dict[str, str]) -> str:
        body = self.template
        for key, value in values.items():
            body = body.replace("{%s}" % key, str(value))
        return body


INTRUDER_EMAIL_TEMPLATE = """\
<html><body>
<p>Hello {intruder_username},</p>
<p>Your processes (PIDs: {pids}) are running on TPU chips <b>{chips}</b>
which are currently reserved by <b>{owners}</b>.</p>
<p>Please terminate them or move to unreserved chips — otherwise they may be
killed by the protection service.</p>
<p>— tpuhive</p>
</body></html>
"""

ADMIN_EMAIL_TEMPLATE = """\
<html><body>
<p>Reservation violation detected:</p>
<ul>
<li>intruder: <b>{intruder_username}</b></li>
<li>chips: {chips}</li>
<li>PIDs: {pids}</li>
<li>reservation owners: {owners}</li>
</ul>
</body></html>
"""


class Mailer:
    """Thin SMTP client (reference mailer.py:64-86)."""

    def __init__(self, config: MailbotConfig) -> None:
        self.config = config
        self._server: Optional[smtplib.SMTP] = None

    def connect(self) -> None:
        cfg = self.config
        self._server = smtplib.SMTP(cfg.smtp_server, cfg.smtp_port, timeout=15)
        self._server.starttls()
        if cfg.smtp_login:
            self._server.login(cfg.smtp_login, cfg.smtp_password)

    def send(self, message: Message) -> None:
        assert self._server is not None, "connect() first"
        self._server.sendmail(message.author, message.to, message.as_mime().as_string())

    def disconnect(self) -> None:
        if self._server is not None:
            try:
                self._server.quit()
            except smtplib.SMTPException:
                pass
            self._server = None

    def test_configuration(self) -> bool:
        """Connectivity self-test run before each batch (reference
        EmailSendingBehaviour tests SMTP config every trigger)."""
        try:
            self.connect()
            return True
        except (smtplib.SMTPException, OSError) as exc:
            log.error("SMTP configuration test failed: %s", exc)
            return False
        finally:
            self.disconnect()
