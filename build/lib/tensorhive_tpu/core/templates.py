"""Launch-topology templates: auto-filled distributed-run parameters.

This is the server-side rebuild of the reference's "parallelism strategies"
UI — the Vue task-template engine in
tensorhive/app/web/dev/src/.../TaskCreate.vue (861 LoC, SURVEY.md §2.5):
``TaskTemplateChooser`` offered *No template / TF ClusterSpec / TF_CONFIG /
PyTorch*, and TaskCreate auto-incremented ``--task_index``/``--rank``,
assigned ports from 2222, and prepended ``CUDA_VISIBLE_DEVICES``. Moving the
engine server-side makes it API-first (any client gets it) and adds the
TPU-native templates the north star requires (BASELINE.json: "templates gain
a jax.distributed.initialize template that wires coordinator/worker roles
across a pod slice").

A template takes a placement (ordered host/chip assignments) and returns one
task descriptor per process: command, env vars, params. The job controller
materializes them as Task rows with command segments.

Templates:

* ``jax``        — jax.distributed.initialize wiring: ``--coordinator_address``
                   (worker 0, port 8476), ``--num_processes``, ``--process_id``
                   params + ``TPU_VISIBLE_CHIPS``/``TPU_PROCESS_BOUNDS``-style
                   env; Cloud TPU autodetection still works when users omit
                   the params — they are additive.
* ``multislice`` — megascale env for multi-slice jobs over DCN:
                   MEGASCALE_COORDINATOR_ADDRESS / NUM_SLICES / SLICE_ID.
* ``torch-xla``  — PJRT_DEVICE=TPU + torchrun-style MASTER_ADDR/PORT,
                   NODE_RANK, nnodes (reference's torch.distributed template,
                   examples/PyTorch/README.md, rebuilt for torch-xla).
* ``tf-config``  — TF_CONFIG JSON env with smart port assignment starting at
                   2222 (reference "Smart TF_CONFIG", TaskCreate.vue:404-424).
* ``tf-cluster`` — TF1 ClusterSpec CLI params --ps_hosts/--worker_hosts/
                   --job_name/--task_index (TaskCreate.vue:202-206,379-390).
* ``plain``      — no distributed wiring, just per-task chip binding.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Sequence

from ..utils.exceptions import ValidationError

JAX_COORDINATOR_PORT = 8476
MEGASCALE_PORT = 8477
TF_BASE_PORT = 2222
TORCH_MASTER_PORT = 12355


@dataclasses.dataclass
class Placement:
    """One process slot: a host and the chips the process may use."""

    hostname: str
    address: str = ""            # routable address; defaults to hostname
    chips: Optional[List[int]] = None

    def __post_init__(self) -> None:
        if not self.address:
            self.address = self.hostname


@dataclasses.dataclass
class TaskSpec:
    """Renderer output: one process to spawn."""

    hostname: str
    command: str
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    params: Dict[str, str] = dataclasses.field(default_factory=dict)


Renderer = Callable[[str, Sequence[Placement], Dict], List[TaskSpec]]
_TEMPLATES: Dict[str, Renderer] = {}


def register_template(name: str):
    def decorate(fn: Renderer) -> Renderer:
        _TEMPLATES[name] = fn
        return fn
    return decorate


def template_names() -> List[str]:
    return sorted(_TEMPLATES)


def render_template(
    name: str,
    command: str,
    placements: Sequence[Placement],
    options: Optional[Dict] = None,
) -> List[TaskSpec]:
    try:
        renderer = _TEMPLATES[name]
    except KeyError:
        raise ValidationError(
            f"unknown template {name!r}; available: {template_names()}"
        )
    if not placements:
        raise ValidationError("template needs at least one placement")
    return renderer(command, list(placements), dict(options or {}))


def _chip_env(placement: Placement) -> Dict[str, str]:
    """Per-process chip binding (the reference prepends
    CUDA_VISIBLE_DEVICES=<n>, TaskCreate.vue convertResource :290-301).
    Uses the same constant chip accounting keys on (db/models/task.py)."""
    from ..db.models.task import CHIP_ENV_VAR

    if placement.chips is None:
        return {}
    return {CHIP_ENV_VAR: ",".join(str(c) for c in placement.chips)}


def _assign_ports(placements: Sequence[Placement], base_port: int) -> List[str]:
    """'addr:port' per placement; processes sharing a host get consecutive
    ports from base_port (reference smart-port assignment)."""
    next_port: Dict[str, int] = {}
    addresses = []
    for placement in placements:
        port = next_port.get(placement.address, base_port)
        next_port[placement.address] = port + 1
        addresses.append(f"{placement.address}:{port}")
    return addresses


@register_template("plain")
def _plain(command, placements, options) -> List[TaskSpec]:
    return [
        TaskSpec(hostname=p.hostname, command=command, env=_chip_env(p))
        for p in placements
    ]


@register_template("jax")
def _jax(command, placements, options) -> List[TaskSpec]:
    port = int(options.get("coordinator_port", JAX_COORDINATOR_PORT))
    coordinator = f"{placements[0].address}:{port}"
    specs = []
    for index, placement in enumerate(placements):
        env = _chip_env(placement)
        params = {
            "--coordinator_address": coordinator,
            "--num_processes": str(len(placements)),
            "--process_id": str(index),
        }
        specs.append(TaskSpec(placement.hostname, command, env=env, params=params))
    return specs


@register_template("multislice")
def _multislice(command, placements, options) -> List[TaskSpec]:
    """One placement per SLICE (each slice's worker-0); megascale env wires
    slices together over DCN; within each slice jax autodetects."""
    port = int(options.get("megascale_port", MEGASCALE_PORT))
    coordinator = f"{placements[0].address}:{port}"
    specs = []
    for slice_id, placement in enumerate(placements):
        env = {
            "MEGASCALE_COORDINATOR_ADDRESS": coordinator,
            "MEGASCALE_NUM_SLICES": str(len(placements)),
            "MEGASCALE_SLICE_ID": str(slice_id),
            "MEGASCALE_PORT": str(port),
            **_chip_env(placement),
        }
        specs.append(TaskSpec(placement.hostname, command, env=env))
    return specs


@register_template("torch-xla")
def _torch_xla(command, placements, options) -> List[TaskSpec]:
    port = int(options.get("master_port", TORCH_MASTER_PORT))
    master = placements[0].address
    specs = []
    for rank, placement in enumerate(placements):
        env = {
            "PJRT_DEVICE": "TPU",
            "MASTER_ADDR": master,
            "MASTER_PORT": str(port),
            "NODE_RANK": str(rank),
            "WORLD_SIZE": str(len(placements)),
            **_chip_env(placement),
        }
        specs.append(TaskSpec(placement.hostname, command, env=env))
    return specs


@register_template("tf-config")
def _tf_config(command, placements, options) -> List[TaskSpec]:
    """Smart TF_CONFIG: ports auto-assigned per host starting at 2222; an
    all-worker cluster where worker 0 acts as de-facto chief — matching the
    reference's generated TF_CONFIG (TaskCreate.vue:404-424)."""
    base_port = int(options.get("base_port", TF_BASE_PORT))
    addresses = _assign_ports(placements, base_port)
    cluster = {"worker": addresses}
    specs = []
    for index, placement in enumerate(placements):
        tf_config = json.dumps({
            "cluster": cluster,
            "task": {"type": "worker", "index": index},
        })
        specs.append(TaskSpec(
            placement.hostname, command,
            env={"TF_CONFIG": tf_config, **_chip_env(placement)},
        ))
    return specs


@register_template("tf-cluster")
def _tf_cluster(command, placements, options) -> List[TaskSpec]:
    """TF1 ClusterSpec params; options['num_ps'] placements become parameter
    servers (reference template tf1, TaskCreate.vue:202-206)."""
    num_ps = int(options.get("num_ps", 0))
    if num_ps >= len(placements):
        raise ValidationError("num_ps must leave at least one worker")
    base_port = int(options.get("base_port", TF_BASE_PORT))
    addresses = _assign_ports(placements, base_port)
    ps_hosts = ",".join(addresses[:num_ps])
    worker_hosts = ",".join(addresses[num_ps:])
    specs = []
    for index, placement in enumerate(placements):
        is_ps = index < num_ps
        params = {
            "--ps_hosts": ps_hosts,
            "--worker_hosts": worker_hosts,
            "--job_name": "ps" if is_ps else "worker",
            "--task_index": str(index if is_ps else index - num_ps),
        }
        specs.append(TaskSpec(placement.hostname, command,
                              env=_chip_env(placement), params=params))
    return specs
