"""Interactive account creation (CLI helper).

Reference: tensorhive/core/utils/AccountCreator.py:25-139 — ``run_prompt``
loops prompting for username/email/password/role, re-asks on validation
errors instead of aborting, supports creating several accounts in one
sitting (``create user --multiple``), and on first use bootstraps the
default group plus the global "can always use everything" restriction
(``_check_restrictions`` :113-139).

The prompt/confirm/echo callables are injected so the loop is unit-testable
without a TTY (the reference's interactive path was untested, SURVEY.md §4).
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional

from ..db.models.restriction import Restriction
from ..db.models.user import Group, User
from ..utils.exceptions import ValidationError
from ..utils.timeutils import utcnow

log = logging.getLogger(__name__)


def ensure_default_group_bootstrap(echo: Callable[[str], None] = log.info) -> Optional[Group]:
    """First-run bootstrap: a default group every new user auto-joins, tied
    to a global permissive restriction (reference
    AccountCreator._check_restrictions:113-139). Idempotent."""
    defaults = Group.get_default_groups()
    if defaults:
        return defaults[0]
    group = Group(name="users", is_default=True).save()
    restriction = Restriction(
        name="default: everything allowed", starts_at=utcnow(), is_global=True
    ).save()
    restriction.apply_to_group(group)
    echo("created default group with a permissive global restriction")
    return group


class AccountCreator:
    """Looped interactive account setup with per-field validation retry."""

    def __init__(
        self,
        prompt: Callable[..., str],
        confirm: Callable[..., bool],
        echo: Callable[[str], None],
        max_attempts_per_field: int = 5,
    ) -> None:
        self.prompt = prompt
        self.confirm = confirm
        self.echo = echo
        self.max_attempts = max_attempts_per_field

    # -- single-account creation (shared with `init` / non-interactive path) --
    @staticmethod
    def create_account(username: str, email: str, password: str, admin: bool = False) -> User:
        import sqlite3

        try:
            user = User(username=username, email=email, password=password).save()
        except sqlite3.IntegrityError as exc:
            # duplicate username racing past the prompt-time check — surface
            # it as the same error type the validators use, so both the CLI
            # and the interactive loop show a message instead of a traceback
            raise ValidationError(f"username {username!r} is already taken") from exc
        user.add_role("user")
        if admin:
            user.add_role("admin")
        for group in Group.get_default_groups():
            group.add_user(user)
        return user

    # -- interactive loop (reference run_prompt :25-111) ----------------------
    def run_prompt(
        self,
        multiple: bool = False,
        username: Optional[str] = None,
        email: Optional[str] = None,
        password: Optional[str] = None,
        admin: Optional[bool] = None,
    ) -> List[User]:
        """Prompt for one account (or several with ``multiple``); invalid
        field values re-prompt instead of aborting the whole flow.
        Pre-supplied ``username``/``email``/``password`` values are tried
        before prompting (partial CLI flags); ``admin=True`` skips the role
        question (``--admin`` on the interactive path). Presets apply to
        the first account only when looping."""
        ensure_default_group_bootstrap(self.echo)
        created: List[User] = []
        while True:
            user = self._prompt_one(username, email, password, admin)
            username = email = password = None  # presets are single-use
            if user is not None:
                created.append(user)
                self.echo(f"user {user.username!r} created")
            if not multiple or not self.confirm("create another account?", default=False):
                return created

    def _prompt_one(
        self,
        preset_username: Optional[str] = None,
        preset_email: Optional[str] = None,
        preset_password: Optional[str] = None,
        admin: Optional[bool] = None,
    ) -> Optional[User]:
        username = self._prompt_valid("username", User.validate_username,
                                      preset=preset_username)
        if username is None:
            return None
        email = self._prompt_valid("email", User.validate_email, preset=preset_email)
        if email is None:
            return None
        password = self._prompt_valid(
            "password",
            User.validate_password,
            preset=preset_password,
            hide_input=True,
            confirmation_prompt=True,
        )
        if password is None:
            return None
        if admin is None:
            admin = self.confirm("grant admin role?", default=False)
        try:
            return self.create_account(username, email, password, admin)
        except ValidationError as exc:
            # e.g. username/email raced into existence since the field check
            self.echo(f"cannot create account: {exc}")
            return None

    def _prompt_valid(
        self,
        field: str,
        validator: Callable[[str], None],
        preset: Optional[str] = None,
        **prompt_kwargs,
    ) -> Optional[str]:
        """Ask until the validator passes (reference re-asks per field rather
        than restarting, AccountCreator.py:45-78); give up after
        ``max_attempts`` so a scripted stdin can't loop forever. A ``preset``
        (CLI flag value) is validated first without consuming a prompt."""
        for attempt in range(self.max_attempts):
            if attempt == 0 and preset is not None:
                value = preset
            else:
                value = self.prompt(field, **prompt_kwargs)
            try:
                validator(value)
                return value
            except ValidationError as exc:
                self.echo(f"invalid {field}: {exc}")
        self.echo(f"too many invalid attempts for {field}; aborting this account")
        return None
