"""Telemetry monitors (reference: tensorhive/core/monitors/)."""
from .base import Monitor
from .cpu import CpuMonitor
from .tpu import TpuMonitor

__all__ = ["Monitor", "CpuMonitor", "TpuMonitor"]
