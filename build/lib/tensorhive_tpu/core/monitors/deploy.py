"""Build + deploy the native telemetry probe onto managed hosts.

The reference assumes ``nvidia-smi`` pre-exists on every managed node (it
ships with the driver). The TPU probe has no such luck, so the manager pushes
its own binary at startup: build locally with the in-tree Makefile (or use a
prebuilt), then copy to ``~/.tpuhive/bin/tpuhive-probe`` on each host. Hosts
where deployment fails silently fall back to the inline Python probe — the
monitoring tick works either way, just slower (interpreter startup dominates
the fallback's latency; see native/probe.cpp header).
"""
from __future__ import annotations

import concurrent.futures
import hashlib
import logging
import subprocess
from pathlib import Path
from typing import Dict, Optional

from ...utils.exceptions import TelemetryError, TransportError
from ..transport.base import TransportManager
from .probe import PROBE_REMOTE_PATH

log = logging.getLogger(__name__)

#: in-package so installed wheels can build+deploy the probe, not just
#: repo checkouts
NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"


def build_probe(native_dir: Optional[Path] = None) -> Path:
    """Compile the probe with the in-tree Makefile; returns the binary path.
    Raises TelemetryError when no toolchain is available."""
    native_dir = native_dir or NATIVE_DIR
    binary = native_dir / "bin" / "tpuhive-probe"
    if not (native_dir / "Makefile").exists():
        if binary.exists():
            return binary
        raise TelemetryError(f"native sources not found under {native_dir}")
    try:
        proc = subprocess.run(
            ["make", "-C", str(native_dir)], capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as exc:
        raise TelemetryError(f"probe build failed to run: {exc}") from exc
    if proc.returncode != 0:
        raise TelemetryError(f"probe build failed:\n{proc.stderr[-2000:]}")
    if not binary.exists():
        raise TelemetryError(f"probe build produced no binary at {binary}")
    return binary


def deploy_probe(
    transports: TransportManager, binary: Optional[Path] = None
) -> Dict[str, bool]:
    """Push the probe binary to every managed host; returns per-host success.
    A host that already has a byte-identical probe (sha256 match) is
    skipped; freshly pushed binaries are verified by executing them."""
    if binary is None:
        try:
            binary = build_probe()
        except TelemetryError as exc:
            log.warning("cannot build native probe (%s); hosts will use the "
                        "python fallback", exc)
            return {name: False for name in transports.hostnames}
    with open(binary, "rb") as fh:
        local_sha = hashlib.sha256(fh.read()).hexdigest()

    def _deploy_one(hostname: str) -> bool:
        transport = transports.for_host(hostname)
        try:
            current = transport.run(
                f"sha256sum {PROBE_REMOTE_PATH} 2>/dev/null | cut -d' ' -f1"
            )
            if current.ok and current.stdout.strip() == local_sha:
                return True
            transport.put_file(str(binary), PROBE_REMOTE_PATH)
            check = transport.run(PROBE_REMOTE_PATH)
            deployed = check.ok and check.stdout.lstrip().startswith("{")
            if not deployed:
                log.warning("deployed probe does not run on %s (foreign arch?); "
                            "python fallback will be used", hostname)
            return deployed
        except TransportError as exc:
            log.warning("probe deployment to %s failed: %s", hostname, exc)
            return False

    # deploy in parallel: boot cost is max(host), not sum(hosts)
    hostnames = transports.hostnames
    if not hostnames:
        return {}
    with concurrent.futures.ThreadPoolExecutor(
        max_workers=min(16, len(hostnames))
    ) as pool:
        return dict(zip(hostnames, pool.map(_deploy_one, hostnames)))
