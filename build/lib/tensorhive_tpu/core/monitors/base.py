"""Monitor strategy interface.

Reference: tensorhive/core/monitors/Monitor.py:5-13 — ``update(connection,
infrastructure_manager)`` run by MonitoringService against all hosts each
tick. Same shape here, with the group SSH client generalized to the
:class:`TransportManager` fan-out.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ..managers.infrastructure import InfrastructureManager
    from ..transport.base import TransportManager


class Monitor:
    """One telemetry dimension (TPU chips, CPU/RAM) polled per tick."""

    #: subtree key this monitor owns inside each node's infra dict
    key: str = ""

    def update(self, transports: "TransportManager", infra: "InfrastructureManager") -> None:
        """Poll all reachable hosts and write per-host subtrees into ``infra``.

        Must isolate per-host failures: one unreachable host may not prevent
        the others from updating (reference ``stop_on_errors=False``,
        GPUMonitor.py:77).
        """
        raise NotImplementedError
