"""Violation record + handler interface.

Reference: tensorhive/core/violation_handlers/ProtectionHandler.py:1-8 (an
indirection wrapping ``trigger_action``) and the per-intruder violation dict
ProtectionService aggregates (GPUS / OWNERS / SSH_CONNECTIONS /
VIOLATION_PIDS, ProtectionService.py:55-78). The dict becomes a typed
dataclass here.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List


@dataclasses.dataclass
class Violation:
    """Everything known about one intruder's trespass, aggregated across
    hosts/chips for a single protection tick."""

    intruder_username: str
    #: chip uids the intruder's processes occupy
    chip_uids: List[str] = dataclasses.field(default_factory=list)
    #: usernames of the reservation owners being violated (empty when the
    #: violation is "unreserved use" in strict mode)
    owner_usernames: List[str] = dataclasses.field(default_factory=list)
    #: hostname -> intruding PIDs on that host
    pids_by_host: Dict[str, List[int]] = dataclasses.field(default_factory=dict)
    #: True when no reservation exists at all (strict-mode violation)
    unreserved: bool = False

    @property
    def hostnames(self) -> List[str]:
        return list(self.pids_by_host)

    @property
    def all_pids(self) -> List[int]:
        return [pid for pids in self.pids_by_host.values() for pid in pids]


class ProtectionHandler:
    """Strategy interface (reference ProtectionHandler.trigger_action)."""

    def begin_tick(self) -> None:
        """Called once per protection tick before any trigger_action —
        the boundary for per-tick budgets (e.g. the email cap)."""

    def trigger_action(self, violation: Violation) -> None:
        raise NotImplementedError
