"""Process-killing handlers.

Reference: UserProcessKillingBehaviour.py:8-31 (SSH **as the intruder** —
their authorized_keys must contain the manager key — then plain ``kill``)
and SudoProcessKillingBehaviour.py:9-30 (manager account + ``sudo kill``,
config kill_processes=2).
"""
from __future__ import annotations

import logging
from typing import Optional

from ...utils.exceptions import TransportError
from ..nursery import OpsFactory, get_ops_factory
from .base import ProtectionHandler, Violation

log = logging.getLogger(__name__)


class ProcessKillingBehaviour(ProtectionHandler):
    """``sudo=False``: connect as the intruder and kill their PIDs (works
    only for accounts that installed the manager key). ``sudo=True``:
    connect as the manager account and ``sudo kill``."""

    def __init__(self, sudo: bool = False, ops_factory: Optional[OpsFactory] = None) -> None:
        self.sudo = sudo
        self._factory = ops_factory

    @property
    def factory(self) -> OpsFactory:
        return self._factory or get_ops_factory()

    def trigger_action(self, violation: Violation) -> None:
        for hostname, pids in violation.pids_by_host.items():
            user = None if self.sudo else violation.intruder_username
            try:
                ops = self.factory.ops_for(hostname, user=user)
                for pid in pids:
                    killed = ops.kill_pid(pid, sig=9, sudo=self.sudo)
                    log.info(
                        "%s pid %d of %s on %s",
                        "killed" if killed else "failed to kill",
                        pid, violation.intruder_username, hostname,
                    )
            except TransportError as exc:
                log.warning("kill handler failed on %s: %s", hostname, exc)
