"""Email notification handler with per-recipient rate limiting.

Reference: tensorhive/core/violation_handlers/EmailSendingBehaviour.py:27-156
— emails the intruder and/or admin using HTML templates, keeps a
``LastEmailTime`` timer per recipient so one trespass doesn't flood a
mailbox, bounds the per-tick send count (MAX_EMAILS_PER_PROTECTION_INTERVAL),
and re-tests the SMTP configuration on every trigger.
"""
from __future__ import annotations

import logging
import time as time_module
from typing import Dict, Optional

from ...config import MailbotConfig, get_config
from ..mailer import (
    ADMIN_EMAIL_TEMPLATE,
    INTRUDER_EMAIL_TEMPLATE,
    Mailer,
    Message,
    MessageBodyTemplater,
)
from .base import ProtectionHandler, Violation

log = logging.getLogger(__name__)


class EmailSendingBehaviour(ProtectionHandler):
    def __init__(self, config: Optional[MailbotConfig] = None,
                 mailer: Optional[Mailer] = None) -> None:
        self.config = config or get_config().mailbot
        self.mailer = mailer or Mailer(self.config)
        #: recipient email -> monotonic time of last send
        self._last_sent: Dict[str, float] = {}
        #: emails sent since the last begin_tick (cap boundary: one
        #: protection tick spans MANY trigger_action calls — one per intruder)
        self._sent_this_tick = 0

    def begin_tick(self) -> None:
        self._sent_this_tick = 0

    # -- rate limiting (reference LastEmailTime timers) ---------------------
    def _may_send(self, recipient: str) -> bool:
        if self._sent_this_tick >= self.config.max_emails_per_interval:
            return False
        last = self._last_sent.get(recipient)
        return last is None or (
            time_module.monotonic() - last >= self.config.interval_between_notifications_s
        )

    def _mark_sent(self, recipient: str) -> None:
        self._last_sent[recipient] = time_module.monotonic()
        self._sent_this_tick += 1

    # ----------------------------------------------------------------------
    def trigger_action(self, violation: Violation) -> None:
        pending = self._gather_notifications(violation)
        if not pending:
            return
        try:
            self.mailer.connect()
            for message in pending:
                self.mailer.send(message)
                for recipient in message.to:
                    self._mark_sent(recipient)
                log.info("violation email sent to %s", message.to)
        except Exception as exc:  # smtplib raises many types; never kill the tick
            log.error("sending violation emails failed: %s", exc)
        finally:
            self.mailer.disconnect()

    def _gather_notifications(self, violation: Violation):
        from ...db.models.user import User

        slots = {
            "intruder_username": violation.intruder_username,
            "pids": ", ".join(str(p) for p in violation.all_pids),
            "chips": ", ".join(violation.chip_uids),
            "owners": ", ".join(violation.owner_usernames) or "(unreserved)",
        }
        author = self.config.smtp_login or "tpuhive@localhost"
        messages = []
        if self.config.notify_intruder:
            intruder = User.find_by_username(violation.intruder_username)
            if intruder is not None and intruder.email and self._may_send(intruder.email):
                messages.append(Message(
                    author, [intruder.email],
                    "TPU reservation violation",
                    MessageBodyTemplater(INTRUDER_EMAIL_TEMPLATE).fill_in(slots),
                ))
            elif intruder is None:
                log.info("intruder %s has no account; cannot email them",
                         violation.intruder_username)
        if self.config.notify_admin and self.config.admin_email:
            if self._may_send(self.config.admin_email):
                messages.append(Message(
                    author, [self.config.admin_email],
                    f"TPU violation by {violation.intruder_username}",
                    MessageBodyTemplater(ADMIN_EMAIL_TEMPLATE).fill_in(slots),
                ))
        return messages
