"""PTY warning handler.

Reference: tensorhive/core/violation_handlers/MessageSendingBehaviour.py:10-89
— list the host's interactive sessions via ``who``, filter to the intruder's
TTYs, and write one warning onto each (merged into a single remote command).
"""
from __future__ import annotations

import logging
from typing import Optional

from ...utils.exceptions import TransportError
from ..nursery import OpsFactory, get_ops_factory
from .base import ProtectionHandler, Violation

log = logging.getLogger(__name__)

WARNING_TEMPLATE = (
    "[tpuhive] Your processes (PIDs: {pids}) violate a TPU reservation "
    "held by {owners} on chips {chips}. Please terminate them — they may "
    "be killed automatically."
)
UNRESERVED_TEMPLATE = (
    "[tpuhive] Your processes (PIDs: {pids}) occupy TPU chips {chips} "
    "without a reservation. Reserve the chips or stop the processes."
)


class MessageSendingBehaviour(ProtectionHandler):
    def __init__(self, ops_factory: Optional[OpsFactory] = None) -> None:
        self._factory = ops_factory

    @property
    def factory(self) -> OpsFactory:
        return self._factory or get_ops_factory()

    def get_warning_message(self, violation: Violation) -> str:
        template = UNRESERVED_TEMPLATE if violation.unreserved else WARNING_TEMPLATE
        return template.format(
            pids=", ".join(str(p) for p in violation.all_pids),
            owners=", ".join(violation.owner_usernames) or "another user",
            chips=", ".join(violation.chip_uids),
        )

    def trigger_action(self, violation: Violation) -> None:
        message = self.get_warning_message(violation)
        for hostname in violation.hostnames:
            try:
                ops = self.factory.ops_for(hostname)
                ttys = [
                    tty for user, tty in ops.pty_sessions()
                    if user == violation.intruder_username
                ]
                if ttys:
                    ops.write_to_ptys(ttys, message)
            except TransportError as exc:
                log.warning("could not warn %s on %s: %s",
                            violation.intruder_username, hostname, exc)
