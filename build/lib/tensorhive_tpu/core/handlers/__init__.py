"""Violation handlers (reference: tensorhive/core/violation_handlers/)."""
from .base import ProtectionHandler, Violation
from .email import EmailSendingBehaviour
from .kill import ProcessKillingBehaviour
from .message import MessageSendingBehaviour

__all__ = [
    "ProtectionHandler",
    "Violation",
    "MessageSendingBehaviour",
    "EmailSendingBehaviour",
    "ProcessKillingBehaviour",
]
