"""Queue-scheduling strategies.

Reference: tensorhive/core/scheduling.py:10-62 — ``Scheduler`` strategy
interface + ``GreedyScheduler``: take a queued job iff every chip its tasks
claim is free of upcoming reservations for at least
``schedule_queued_when_free_mins`` and not already taken by an earlier job
this round; skip a slot when the *owner's own* reservation is upcoming
(they'll use it themselves, GreedyScheduler.schedule_jobs:30-62).
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Set

from ..db.models.job import Job
from ..db.models.reservation import Reservation
from ..db.models.user import User
from ..utils.timeutils import minutes_between, utcnow

log = logging.getLogger(__name__)

#: per-job eligible-host resolver: returns the set of hostnames the job's
#: owner may launch on, or None for "unrestricted" (reference
#: get_hosts_with_gpus_eligible_for_jobs, JobSchedulingService.py:174-195)
EligibleHostsFn = Callable[[Job], Optional[Set[str]]]


class Scheduler:
    """Strategy: pick queued jobs to launch given per-chip free windows."""

    def schedule_jobs(
        self,
        queued_jobs: List[Job],
        required_free_minutes: float,
        at=None,
        eligible_hosts: Optional[EligibleHostsFn] = None,
    ) -> List[Job]:
        raise NotImplementedError


def chip_free_minutes(
    uid: str,
    horizon_mins: float,
    at=None,
    for_user_id: Optional[int] = None,
) -> float:
    """Minutes until the chip's next active/non-cancelled reservation, capped
    at ``horizon_mins`` (reference check_current_gpu_slots,
    JobSchedulingService.py:76-104). A chip under a *current* reservation has
    0 free minutes. Reservations owned by ``for_user_id`` don't count —
    a user's queued job may run inside their own reserved window (reference
    GreedyScheduler treats the owner's own upcoming reservation as free,
    scheduling.py:48-56)."""
    at = at or utcnow()
    current = Reservation.current_for_resource(uid, at=at)
    if current is not None and current.user_id != for_user_id:
        return 0.0
    candidates = [
        r for r in Reservation.upcoming_events_for_resource(uid, at=at)
        if r.user_id != for_user_id
    ]
    if not candidates:
        return horizon_mins
    return max(0.0, min(minutes_between(at, r.start) for r in candidates))


class GreedyScheduler(Scheduler):
    """First-come-first-served over the queue in enqueue order."""

    HORIZON_MINS = 24 * 60.0

    def schedule_jobs(
        self,
        queued_jobs: List[Job],
        required_free_minutes: float,
        at=None,
        eligible_hosts: Optional[EligibleHostsFn] = None,
    ) -> List[Job]:
        at = at or utcnow()
        taken: set = set()
        chosen: List[Job] = []
        for job in queued_jobs:
            if not self._hosts_eligible(job, eligible_hosts):
                continue
            uids = job.chip_uids
            if not uids:
                # no chip claims (CPU-only job): the host-eligibility gate
                # above is the whole check — reference launches chip-less
                # jobs only on eligible hosts too (JobSchedulingService.py
                # :174-195); without it a queued job on an unknown or
                # restricted host would bypass all gating
                chosen.append(job)
                continue
            ok = True
            for uid in uids:
                free = chip_free_minutes(
                    uid, self.HORIZON_MINS, at=at, for_user_id=job.user_id
                )
                if uid in taken or free < required_free_minutes:
                    ok = False
                    break
            if ok:
                taken.update(uids)
                chosen.append(job)
        return chosen

    @staticmethod
    def _hosts_eligible(job: Job, eligible_hosts: Optional[EligibleHostsFn]) -> bool:
        """Every task hostname must be eligible for the job's owner."""
        if eligible_hosts is None:
            return True
        hosts = eligible_hosts(job)
        if hosts is None:  # unrestricted user
            return True
        missing = {task.hostname for task in job.tasks} - hosts
        if missing:
            log.debug("job %d skipped: hosts %s not eligible", job.id, sorted(missing))
        return not missing
