"""Service base class.

Reference: tensorhive/core/services/Service.py (16 LoC) + StoppableThread —
a thread with an abstract ``inject`` hook through which ServiceManager pushes
the shared managers (ServiceManager.py:configure_all_services). Here the
injection is explicit and typed, and every service gets uniform tick timing:
the reference hand-rolled per-loop perf_counter bookkeeping in each service
(MonitoringService.py:38-54, ProtectionService.py:81) — that bookkeeping is
the *only* profiling the reference has (SURVEY.md §5 Tracing), so it is kept
and centralized, feeding the poll-latency metric BASELINE.md asks for.
"""
from __future__ import annotations

import collections
import logging
import statistics
import time
from typing import TYPE_CHECKING, Deque, Optional

from ...utils.threading import StoppableThread

if TYPE_CHECKING:
    from ..managers.infrastructure import InfrastructureManager
    from ..transport.base import TransportManager

log = logging.getLogger(__name__)


class Service(StoppableThread):
    """Periodic daemon thread: ``do_run()`` every ``interval_s`` seconds.

    Subclasses implement :meth:`do_run`; the run loop measures each tick and
    sleeps out the interval remainder (interruptible by shutdown).
    """

    def __init__(self, interval_s: float, name: Optional[str] = None) -> None:
        super().__init__(name=name or type(self).__name__)
        self.interval_s = interval_s
        self.infrastructure_manager: Optional["InfrastructureManager"] = None
        self.transport_manager: Optional["TransportManager"] = None
        #: rolling window of tick durations (seconds) for latency stats
        self.tick_durations: Deque[float] = collections.deque(maxlen=256)
        self.ticks_completed = 0

    def inject(self, infrastructure_manager: "InfrastructureManager",
               transport_manager: "TransportManager") -> None:
        """Receive shared managers (reference Service.inject)."""
        self.infrastructure_manager = infrastructure_manager
        self.transport_manager = transport_manager

    # -- loop ---------------------------------------------------------------
    def run(self) -> None:
        while not self.stopped:
            started = time.perf_counter()
            try:
                self.do_run()
            except Exception:
                # a crashing tick must not kill the daemon thread (the
                # reference would die silently here — its threads have no
                # guard and a monitor exception stops all monitoring)
                log.exception("%s tick failed", self.name)
            elapsed = time.perf_counter() - started
            self.tick_durations.append(elapsed)
            self.ticks_completed += 1
            remaining = self.interval_s - elapsed
            if remaining > 0:
                self.wait(remaining)
            else:
                log.debug("%s tick overran interval: %.3fs > %.3fs",
                          self.name, elapsed, self.interval_s)

    def do_run(self) -> None:
        raise NotImplementedError

    # -- introspection ------------------------------------------------------
    def tick_latency_p50(self) -> Optional[float]:
        if not self.tick_durations:
            return None
        return statistics.median(self.tick_durations)
