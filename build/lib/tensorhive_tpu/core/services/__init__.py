"""Daemon services (reference: tensorhive/core/services/)."""
from .base import Service
from .monitoring import MonitoringService

__all__ = ["Service", "MonitoringService"]
