"""Core runtime managers (reference: tensorhive/core/managers/)."""
from .infrastructure import InfrastructureManager

__all__ = ["InfrastructureManager"]
