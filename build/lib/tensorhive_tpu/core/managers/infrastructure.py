"""In-memory latest-telemetry store shared across services and the API.

Reference: tensorhive/core/managers/InfrastructureManager.py:8-78 — a plain
dict ``{host: {'GPU': {uuid: {...}}, 'CPU': {...}}}`` written by the monitor
thread and read by the API/protection/scheduler threads *without locks*,
relying on ``deepcopy`` on the read path (controllers/nodes.py:15). SURVEY.md
§7 flags that implicit contract as a thing to re-implement deliberately: here
every access goes through an RW lock and readers get deep copies, so torn
reads are impossible by construction rather than by CPython luck.

Node shape (TPU-flavored)::

    {host: {"TPU": {chip_uid: {"uid", "index", "hostname",
                               "accelerator_type", "hbm_used_mib",
                               "hbm_total_mib", "hbm_util_pct",
                               "duty_cycle_pct", "processes": [
                                   {"pid", "user", "command"}]}},
            "CPU": {f"CPU_{host}": {"util_pct", "mem_total_mib",
                                     "mem_used_mib", "mem_util_pct"}}}}

Chip UIDs are ``{hostname}:tpu:{index}`` — globally unique and stable across
reboots, playing the role the 40-char GPU UUID plays in the reference
(models/Reservation.py:54 asserts on it; here Resource rows store this uid).
"""
from __future__ import annotations

import copy
from typing import Dict, List, Optional

from ...utils.threading import RWLock

#: executable basenames never treated as foreign/intruding (reference
#: InfrastructureManager.ignored_processes: Xorg and friends; the TPU
#: equivalents are the platform daemons that idle-hold devices). Matching is
#: on the exact basename of argv[0] — substring matching over the command
#: line would let any user process exempt itself from intruder detection by
#: putting an ignored name in its arguments.
DEFAULT_IGNORED_PROCESSES = (
    "tpu-runtime",
    "tpuhive-probe",
)


def chip_uid(hostname: str, index: int) -> str:
    return f"{hostname}:tpu:{index}"


class InfrastructureManager:
    """Thread-safe latest-metrics store; monitors replace whole per-host
    subtrees, readers receive snapshots."""

    def __init__(self, hostnames: Optional[List[str]] = None) -> None:
        self._lock = RWLock()
        self._infra: Dict[str, Dict] = {name: {} for name in (hostnames or [])}
        self.ignored_processes: List[str] = list(DEFAULT_IGNORED_PROCESSES)

    # -- write path (monitors) ---------------------------------------------
    def update_subtree(self, hostname: str, key: str, subtree: Dict) -> None:
        """Atomically replace one monitor's subtree for one host (reference
        monitors assign whole ``['GPU']`` dicts, GPUMonitor.py:92)."""
        with self._lock.write():
            self._infra.setdefault(hostname, {})[key] = subtree

    def mark_unreachable(self, hostname: str, key: str) -> None:
        """Drop a host's subtree when it stops responding so stale telemetry
        is never mistaken for live (the reference leaves the last values in
        place indefinitely — a known sharp edge)."""
        with self._lock.write():
            node = self._infra.get(hostname)
            if node is not None:
                node.pop(key, None)

    # -- read path ----------------------------------------------------------
    @property
    def infrastructure(self) -> Dict[str, Dict]:
        """Deep-copied snapshot of everything."""
        with self._lock.read():
            return copy.deepcopy(self._infra)

    def node(self, hostname: str) -> Dict:
        with self._lock.read():
            return copy.deepcopy(self._infra.get(hostname, {}))

    @property
    def hostnames(self) -> List[str]:
        with self._lock.read():
            return list(self._infra)

    # -- process queries (reference InfrastructureManager.py:34-78) ---------
    def node_tpu_processes(self, hostname: str) -> Dict[str, List[Dict]]:
        """``{chip_uid: [process, ...]}`` for one host, ignored processes
        filtered out (reference node_gpu_processes)."""
        with self._lock.read():
            chips = self._infra.get(hostname, {}).get("TPU", {})
            result: Dict[str, List[Dict]] = {}
            for uid, chip in chips.items():
                procs = [
                    copy.deepcopy(p)
                    for p in chip.get("processes", [])
                    if not self._ignored(p.get("command", ""))
                ]
                result[uid] = procs
            return result

    def all_nodes_with_tpu_processes(self) -> Dict[str, Dict[str, List[Dict]]]:
        """Reference InfrastructureManager.all_nodes_with_gpu_processes:63."""
        return {host: self.node_tpu_processes(host) for host in self.hostnames}

    def find_chip(self, uid: str) -> Optional[Dict]:
        """Locate a chip's metrics dict by uid across all hosts."""
        with self._lock.read():
            for node in self._infra.values():
                chip = node.get("TPU", {}).get(uid)
                if chip is not None:
                    return copy.deepcopy(chip)
        return None

    def find_chip_hostname(self, uid: str) -> Optional[str]:
        """Reference InfrastructureManager.get_gpu_uid inverse lookup."""
        with self._lock.read():
            for hostname, node in self._infra.items():
                if uid in node.get("TPU", {}):
                    return hostname
        return None

    def _ignored(self, command: str) -> bool:
        argv0 = command.split()[0] if command.split() else ""
        basename = argv0.rsplit("/", 1)[-1]
        return basename in self.ignored_processes
