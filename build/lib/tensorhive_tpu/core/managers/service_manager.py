"""ServiceManager: owns the daemon service threads.

Reference: tensorhive/core/managers/ServiceManager.py (29 LoC) — holds the
services, injects the shared managers into each, starts/stops all.
"""
from __future__ import annotations

import logging
from typing import TYPE_CHECKING, List, Optional

if TYPE_CHECKING:
    from ..services.base import Service
    from ..transport.base import TransportManager
    from .infrastructure import InfrastructureManager

log = logging.getLogger(__name__)


class ServiceManager:
    def __init__(
        self,
        services: List["Service"],
        infrastructure_manager: "InfrastructureManager",
        transport_manager: "TransportManager",
    ) -> None:
        self.services = services
        self.infrastructure_manager = infrastructure_manager
        self.transport_manager = transport_manager

    def configure_all_services(self) -> None:
        for service in self.services:
            service.inject(self.infrastructure_manager, self.transport_manager)

    def start_all_services(self) -> None:
        for service in self.services:
            log.info("starting %s (interval %.1fs)", service.name, service.interval_s)
            service.start()

    def shutdown_all_services(self, join_timeout_s: float = 5.0) -> None:
        for service in self.services:
            service.shutdown()
        for service in self.services:
            service.join(timeout=join_timeout_s)
            if service.is_alive():
                log.warning("%s did not stop within %.1fs", service.name, join_timeout_s)

    def service(self, cls: type) -> Optional["Service"]:
        for service in self.services:
            if isinstance(service, cls):
                return service
        return None
