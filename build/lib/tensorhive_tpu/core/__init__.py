"""Core runtime: transports, process nursery, telemetry, daemon services
(reference: tensorhive/core/)."""
