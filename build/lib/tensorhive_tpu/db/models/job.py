"""Jobs: containers of distributed tasks (reference: tensorhive/models/Job.py:24-158).

A job owns an ordered set of :class:`~.task.Task` rows — one process per
host/worker of a distributed training run. Status is derived from task
statuses (``synchronize_status``, reference Job.py:81-99). Jobs can be
scheduled for timed start/stop (``start_at``/``stop_at``) or placed in the
queue, from which :class:`JobSchedulingService` launches them when their
chips are free of reservations (reference Job.py:101-157).
"""
from __future__ import annotations

import enum
from datetime import datetime
from typing import Any, Dict, List, Optional

from ...utils.exceptions import ValidationError
from ...utils.timeutils import iso_utc, utcnow
from ..orm import Column, Model


class JobStatus(str, enum.Enum):
    """Reference: models/Job.py:16-22 status enum."""

    not_running = "not_running"
    running = "running"
    pending = "pending"
    terminated = "terminated"
    unsynchronized = "unsynchronized"


class Job(Model):
    __tablename__ = "jobs"
    __public__ = ("id", "name", "description", "user_id", "status", "start_at", "stop_at", "is_queued")

    id = Column(int, primary_key=True)
    name = Column(str, nullable=False)
    description = Column(str, default="")
    user_id = Column(int, nullable=False, foreign_key="users(id)", index=True)
    _status = Column(str, default=JobStatus.not_running.value)
    start_at = Column(datetime)      # timed start (reference _start_at)
    stop_at = Column(datetime)       # timed stop (reference _stop_at)
    is_queued = Column(bool, default=False)
    queued_at = Column(datetime)

    def check_assertions(self) -> None:
        if not self.name:
            raise ValidationError("job name must not be empty")
        if self._status not in JobStatus.__members__:
            raise ValidationError(f"invalid job status {self._status!r}")
        if self.start_at and self.stop_at and self.stop_at <= self.start_at:
            raise ValidationError("job stop_at must be after start_at")

    # -- status ------------------------------------------------------------
    @property
    def status(self) -> JobStatus:
        return JobStatus(self._status)

    @status.setter
    def status(self, value) -> None:
        self._status = JobStatus(value).value

    def synchronize_status(self) -> None:
        """Derive job status from its tasks (reference Job.py:81-99): any
        task running → running; any unsynchronized → unsynchronized; all
        terminated → terminated; otherwise not_running."""
        statuses = {t.status for t in self.tasks}
        from .task import TaskStatus

        if TaskStatus.running in statuses:
            self.status = JobStatus.running
        elif TaskStatus.unsynchronized in statuses:
            self.status = JobStatus.unsynchronized
        elif statuses and statuses <= {TaskStatus.terminated}:
            self.status = JobStatus.terminated
        else:
            self.status = JobStatus.not_running
        self.save()

    # -- tasks -------------------------------------------------------------
    @property
    def tasks(self) -> List:
        from .task import Task

        return Task.filter_by(job_id=self.id)

    @property
    def hostnames(self) -> List[str]:
        seen: List[str] = []
        for task in self.tasks:
            if task.hostname not in seen:
                seen.append(task.hostname)
        return seen

    @property
    def chip_uids(self) -> List[str]:
        """All chips this job's tasks claim (for reservation checks)."""
        uids: List[str] = []
        for task in self.tasks:
            uids.extend(task.chip_uids)
        return uids

    # -- queue (reference Job.py:101-157) ----------------------------------
    def enqueue(self) -> None:
        if self.status == JobStatus.running:
            raise ValidationError("cannot enqueue a running job")
        self.is_queued = True
        self.queued_at = utcnow()
        self.status = JobStatus.pending
        self.save()

    def dequeue(self) -> None:
        self.is_queued = False
        self.queued_at = None
        if self.status == JobStatus.pending:
            self.status = JobStatus.not_running
        self.save()

    @classmethod
    def get_job_queue(cls) -> List["Job"]:
        """Queued jobs awaiting execution, FIFO (reference Job.py:153)."""
        jobs = cls.where("is_queued = 1 AND _status = ?", [JobStatus.pending.value])
        jobs.sort(key=lambda j: (j.queued_at or utcnow(), j.id))
        return jobs

    @classmethod
    def get_jobs_running_from_queue(cls) -> List["Job"]:
        """Running jobs that were started by the queue scheduler
        (reference Job.py:157) — candidates for preemption."""
        return cls.where("is_queued = 1 AND _status = ?", [JobStatus.running.value])

    @classmethod
    def find_scheduled_to_start(cls, at: Optional[datetime] = None) -> List["Job"]:
        """Timed jobs due to start — and not already past their stop time
        (reference can_execute_now requires start_at < now < stop_at,
        JobSchedulingService.py:54-61); an expired window must not trigger a
        late spawn/kill cycle after downtime."""
        at = at or utcnow()
        return cls.where(
            "start_at IS NOT NULL AND start_at <= ? "
            "AND (stop_at IS NULL OR stop_at > ?) AND _status IN (?, ?)",
            [iso_utc(at), iso_utc(at),
             JobStatus.not_running.value, JobStatus.pending.value],
        )

    @classmethod
    def find_scheduled_to_stop(cls, at: Optional[datetime] = None) -> List["Job"]:
        at = at or utcnow()
        return cls.where(
            "stop_at IS NOT NULL AND stop_at <= ? AND _status = ?",
            [iso_utc(at), JobStatus.running.value],
        )

    def as_dict(self, include_private: bool = False) -> Dict[str, Any]:
        out = super().as_dict(include_private)
        out["status"] = self.status.value
        out["tasks"] = [t.as_dict() for t in self.tasks]
        return out
