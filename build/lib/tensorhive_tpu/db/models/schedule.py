"""Weekly time-window schedules attached to restrictions
(reference: tensorhive/models/RestrictionSchedule.py:16-107).

``schedule_days`` is the reference's weekday-mask string: characters '1'-'7'
(Mon..Sun, ISO weekday numbers). ``hour_start``/``hour_end`` bound the active
window within each listed day; windows with hour_end <= hour_start are
rejected (the reference stores times as TIME columns with the same rule).
"""
from __future__ import annotations

from datetime import datetime, time
from typing import Any, Dict, List, Optional, Set

from ...utils.exceptions import ValidationError
from ...utils.timeutils import utcnow
from ..orm import Column, Model

_VALID_DAYS = set("1234567")


class RestrictionSchedule(Model):
    __tablename__ = "restriction_schedules"
    __public__ = ("id", "schedule_days", "hour_start", "hour_end")

    id = Column(int, primary_key=True)
    schedule_days = Column(str, nullable=False)  # e.g. "12345"
    hour_start = Column(str, nullable=False)     # "HH:MM"
    hour_end = Column(str, nullable=False)       # "HH:MM"

    def check_assertions(self) -> None:
        days = set(self.schedule_days or "")
        if not days or not days <= _VALID_DAYS:
            raise ValidationError(
                f"schedule_days must be a non-empty subset of '1234567', got {self.schedule_days!r}"
            )
        start, end = self.parsed_hour_start, self.parsed_hour_end
        if end <= start:
            raise ValidationError("hour_end must be after hour_start")

    # -- parsing (reference RestrictionSchedule.py:95-101) -----------------
    @staticmethod
    def _parse_hour(value: str) -> time:
        try:
            hours, minutes = value.split(":")
            return time(int(hours), int(minutes))
        except (ValueError, AttributeError) as exc:
            raise ValidationError(f"invalid HH:MM time: {value!r}") from exc

    @property
    def parsed_hour_start(self) -> time:
        return self._parse_hour(self.hour_start)

    @property
    def parsed_hour_end(self) -> time:
        return self._parse_hour(self.hour_end)

    @property
    def days(self) -> Set[int]:
        return {int(c) for c in self.schedule_days}

    # -- activity (reference RestrictionSchedule.py:77-81) -----------------
    def is_active(self, at: Optional[datetime] = None) -> bool:
        at = at or utcnow()
        if at.isoweekday() not in self.days:
            return False
        return self.parsed_hour_start <= at.time() < self.parsed_hour_end

    # -- linked restrictions ----------------------------------------------
    @property
    def restrictions(self) -> List:
        from .restriction import Restriction, Restriction2Schedule

        links = Restriction2Schedule.filter_by(schedule_id=self.id)
        return [Restriction.get(link.restriction_id) for link in links]

    def as_dict(self, include_private: bool = False) -> Dict[str, Any]:
        return super().as_dict(include_private)
