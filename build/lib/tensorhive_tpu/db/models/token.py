"""JWT revocation list (reference: tensorhive/models/RevokedToken.py:10-26)."""
from __future__ import annotations

from datetime import datetime
from typing import Any

from ...utils.timeutils import utcnow
from ..orm import Column, Model


class RevokedToken(Model):
    __tablename__ = "revoked_tokens"

    id = Column(int, primary_key=True)
    jti = Column(str, nullable=False, unique=True)
    revoked_at = Column(datetime)

    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("revoked_at", utcnow())
        super().__init__(**kwargs)

    @classmethod
    def is_jti_blacklisted(cls, jti: str) -> bool:
        return bool(cls.filter_by(jti=jti))

    @classmethod
    def add(cls, jti: str) -> None:
        with cls.atomically():
            if not cls.is_jti_blacklisted(jti):
                cls(jti=jti).save()
