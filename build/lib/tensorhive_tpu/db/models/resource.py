"""Physical accelerator chips (reference: tensorhive/models/Resource.py:8-61).

A Resource row is one TPU chip, keyed by a stable chip UID
(``<hostname>:tpu:<index>`` as emitted by the telemetry layer — the analog of
the reference's 40-char GPU UUID). TPU-specific additions: slice metadata so
the scheduler can reason about whole-slice reservations (SURVEY.md §7 risk
"chip vs slice granularity": a v5e-16 slice = 4 VMs x 4 chips; the reference
only ever matched single UUIDs).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ...utils.exceptions import ValidationError
from ..orm import Column, Model


class Resource(Model):
    __tablename__ = "resources"
    __public__ = ("id", "uid", "name", "hostname", "accelerator_type", "slice_name", "chip_index")

    id = Column(int, primary_key=True)
    uid = Column(str, nullable=False, unique=True)
    name = Column(str)            # display name, e.g. "TPU v5e chip 0"
    hostname = Column(str, index=True)
    accelerator_type = Column(str, default="")   # "v5litepod-16", "" for CPU hosts
    slice_name = Column(str, default="", index=True)
    chip_index = Column(int, default=0)

    MAX_UID_LEN = 64

    def check_assertions(self) -> None:
        if not self.uid or len(self.uid) > self.MAX_UID_LEN:
            raise ValidationError(
                f"resource uid must be 1..{self.MAX_UID_LEN} chars, got {self.uid!r}"
            )

    # -- lookups (reference Resource.py:56-61) -----------------------------
    @classmethod
    def get_by_uid(cls, uid: str) -> Optional["Resource"]:
        return cls.first_by(uid=uid)

    @classmethod
    def get_by_name(cls, name: str) -> List["Resource"]:
        return cls.filter_by(name=name)

    @classmethod
    def get_by_hostname(cls, hostname: str) -> List["Resource"]:
        return cls.filter_by(hostname=hostname)

    @classmethod
    def get_by_slice(cls, slice_name: str) -> List["Resource"]:
        members = cls.filter_by(slice_name=slice_name)
        members.sort(key=lambda r: (r.hostname, r.chip_index))
        return members

    # -- restrictions (reference Resource.py:29-41, incl. global) ----------
    def get_restrictions(self, include_global: bool = True):
        from .restriction import Restriction

        restrictions = Restriction.for_resource(self.id)
        if include_global:
            seen = {r.id for r in restrictions}
            restrictions += [
                r for r in Restriction.get_global_restrictions() if r.id not in seen
            ]
        return restrictions

    def as_dict(self, include_private: bool = False) -> Dict[str, Any]:
        return super().as_dict(include_private)
