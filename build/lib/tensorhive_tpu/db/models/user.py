"""Users, roles, groups (reference: tensorhive/models/{User,Role,Group}.py).

Password hashing uses stdlib ``hashlib.pbkdf2_hmac`` (sha256, 29000 rounds,
random salt) — functionally equivalent to the reference's passlib
``pbkdf2_sha256`` (models/User.py:92-96) without the passlib dependency.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import os
import re
from datetime import datetime
from typing import Any, Dict, List, Optional

from ...utils.exceptions import ValidationError
from ...utils.timeutils import utcnow
from ..orm import Column, Model

_PBKDF2_ROUNDS = 29000
_EMAIL_RE = re.compile(r"^[^@\s]+@[^@\s]+\.[^@\s]+$")


def hash_password(plain: str) -> str:
    salt = os.urandom(16)
    digest = hashlib.pbkdf2_hmac("sha256", plain.encode(), salt, _PBKDF2_ROUNDS)
    return "pbkdf2-sha256$%d$%s$%s" % (
        _PBKDF2_ROUNDS,
        base64.b64encode(salt).decode(),
        base64.b64encode(digest).decode(),
    )


def verify_password(plain: str, hashed: str) -> bool:
    try:
        _scheme, rounds, salt_b64, digest_b64 = hashed.split("$")
        digest = hashlib.pbkdf2_hmac(
            "sha256", plain.encode(), base64.b64decode(salt_b64), int(rounds)
        )
        return hmac.compare_digest(digest, base64.b64decode(digest_b64))
    except (ValueError, TypeError):
        return False


class User(Model):
    """Reference: tensorhive/models/User.py:31-186."""

    __tablename__ = "users"
    __public__ = ("id", "username", "email", "created_at", "last_login_at")

    id = Column(int, primary_key=True)
    username = Column(str, nullable=False, unique=True)
    email = Column(str, nullable=False)
    _hashed_password = Column(str, nullable=False)
    created_at = Column(datetime)
    # schema v2 (db/migrations.py): stamped on successful login, surfaced in
    # the users admin view
    last_login_at = Column(datetime)

    MIN_USERNAME_LEN = 3
    MIN_PASSWORD_LEN = 8

    def __init__(self, password: Optional[str] = None, **kwargs: Any) -> None:
        kwargs.setdefault("created_at", utcnow())
        super().__init__(**kwargs)
        if password is not None:
            self.password = password

    # -- per-field validators (reference User.py:98-108; used by the
    # interactive AccountCreator to re-prompt on a single bad field) -------
    @classmethod
    def validate_username_format(cls, username: str) -> None:
        if not username or len(username) < cls.MIN_USERNAME_LEN:
            raise ValidationError(
                f"username must have at least {cls.MIN_USERNAME_LEN} characters"
            )

    @classmethod
    def validate_username(cls, username: str) -> None:
        """Format + uniqueness (for NEW accounts; re-saving an existing row
        must use validate_username_format to avoid self-collision)."""
        cls.validate_username_format(username)
        if cls.find_by_username(username) is not None:
            raise ValidationError(f"username {username!r} is already taken")

    @classmethod
    def validate_email(cls, email: str) -> None:
        if not email or not _EMAIL_RE.match(email):
            raise ValidationError(f"invalid email: {email!r}")

    @classmethod
    def validate_password(cls, password: str) -> None:
        if len(password or "") < cls.MIN_PASSWORD_LEN:
            raise ValidationError(
                f"password must have at least {cls.MIN_PASSWORD_LEN} characters"
            )

    # -- validation (reference User.py:98-108 validators) ------------------
    def check_assertions(self) -> None:
        # uniqueness is NOT re-checked here (validate_username does): an
        # existing row re-saving itself would collide with its own username
        self.validate_username_format(self.username)
        self.validate_email(self.email)
        if not self._hashed_password:
            raise ValidationError("password must be set")

    # -- password ----------------------------------------------------------
    @property
    def password(self) -> str:
        raise AttributeError("password is write-only")

    @password.setter
    def password(self, plain: str) -> None:
        if len(plain) < self.MIN_PASSWORD_LEN:
            raise ValidationError(
                f"password must have at least {self.MIN_PASSWORD_LEN} characters"
            )
        self._hashed_password = hash_password(plain)

    def check_password(self, plain: str) -> bool:
        return verify_password(plain, self._hashed_password)

    # -- lookups -----------------------------------------------------------
    @classmethod
    def find_by_username(cls, username: str) -> Optional["User"]:
        return cls.first_by(username=username)

    # -- roles (reference models/Role.py per-user rows) --------------------
    @property
    def roles(self) -> List[str]:
        return [r.name for r in Role.filter_by(user_id=self.id)]

    def has_role(self, name: str) -> bool:
        return name in self.roles

    def add_role(self, name: str) -> None:
        with Role.atomically():
            if not self.has_role(name):
                Role(name=name, user_id=self.id).save()

    def remove_role(self, name: str) -> None:
        for role in Role.filter_by(user_id=self.id, name=name):
            role.destroy()

    # -- groups ------------------------------------------------------------
    @property
    def groups(self) -> List["Group"]:
        links = User2Group.filter_by(user_id=self.id)
        return Group.get_many([link.group_id for link in links])

    # -- restrictions (reference User.py:149-164) --------------------------
    def get_restrictions(self, include_group: bool = True, include_global: bool = True):
        from .restriction import Restriction

        restrictions = Restriction.for_user(self.id)
        seen = {r.id for r in restrictions}
        if include_group:
            for group in self.groups:
                for r in Restriction.for_group(group.id):
                    if r.id not in seen:
                        seen.add(r.id)
                        restrictions.append(r)
        if include_global:
            for r in Restriction.get_global_restrictions():
                if r.id not in seen:
                    seen.add(r.id)
                    restrictions.append(r)
        return restrictions

    def get_active_restrictions(self):
        return [r for r in self.get_restrictions() if r.is_active()]

    def allowed_resource_uids(self) -> Optional[set]:
        """UIDs this user may currently use; None means unrestricted (some
        active restriction is global, i.e. applies to all resources)."""
        uids: set = set()
        for restriction in self.get_active_restrictions():
            if restriction.is_global:
                return None
            uids.update(res.uid for res in restriction.resources)
        return uids

    def filter_infrastructure_by_user_restrictions(
        self, infrastructure: Dict[str, Dict]
    ) -> Dict[str, Dict]:
        """Prune an infrastructure dict to accelerators this user may access
        (reference: User.py:166-186). CPU metrics are always visible."""
        allowed = self.allowed_resource_uids()
        if allowed is None:
            return infrastructure
        filtered: Dict[str, Dict] = {}
        for hostname, node in infrastructure.items():
            kept = dict(node)
            devices = node.get("TPU", {})
            kept["TPU"] = {uid: m for uid, m in devices.items() if uid in allowed}
            filtered[hostname] = kept
        return filtered

    def as_dict(self, include_private: bool = False) -> Dict[str, Any]:
        out = super().as_dict(include_private)
        out["roles"] = self.roles
        return out


class Role(Model):
    """Reference: tensorhive/models/Role.py (rows 'user'/'admin' per user)."""

    __tablename__ = "roles"
    __table_constraints__ = ("UNIQUE(user_id, name)",)

    id = Column(int, primary_key=True)
    name = Column(str, nullable=False)
    user_id = Column(int, nullable=False, foreign_key="users(id)", index=True)

    VALID = ("user", "admin")

    def check_assertions(self) -> None:
        if self.name not in self.VALID:
            raise ValidationError(f"invalid role {self.name!r}; valid: {self.VALID}")


class Group(Model):
    """Reference: tensorhive/models/Group.py:16-87; ``is_default`` groups
    auto-attach newly created users (Group.py:77)."""

    __tablename__ = "groups"
    __public__ = ("id", "name", "is_default", "created_at")

    id = Column(int, primary_key=True)
    name = Column(str, nullable=False, unique=True)
    is_default = Column(bool, default=False)
    created_at = Column(datetime)

    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("created_at", utcnow())
        super().__init__(**kwargs)

    def check_assertions(self) -> None:
        if not self.name:
            raise ValidationError("group name must not be empty")

    @property
    def users(self) -> List[User]:
        return User.get_many(
            [link.user_id for link in User2Group.filter_by(group_id=self.id)]
        )

    def add_user(self, user: User) -> None:
        with User2Group.atomically():
            if not User2Group.filter_by(group_id=self.id, user_id=user.id):
                User2Group(group_id=self.id, user_id=user.id).save()

    def remove_user(self, user: User) -> None:
        for link in User2Group.filter_by(group_id=self.id, user_id=user.id):
            link.destroy()

    @classmethod
    def get_default_groups(cls) -> List["Group"]:
        return cls.filter_by(is_default=True)

    def as_dict(self, include_private: bool = False) -> Dict[str, Any]:
        out = super().as_dict(include_private)
        out["users"] = [u.as_dict() for u in self.users]
        return out


class User2Group(Model):
    """Reference: tensorhive/models/Group.py:84 (link table)."""

    __tablename__ = "user2group"
    __table_constraints__ = ("UNIQUE(user_id, group_id)",)

    id = Column(int, primary_key=True)
    user_id = Column(int, nullable=False, foreign_key="users(id)", index=True)
    group_id = Column(int, nullable=False, foreign_key="groups(id)", index=True)
