"""Reservation calendar events (reference: tensorhive/models/Reservation.py:14-168).

One reservation grants a user exclusive use of one chip (Resource uid) for a
UTC time window. Invariants enforced at save time mirror the reference's
(Reservation.py:38-56): duration within [30 min, 8 days], end after start,
and no overlap with other non-cancelled reservations for the same resource
(``would_interfere``, Reservation.py:120-131). Usage-average columns are the
TPU analogs of the reference's ``gpu_util_avg``/``mem_util_avg``: duty-cycle
(MXU activity) and HBM utilization, filled by the usage-logging service.
"""
from __future__ import annotations

from datetime import datetime, timedelta
from typing import Any, Dict, Iterable, List, Optional

from ...utils.exceptions import ConflictError, ValidationError
from ...utils.timeutils import iso_utc, utcnow
from ..orm import Column, Model


class Reservation(Model):
    __tablename__ = "reservations"
    __public__ = (
        "id", "title", "description", "resource_id", "user_id",
        "start", "end", "is_cancelled", "duty_cycle_avg", "hbm_util_avg",
    )

    id = Column(int, primary_key=True)
    title = Column(str, nullable=False)
    description = Column(str, default="")
    resource_id = Column(str, nullable=False, index=True)  # Resource.uid
    user_id = Column(int, nullable=False, foreign_key="users(id)", index=True)
    start = Column(datetime, nullable=False, index=True)
    end = Column(datetime, nullable=False, index=True)
    is_cancelled = Column(bool, default=False)
    created_at = Column(datetime)
    duty_cycle_avg = Column(float)
    hbm_util_avg = Column(float)

    MIN_DURATION = timedelta(minutes=30)
    MAX_DURATION = timedelta(days=8)
    MAX_RESOURCE_ID_LEN = 64

    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("created_at", utcnow())
        super().__init__(**kwargs)

    # -- validation (reference Reservation.py:38-56) -----------------------
    def check_assertions(self) -> None:
        if not self.title:
            raise ValidationError("reservation title must not be empty")
        if not self.resource_id or len(self.resource_id) > self.MAX_RESOURCE_ID_LEN:
            raise ValidationError(f"invalid resource_id: {self.resource_id!r}")
        if self.start is None or self.end is None:
            raise ValidationError("start and end are required")
        if self.end <= self.start:
            raise ValidationError("reservation end must be after start")
        duration = self.end - self.start
        if duration < self.MIN_DURATION:
            raise ValidationError(
                f"reservation must last at least {self.MIN_DURATION}"
            )
        if duration > self.MAX_DURATION:
            raise ValidationError(f"reservation must not exceed {self.MAX_DURATION}")
        if self.would_interfere():
            raise ConflictError(
                "reservation would overlap an existing reservation for "
                f"resource {self.resource_id}"
            )

    # -- overlap (reference Reservation.py:120-131) ------------------------
    def would_interfere(self) -> bool:
        clauses = "resource_id = ? AND is_cancelled = 0 AND start < ? AND end > ?"
        params: List[Any] = [self.resource_id, iso_utc(self.end), iso_utc(self.start)]
        if self.id is not None:
            clauses += " AND id != ?"
            params.append(self.id)
        return bool(Reservation.where(clauses, params))

    # -- time-window queries (reference Reservation.py:90-133) -------------
    @classmethod
    def current_events(cls, at: Optional[datetime] = None) -> List["Reservation"]:
        at = at or utcnow()
        iso = iso_utc(at)
        return cls.where("is_cancelled = 0 AND start <= ? AND end > ?", [iso, iso])

    @classmethod
    def current_for_resource(cls, resource_id: str, at: Optional[datetime] = None) -> Optional["Reservation"]:
        at = at or utcnow()
        iso = iso_utc(at)
        rows = cls.where(
            "is_cancelled = 0 AND resource_id = ? AND start <= ? AND end > ?",
            [resource_id, iso, iso],
        )
        return rows[0] if rows else None

    @classmethod
    def upcoming_events_for_resource(
        cls, resource_id: str, at: Optional[datetime] = None
    ) -> List["Reservation"]:
        """Active-or-future events, soonest first (Reservation.py:107)."""
        at = at or utcnow()
        rows = cls.where(
            "is_cancelled = 0 AND resource_id = ? AND end > ?",
            [resource_id, iso_utc(at)],
        )
        rows.sort(key=lambda r: r.start)
        return rows

    @classmethod
    def filter_by_uids_and_time_range(
        cls,
        uids: Optional[Iterable[str]] = None,
        start: Optional[datetime] = None,
        end: Optional[datetime] = None,
    ) -> List["Reservation"]:
        """Calendar read path (reference Reservation.py:133). Each filter is
        optional: uids only, time range only, or both."""
        clauses: List[str] = []
        params: List[Any] = []
        if uids is not None:
            uids = list(uids)
            if not uids:
                return []
            clauses.append(f"resource_id IN ({', '.join('?' * len(uids))})")
            params.extend(uids)
        if end is not None:
            clauses.append("start < ?")
            params.append(iso_utc(end))
        if start is not None:
            clauses.append("end > ?")
            params.append(iso_utc(start))
        if not clauses:
            return cls.all()
        return cls.where(" AND ".join(clauses), params)

    def is_active(self, at: Optional[datetime] = None) -> bool:
        at = at or utcnow()
        return not self.is_cancelled and self.start <= at < self.end

    def as_dict(self, include_private: bool = False) -> Dict[str, Any]:
        return super().as_dict(include_private)
