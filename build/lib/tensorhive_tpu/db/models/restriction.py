"""Access restrictions (reference: tensorhive/models/Restriction.py:20-238).

A restriction is a *permission grant*: "these users/groups may use these
resources between ``starts_at`` and ``ends_at`` (None = forever), optionally
only within attached weekly schedules". ``is_global`` restrictions apply to
every resource (Restriction.py:187 get_global_restrictions). A user with no
active restriction covering a chip cannot reserve it — enforced by
:class:`~tensorhive_tpu.core.verifier.ReservationVerifier`.
"""
from __future__ import annotations

from datetime import datetime
from typing import Any, Dict, List, Optional

from ...utils.exceptions import ValidationError
from ...utils.timeutils import utcnow
from ..orm import Column, Model


class Restriction(Model):
    __tablename__ = "restrictions"
    __public__ = ("id", "name", "starts_at", "ends_at", "is_global", "created_at")

    id = Column(int, primary_key=True)
    name = Column(str, default="")
    starts_at = Column(datetime, nullable=False)
    ends_at = Column(datetime)       # None = no expiry
    is_global = Column(bool, default=False)
    created_at = Column(datetime)

    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("created_at", utcnow())
        super().__init__(**kwargs)

    def check_assertions(self) -> None:
        if self.starts_at is None:
            raise ValidationError("restriction starts_at is required")
        if self.ends_at is not None and self.ends_at <= self.starts_at:
            raise ValidationError("restriction ends_at must be after starts_at")

    # -- activity (reference Restriction.py:195-204) -----------------------
    def is_active(self, at: Optional[datetime] = None) -> bool:
        at = at or utcnow()
        if at < self.starts_at:
            return False
        if self.ends_at is not None and at >= self.ends_at:
            return False
        schedules = self.schedules
        if not schedules:
            return True
        return any(s.is_active(at) for s in schedules)

    # -- linked entities ---------------------------------------------------
    @property
    def users(self) -> List:
        from .user import User

        return User.get_many(
            [l.user_id for l in Restriction2User.filter_by(restriction_id=self.id)]
        )

    @property
    def groups(self) -> List:
        from .user import Group

        return Group.get_many(
            [l.group_id for l in Restriction2Group.filter_by(restriction_id=self.id)]
        )

    @property
    def resources(self) -> List:
        from .resource import Resource

        return Resource.get_many(
            [l.resource_id for l in Restriction2Resource.filter_by(restriction_id=self.id)]
        )

    @property
    def schedules(self) -> List:
        from .schedule import RestrictionSchedule

        return RestrictionSchedule.get_many(
            [l.schedule_id for l in Restriction2Schedule.filter_by(restriction_id=self.id)]
        )

    # -- apply/remove (reference Restriction.py:108-178) -------------------
    def apply_to_user(self, user) -> None:
        with Restriction2User.atomically():
            if not Restriction2User.filter_by(restriction_id=self.id, user_id=user.id):
                Restriction2User(restriction_id=self.id, user_id=user.id).save()

    def remove_from_user(self, user) -> None:
        for link in Restriction2User.filter_by(restriction_id=self.id, user_id=user.id):
            link.destroy()

    def apply_to_group(self, group) -> None:
        with Restriction2Group.atomically():
            if not Restriction2Group.filter_by(restriction_id=self.id, group_id=group.id):
                Restriction2Group(restriction_id=self.id, group_id=group.id).save()

    def remove_from_group(self, group) -> None:
        for link in Restriction2Group.filter_by(restriction_id=self.id, group_id=group.id):
            link.destroy()

    def apply_to_resource(self, resource) -> None:
        with Restriction2Resource.atomically():
            if not Restriction2Resource.filter_by(restriction_id=self.id, resource_id=resource.id):
                Restriction2Resource(restriction_id=self.id, resource_id=resource.id).save()

    def remove_from_resource(self, resource) -> None:
        for link in Restriction2Resource.filter_by(
            restriction_id=self.id, resource_id=resource.id
        ):
            link.destroy()

    def apply_to_resources_by_hostname(self, hostname: str) -> int:
        """Attach every chip of a host (reference restriction controller's
        apply-to-hostname path, controllers/restriction.py)."""
        from .resource import Resource

        count = 0
        for resource in Resource.get_by_hostname(hostname):
            self.apply_to_resource(resource)
            count += 1
        return count

    def add_schedule(self, schedule) -> None:
        with Restriction2Schedule.atomically():
            if not Restriction2Schedule.filter_by(
                restriction_id=self.id, schedule_id=schedule.id
            ):
                Restriction2Schedule(restriction_id=self.id, schedule_id=schedule.id).save()

    def remove_schedule(self, schedule) -> None:
        for link in Restriction2Schedule.filter_by(
            restriction_id=self.id, schedule_id=schedule.id
        ):
            link.destroy()

    # -- queries (reference Restriction.py:180-193, RestrictionAssignee) ---
    @classmethod
    def get_global_restrictions(cls, include_expired: bool = False) -> List["Restriction"]:
        rows = cls.filter_by(is_global=True)
        if include_expired:
            return rows
        now = utcnow()
        return [r for r in rows if r.ends_at is None or r.ends_at > now]

    @classmethod
    def for_user(cls, user_id: int) -> List["Restriction"]:
        return cls.get_many(
            [l.restriction_id for l in Restriction2User.filter_by(user_id=user_id)]
        )

    @classmethod
    def for_group(cls, group_id: int) -> List["Restriction"]:
        return cls.get_many(
            [l.restriction_id for l in Restriction2Group.filter_by(group_id=group_id)]
        )

    @classmethod
    def for_resource(cls, resource_id: int) -> List["Restriction"]:
        return cls.get_many(
            [l.restriction_id for l in Restriction2Resource.filter_by(resource_id=resource_id)]
        )

    def as_dict(self, include_private: bool = False) -> Dict[str, Any]:
        out = super().as_dict(include_private)
        out["schedules"] = [s.as_dict() for s in self.schedules]
        out["resources"] = [r.as_dict() for r in self.resources]
        out["users"] = [u.id for u in self.users]
        out["groups"] = [g.id for g in self.groups]
        return out


class Restriction2User(Model):
    __tablename__ = "restriction2user"
    __table_constraints__ = ("UNIQUE(restriction_id, user_id)",)

    id = Column(int, primary_key=True)
    restriction_id = Column(int, nullable=False, foreign_key="restrictions(id)", index=True)
    user_id = Column(int, nullable=False, foreign_key="users(id)", index=True)


class Restriction2Group(Model):
    __tablename__ = "restriction2group"
    __table_constraints__ = ("UNIQUE(restriction_id, group_id)",)

    id = Column(int, primary_key=True)
    restriction_id = Column(int, nullable=False, foreign_key="restrictions(id)", index=True)
    group_id = Column(int, nullable=False, foreign_key="groups(id)", index=True)


class Restriction2Resource(Model):
    __tablename__ = "restriction2resource"
    __table_constraints__ = ("UNIQUE(restriction_id, resource_id)",)

    id = Column(int, primary_key=True)
    restriction_id = Column(int, nullable=False, foreign_key="restrictions(id)", index=True)
    resource_id = Column(int, nullable=False, foreign_key="resources(id)", index=True)


class Restriction2Schedule(Model):
    """Reference: tensorhive/models/RestrictionSchedule.py:103."""

    __tablename__ = "restriction2schedule"
    __table_constraints__ = ("UNIQUE(restriction_id, schedule_id)",)

    id = Column(int, primary_key=True)
    restriction_id = Column(int, nullable=False, foreign_key="restrictions(id)", index=True)
    schedule_id = Column(int, nullable=False, foreign_key="restriction_schedules(id)", index=True)
