"""Tasks: one command on one host (reference: tensorhive/models/Task.py:19-164)
and their command segments (reference: tensorhive/models/CommandSegment.py:18-75).

``full_command`` re-assembles ``ENV=val ... command --param=val ...`` from
ordered segments exactly like the reference (Task.py:78-101), except segment
kind is an explicit column instead of the reference's signed-index encoding
(negative=env / positive=param, CommandSegment.py:62-75) — same information,
no sign tricks. The chip-binding env var is ``TPU_VISIBLE_CHIPS`` (the
reference greps ``CUDA_VISIBLE_DEVICES=`` prefixes, controllers/task.py:322-328).
"""
from __future__ import annotations

import enum
import shlex
from typing import Any, Dict, List, Optional

from ...utils.exceptions import ValidationError
from ..orm import Column, Model

CHIP_ENV_VAR = "TPU_VISIBLE_CHIPS"


class TaskStatus(str, enum.Enum):
    """Reference: task status values used by controllers/task.py:44-94."""

    not_running = "not_running"
    running = "running"
    terminated = "terminated"
    unsynchronized = "unsynchronized"


class SegmentType(str, enum.Enum):
    env_variable = "env_variable"
    parameter = "parameter"


class Task(Model):
    __tablename__ = "tasks"
    __public__ = ("id", "job_id", "hostname", "pid", "status", "command")

    id = Column(int, primary_key=True)
    job_id = Column(int, nullable=False, foreign_key="jobs(id)", index=True)
    hostname = Column(str, nullable=False)
    command = Column(str, nullable=False)     # base executable + built-in args
    pid = Column(int)
    _status = Column(str, default=TaskStatus.not_running.value)

    def check_assertions(self) -> None:
        if not self.hostname:
            raise ValidationError("task hostname must not be empty")
        if not self.command:
            raise ValidationError("task command must not be empty")
        if self._status not in TaskStatus.__members__:
            raise ValidationError(f"invalid task status {self._status!r}")

    # -- status (propagates to job, reference Task.py:50-55) ---------------
    @property
    def status(self) -> TaskStatus:
        return TaskStatus(self._status)

    @status.setter
    def status(self, value) -> None:
        self._status = TaskStatus(value).value

    def set_status(self, value, synchronize_job: bool = True) -> None:
        self.status = value
        self.save()
        if synchronize_job:
            from .job import Job

            job = Job.get_or_none(self.job_id)
            if job is not None:
                job.synchronize_status()

    # -- segments (reference Task.py:109-139) ------------------------------
    @property
    def segment_links(self) -> List["CommandSegment2Task"]:
        links = CommandSegment2Task.filter_by(task_id=self.id)
        links.sort(key=lambda l: l.position)
        return links

    def _links_with_segments(self) -> List[tuple]:
        """One link-table scan + one batched segment fetch (avoids the N+1
        of calling ``link.segment`` per entry)."""
        links = self.segment_links
        if not links:
            return []
        ids = sorted({l.segment_id for l in links})
        placeholders = ", ".join("?" * len(ids))
        segments = {
            s.id: s for s in CommandSegment.where(f"id IN ({placeholders})", ids)
        }
        return [(link, segments[link.segment_id]) for link in links]

    def add_cmd_segment(self, name: str, value: str = "", segment_type=SegmentType.parameter) -> "CommandSegment":
        segment_type = SegmentType(segment_type)
        with CommandSegment.atomically():
            segment = CommandSegment.first_by(name=name, _segment_type=segment_type.value)
            if segment is None:
                segment = CommandSegment(name=name, _segment_type=segment_type.value).save()
            existing = CommandSegment2Task.filter_by(task_id=self.id, segment_id=segment.id)
            if existing:
                link = existing[0]
                link.value = value
                link.save()
            else:
                links = self.segment_links
                next_position = max((l.position for l in links), default=0) + 1
                CommandSegment2Task(
                    task_id=self.id, segment_id=segment.id, value=value, position=next_position
                ).save()
        return segment

    def remove_cmd_segment(self, name: str) -> bool:
        for link, segment in self._links_with_segments():
            if segment.name == name:
                link.destroy()
                return True
        return False

    def get_segment_value(self, name: str) -> Optional[str]:
        for link, segment in self._links_with_segments():
            if segment.name == name:
                return link.value
        return None

    # -- command assembly (reference Task.py:78-101) -----------------------
    @property
    def env_segments(self) -> List["CommandSegment2Task"]:
        return [
            link for link, seg in self._links_with_segments()
            if seg.segment_type is SegmentType.env_variable
        ]

    @property
    def param_segments(self) -> List["CommandSegment2Task"]:
        return [
            link for link, seg in self._links_with_segments()
            if seg.segment_type is SegmentType.parameter
        ]

    @property
    def full_command(self) -> str:
        envs: List[str] = []
        params: List[str] = []
        for link, segment in self._links_with_segments():
            if segment.segment_type is SegmentType.env_variable:
                envs.append(f"{segment.name}={shlex.quote(link.value or '')}")
            elif link.value:
                params.append(f"{segment.name}={shlex.quote(link.value)}")
            else:
                params.append(segment.name)
        return " ".join(envs + [self.command] + params)

    # -- chip binding ------------------------------------------------------
    @property
    def chip_ids(self) -> List[int]:
        """Local chip indices bound via TPU_VISIBLE_CHIPS (reference parses
        CUDA_VISIBLE_DEVICES=N, controllers/task.py:322-328)."""
        raw = self.get_segment_value(CHIP_ENV_VAR)
        if not raw:
            return []
        try:
            return [int(x) for x in raw.split(",") if x.strip() != ""]
        except ValueError:
            return []

    @property
    def chip_uids(self) -> List[str]:
        """Global chip UIDs = '<hostname>:tpu:<index>' (Resource.uid scheme)."""
        return [f"{self.hostname}:tpu:{i}" for i in self.chip_ids]

    def as_dict(self, include_private: bool = False) -> Dict[str, Any]:
        out = super().as_dict(include_private)
        out["status"] = self.status.value
        out["fullCommand"] = self.full_command
        out["cmdSegments"] = [
            {
                "name": segment.name,
                "value": link.value,
                "type": segment.segment_type.value,
                "index": link.position,
            }
            for link, segment in self._links_with_segments()
        ]
        return out


class CommandSegment(Model):
    """Reference: tensorhive/models/CommandSegment.py:18-60."""

    __tablename__ = "command_segments"
    __table_constraints__ = ("UNIQUE(name, _segment_type)",)

    id = Column(int, primary_key=True)
    name = Column(str, nullable=False)
    _segment_type = Column(str, nullable=False, default=SegmentType.parameter.value)

    @property
    def segment_type(self) -> SegmentType:
        return SegmentType(self._segment_type)

    def check_assertions(self) -> None:
        if not self.name:
            raise ValidationError("segment name must not be empty")
        if self._segment_type not in SegmentType.__members__:
            raise ValidationError(f"invalid segment type {self._segment_type!r}")


class CommandSegment2Task(Model):
    """Link table carrying per-task value and ordering
    (reference: CommandSegment.py:62-75 `_value`, signed `_index`)."""

    __tablename__ = "command_segment2task"
    __table_constraints__ = ("UNIQUE(task_id, segment_id)",)

    id = Column(int, primary_key=True)
    task_id = Column(int, nullable=False, foreign_key="tasks(id)", index=True)
    segment_id = Column(int, nullable=False, foreign_key="command_segments(id)", index=True)
    value = Column(str, default="")
    position = Column(int, default=0)

    @property
    def segment(self) -> CommandSegment:
        return CommandSegment.get(self.segment_id)
