"""Domain entities (reference: tensorhive/models/).

Importing this package registers every table with the ORM so
:func:`tensorhive_tpu.db.create_all` sees the full schema (the same role as
the reference's migrations/env.py:10-22 importing all models).
"""
from .user import User, Role, Group, User2Group  # noqa: F401
from .resource import Resource  # noqa: F401
from .reservation import Reservation  # noqa: F401
from .restriction import (  # noqa: F401
    Restriction,
    Restriction2User,
    Restriction2Group,
    Restriction2Resource,
    Restriction2Schedule,
)
from .schedule import RestrictionSchedule  # noqa: F401
from .job import Job, JobStatus  # noqa: F401
from .task import Task, TaskStatus, CommandSegment, CommandSegment2Task, SegmentType  # noqa: F401
from .token import RevokedToken  # noqa: F401
