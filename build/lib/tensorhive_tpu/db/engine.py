"""SQLite engine: one serialized connection per process.

Reference: tensorhive/database.py:15-23 (engine + scoped session; in-memory
SQLite when ``PYTEST`` env set, config.py:164). The reference shares one
scoped session across API threads and service threads (SURVEY.md §3.5
boundary notes); here all access goes through a single connection guarded by
an RLock — writes in a cluster manager are rare and tiny, so serialization is
simpler and race-free. File databases get WAL mode for concurrent readers.
"""
from __future__ import annotations

import logging
import os
import sqlite3
import threading
from typing import Any, Iterable, Optional

from ..config import get_config

log = logging.getLogger(__name__)


class Engine:
    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.RLock()
        self._txn_depth = 0  # >0 while inside an explicit transaction()
        if path != ":memory:":
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        with self._lock:
            self._conn.execute("PRAGMA foreign_keys = ON")
            if path != ":memory:":
                self._conn.execute("PRAGMA journal_mode = WAL")

    # -- statement API -----------------------------------------------------
    def execute(self, sql: str, params: Iterable[Any] = ()) -> sqlite3.Cursor:
        with self._lock:
            cursor = self._conn.execute(sql, tuple(params))
            if self._txn_depth == 0:
                self._conn.commit()
            return cursor

    def executemany(self, sql: str, rows: Iterable[Iterable[Any]]) -> None:
        with self._lock:
            self._conn.executemany(sql, [tuple(r) for r in rows])
            if self._txn_depth == 0:
                self._conn.commit()

    def query(self, sql: str, params: Iterable[Any] = ()) -> list:
        with self._lock:
            return self._conn.execute(sql, tuple(params)).fetchall()

    def scalar(self, sql: str, params: Iterable[Any] = ()) -> Any:
        rows = self.query(sql, params)
        return rows[0][0] if rows else None

    def transaction(self) -> "_Transaction":
        """Explicit multi-statement transaction (scheduler state flips)."""
        return _Transaction(self)

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    @property
    def user_version(self) -> int:
        return int(self.scalar("PRAGMA user_version"))

    @user_version.setter
    def user_version(self, value: int) -> None:
        self.execute(f"PRAGMA user_version = {int(value)}")


class _Transaction:
    """Holds the engine lock for its whole extent and defers commit to exit,
    so multi-statement sequences are atomic (vs other threads) AND
    all-or-nothing (rollback undoes every statement issued inside)."""

    def __init__(self, engine: Engine) -> None:
        self._engine = engine

    def __enter__(self) -> sqlite3.Connection:
        self._engine._lock.acquire()
        self._engine._txn_depth += 1
        return self._engine._conn

    def __exit__(self, exc_type, exc, tb) -> bool:
        engine = self._engine
        try:
            engine._txn_depth -= 1
            if engine._txn_depth == 0:
                if exc_type is None:
                    engine._conn.commit()
                else:
                    engine._conn.rollback()
        finally:
            engine._lock.release()
        return False


# ---------------------------------------------------------------------------
_engine: Optional[Engine] = None
_engine_lock = threading.Lock()


def get_engine() -> Engine:
    """Process-wide engine, created on first use against the configured DB
    path (in-memory under pytest). Schema is ensured on creation — the
    equivalent of the reference's ``ensure_db_with_current_schema``
    (database.py:72-87)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            from .migrations import ensure_schema

            _engine = Engine(get_config().db_path)
            ensure_schema(_engine)
        return _engine


def set_engine(engine: Engine) -> None:
    global _engine
    with _engine_lock:
        _engine = engine


def reset_engine() -> None:
    global _engine
    with _engine_lock:
        if _engine is not None:
            _engine.close()
        _engine = None
