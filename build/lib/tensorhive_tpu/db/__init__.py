"""Persistence: sqlite3 engine, declarative ORM-lite, schema migrations.

Reference: tensorhive/database.py + tensorhive/models/ built on SQLAlchemy +
Alembic. Neither is assumed available here; the rebuild uses a small
stdlib-``sqlite3`` declarative layer with the same capabilities the reference
actually exercises: CRUD base with save-time validation hooks
(models/CRUDModel.py:11-94), scoped per-process access, in-memory DB under
pytest (database.py:15-18), foreign keys ON (database.py:90-94), and
sequential schema migrations (``PRAGMA user_version`` standing in for Alembic
revisions, database.py:72-87).
"""
from .engine import Engine, get_engine, reset_engine, set_engine  # noqa: F401
from .orm import Column, Model, create_all  # noqa: F401
