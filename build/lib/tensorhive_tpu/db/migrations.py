"""Sequential schema migrations via ``PRAGMA user_version``.

Reference: tensorhive/database.py:72-87 creates the schema then
Alembic-stamps/upgrades on boot (18 revisions under tensorhive/migrations/).
Here each migration is a ``(version, fn)`` pair applied in order; a fresh DB
gets ``create_all`` and is stamped at the latest version directly.
"""
from __future__ import annotations

import logging
from typing import Callable, List, Tuple

from .engine import Engine
from .orm import create_all

log = logging.getLogger(__name__)


def _column_names(engine: Engine, table: str) -> List[str]:
    return [row[1] for row in engine.execute(f"PRAGMA table_info({table})")]


def _add_column(engine: Engine, table: str, column: str, ddl_type: str) -> None:
    """Idempotent ADD COLUMN: safe to re-run after a crash mid-upgrade."""
    if column not in _column_names(engine, table):
        engine.execute(f"ALTER TABLE {table} ADD COLUMN {column} {ddl_type}")


def _migration_2_user_last_login(engine: Engine) -> None:
    """v1 → v2: ``users.last_login_at`` (ISO-8601 TEXT, set by the login
    controller; shown in the users admin view)."""
    _add_column(engine, "users", "last_login_at", "TEXT")


# append (version, fn) pairs as the schema evolves; fn(engine) must be
# idempotent enough to re-run after a crash mid-upgrade.
MIGRATIONS: List[Tuple[int, Callable[[Engine], None]]] = [
    (2, _migration_2_user_last_login),
]

SCHEMA_VERSION = 2


def ensure_schema(engine: Engine) -> None:
    from . import models  # noqa: F401  (register all tables)

    current = engine.user_version
    if current == 0:
        create_all(engine)
        engine.user_version = SCHEMA_VERSION
        log.info("database schema created at version %d", SCHEMA_VERSION)
        return
    for version, migrate in MIGRATIONS:
        if version > current:
            log.info("applying migration %d", version)
            migrate(engine)
            engine.user_version = version
    # create any tables added since the stamped version (additive changes)
    create_all(engine)
    if engine.user_version < SCHEMA_VERSION:
        engine.user_version = SCHEMA_VERSION
