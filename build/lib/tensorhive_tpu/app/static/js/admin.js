"use strict";
/* users + groups administration.
   Reference: UsersOverview.vue (user table + role editing) and the group
   admin parts of the reference UI (default groups auto-attach new users,
   models/Group.py get_default_groups). */

/* ---------- users -------------------------------------------------------- */
function renderUsers(main) {
  main.innerHTML = `<div class="card">
    <div class="row"><h3 style="margin:0">Users</h3><span style="flex:1"></span>
      <button class="primary" onclick="openUserDialog()">New user</button></div>
    <div id="user-list" style="margin-top:.8rem"></div></div>
    <dialog id="user-dialog"></dialog>`;
  loadUsers().catch(e => toast(e.message, true));
}
async function loadUsers() {
  const users = await api("/users");
  const el = document.getElementById("user-list");
  if (!el) return;
  el.innerHTML = `
    <table><tr><th>id</th><th>username</th><th>email</th><th>roles</th>
      <th>last login</th><th></th></tr>
    ${users.map(u => `<tr><td>${u.id}</td><td>${esc(u.username)}</td>
      <td>${esc(u.email)}</td><td>${(u.roles || []).join(", ")}</td>
      <td class="muted">${fmtDt(u.lastLoginAt)}</td>
      <td class="row">
        <button class="ghost small" onclick="openUserEditDialog(${u.id})">edit</button>
        <button class="ghost small danger" onclick="deleteUser(${u.id})">✕</button>
      </td></tr>`).join("")}</table>`;
}
function openUserDialog() {
  const dialog = document.getElementById("user-dialog");
  dialog.innerHTML = `<h3>New user</h3>
    <label>Username</label><input id="ud-name">
    <label>Email</label><input id="ud-email">
    <label>Password</label><input id="ud-pass" type="password">
    <label class="inline"><input id="ud-admin" type="checkbox"> admin</label>
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="createUser()">Create</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
    </div>`;
  dialog.showModal();
}
async function createUser() {
  try {
    await api("/users", { json: {
      username: document.getElementById("ud-name").value,
      email: document.getElementById("ud-email").value,
      password: document.getElementById("ud-pass").value,
      admin: document.getElementById("ud-admin").checked } });
    document.getElementById("user-dialog").close(); loadUsers();
  } catch (e) { toast(e.message, true); }
}
async function openUserEditDialog(id) {
  let user;
  try { user = await api("/users/" + id); }
  catch (e) { return toast(e.message, true); }
  const dialog = document.getElementById("user-dialog");
  dialog.innerHTML = `<h3>Edit ${esc(user.username)}
      <span class="muted">#${user.id}</span></h3>
    <label>Email</label><input id="ud-email" value="${esc(user.email)}">
    <label>New password <span class="muted">(leave empty to keep)</span></label>
    <input id="ud-pass" type="password" autocomplete="new-password">
    <label class="inline"><input id="ud-admin" type="checkbox"
      ${(user.roles || []).includes("admin") ? "checked" : ""}> admin</label>
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="saveUser(${user.id})">Save</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
    </div>`;
  dialog.showModal();
}
async function saveUser(id) {
  try {
    const body = { email: document.getElementById("ud-email").value,
                   roles: document.getElementById("ud-admin").checked
                     ? ["user", "admin"] : ["user"] };
    const pass = document.getElementById("ud-pass").value;
    if (pass) body.password = pass;
    await api("/users/" + id, { method: "PUT", json: body });
    document.getElementById("user-dialog").close(); loadUsers();
  } catch (e) { toast(e.message, true); }
}
async function deleteUser(id) {
  try { await api("/users/" + id, { method: "DELETE" }); loadUsers(); }
  catch (e) { toast(e.message, true); }
}

/* ---------- groups ------------------------------------------------------- */
function renderGroups(main) {
  main.innerHTML = `<div class="card">
    <div class="row"><h3 style="margin:0">Groups</h3><span style="flex:1"></span>
      <button class="primary" onclick="openGroupDialog()">New group</button></div>
    <div id="group-list" style="margin-top:.8rem"></div></div>
    <dialog id="group-dialog"></dialog>`;
  loadGroups().catch(e => toast(e.message, true));
}
async function loadGroups() {
  const groups = await api("/groups");
  const el = document.getElementById("group-list");
  if (!el) return;
  el.innerHTML = groups.length ? `
    <table><tr><th>id</th><th>name</th><th>default</th><th>members</th><th></th></tr>
    ${groups.map(g => `<tr><td>${g.id}</td><td>${esc(g.name)}</td>
      <td>${g.isDefault ? '<span class="badge on">default</span>' : ""}</td>
      <td class="muted">${(g.users || []).map(u => esc(u.username)).join(", ") || "—"}</td>
      <td class="row">
        <button class="ghost small" onclick="openGroupEditDialog(${g.id})">edit</button>
        <button class="ghost small danger" onclick="deleteGroup(${g.id})">✕</button>
      </td></tr>`).join("")}</table>` :
    `<p class="muted">No groups yet.</p>`;
}
function openGroupDialog() {
  const dialog = document.getElementById("group-dialog");
  dialog.innerHTML = `<h3>New group</h3>
    <label>Name</label><input id="gd-name">
    <label class="inline"><input id="gd-default" type="checkbox">
      default <span class="muted">(new users auto-join)</span></label>
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="createGroup()">Create</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
    </div>`;
  dialog.showModal();
}
async function createGroup() {
  try {
    await api("/groups", { json: {
      name: document.getElementById("gd-name").value,
      isDefault: document.getElementById("gd-default").checked } });
    document.getElementById("group-dialog").close(); loadGroups();
  } catch (e) { toast(e.message, true); }
}
async function openGroupEditDialog(id) {
  let group, users;
  try {
    [group, users] = await Promise.all([api("/groups/" + id), api("/users")]);
  } catch (e) { return toast(e.message, true); }
  const memberIds = new Set((group.users || []).map(u => u.id));
  const nonMembers = users.filter(u => !memberIds.has(u.id));
  const dialog = document.getElementById("group-dialog");
  dialog.innerHTML = `<h3>Edit ${esc(group.name)}
      <span class="muted">#${group.id}</span></h3>
    <label>Name</label><input id="gd-name" value="${esc(group.name)}">
    <label class="inline"><input id="gd-default" type="checkbox"
      ${group.isDefault ? "checked" : ""}> default</label>
    <label>Members</label>
    <div class="assign-list">${(group.users || []).map(u => `
      <div class="tagrow"><span>${esc(u.username)}</span>
        <button class="ghost small danger"
          onclick="groupRemoveMember(${group.id}, ${u.id})">✕</button></div>`).join("")
      || '<span class="muted">none</span>'}</div>
    <div class="row">
      <select id="gd-adduser" style="flex:1">${nonMembers.map(u =>
        `<option value="${u.id}">${esc(u.username)}</option>`).join("")}</select>
      <button class="ghost" onclick="groupAddMember(${group.id})"
        ${nonMembers.length ? "" : "disabled"}>Add member</button>
    </div>
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="saveGroup(${group.id})">Save</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Close</button>
    </div>`;
  dialog.showModal();
}
async function saveGroup(id) {
  try {
    await api("/groups/" + id, { method: "PUT", json: {
      name: document.getElementById("gd-name").value,
      isDefault: document.getElementById("gd-default").checked } });
    document.getElementById("group-dialog").close(); loadGroups();
  } catch (e) { toast(e.message, true); }
}
async function groupAddMember(groupId) {
  const userId = document.getElementById("gd-adduser").value;
  try {
    await api(`/groups/${groupId}/users/${userId}`, { method: "PUT" });
    openGroupEditDialog(groupId); loadGroups();
  } catch (e) { toast(e.message, true); }
}
async function groupRemoveMember(groupId, userId) {
  try {
    await api(`/groups/${groupId}/users/${userId}`, { method: "DELETE" });
    openGroupEditDialog(groupId); loadGroups();
  } catch (e) { toast(e.message, true); }
}
async function deleteGroup(id) {
  try { await api("/groups/" + id, { method: "DELETE" }); loadGroups(); }
  catch (e) { toast(e.message, true); }
}
