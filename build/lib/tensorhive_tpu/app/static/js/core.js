"use strict";
/* core: api client + session + router.
   Reference: src/api/index.js (axios wrapper), store/state.js (token store),
   TheLogin.vue (login + ssh signup). */

let API = location.protocol + "//" + location.hostname + ":1111/api";
const state = { user: null, access: null, refresh: null, view: "nodes",
                timers: [] };

async function loadConfig() {
  try {
    const cfg = await (await fetch("/config.json")).json();
    API = cfg.apiUrl.replace("{host}", location.hostname);
  } catch (e) { /* defaults */ }
}

async function api(path, options = {}) {
  options.headers = Object.assign(
    { "Content-Type": "application/json" },
    state.access ? { Authorization: "Bearer " + state.access } : {},
    options.headers || {});
  if (options.json !== undefined) {
    options.body = JSON.stringify(options.json); options.method = options.method || "POST";
  }
  let resp = await fetch(API + path, options);
  if (resp.status === 401 && state.refresh && path !== "/user/refresh") {
    if (await tryRefresh()) {
      options.headers.Authorization = "Bearer " + state.access;
      resp = await fetch(API + path, options);
    } else { logout(); throw new Error("session expired"); }
  }
  const body = await resp.json().catch(() => ({}));
  if (!resp.ok) throw new Error(body.msg || resp.statusText);
  return body;
}

async function tryRefresh() {
  try {
    const body = await (await fetch(API + "/user/refresh", {
      method: "POST", headers: { Authorization: "Bearer " + state.refresh }})).json();
    if (body.accessToken) { state.access = body.accessToken; persist(); return true; }
  } catch (e) {}
  return false;
}

function persist() {
  localStorage.setItem("tpuhive", JSON.stringify(
    { user: state.user, access: state.access, refresh: state.refresh }));
}
function restore() {
  try { Object.assign(state, JSON.parse(localStorage.getItem("tpuhive") || "{}")); }
  catch (e) {}
}
function logout() {
  // revoke both tokens server-side (reference logout + logout/refresh)
  if (state.access) api("/user/logout", { method: "POST" }).catch(() => {});
  if (state.refresh) {
    fetch(API + "/user/logout/refresh", { method: "POST",
      headers: { Authorization: "Bearer " + state.refresh } }).catch(() => {});
  }
  state.user = state.access = state.refresh = null;
  localStorage.removeItem("tpuhive");
  render();
}
function toast(msg, isError) {
  const el = document.getElementById("toast");
  el.textContent = msg; el.style.display = "block";
  el.style.borderColor = isError ? "var(--err)" : "var(--border)";
  clearTimeout(el._t); el._t = setTimeout(() => el.style.display = "none", 4000);
}
const esc = s => String(s ?? "").replace(/[&<>"]/g,
  c => ({ "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;" }[c]));
// for server-provided strings inside single-quoted args of inline handlers:
// JS-escape first (backslash, quote), THEN html-escape — the browser decodes
// entities before the JS engine parses the handler, so esc() alone would
// still let an apostrophe terminate the string literal
const jsArg = s => esc(String(s ?? "")
  .replace(/\\/g, "\\\\").replace(/'/g, "\\'"));
const isAdmin = () => state.user && (state.user.roles || []).includes("admin");
const fmtDt = iso => iso ? new Date(iso).toLocaleString(undefined,
  { dateStyle: "short", timeStyle: "short" }) : "—";
// <input type=datetime-local> value for a Date (local tz)
const toLocalInput = d =>
  new Date(d - d.getTimezoneOffset() * 6e4).toISOString().slice(0, 16);
const fromLocalInput = v => new Date(v).toISOString();

/* ---------- shell -------------------------------------------------------- */
const VIEWS = {
  nodes: { label: "Nodes", render: () => renderNodes(mainEl()) },
  calendar: { label: "Reservations", render: () => renderCalendar(mainEl()) },
  jobs: { label: "Jobs", render: () => renderJobs(mainEl()) },
  users: { label: "Users", render: () => renderUsers(mainEl()), admin: true },
  groups: { label: "Groups", render: () => renderGroups(mainEl()), admin: true },
  access: { label: "Access", render: () => renderAccess(mainEl()) },
};
const mainEl = () => document.getElementById("main");

function render() {
  state.timers.forEach(clearInterval); state.timers = [];
  const main = mainEl();
  const topbar = document.getElementById("topbar");
  if (!state.access) { topbar.style.display = "none"; return renderLogin(main); }
  topbar.style.display = "flex";
  document.getElementById("user-box").textContent =
    state.user.username + (isAdmin() ? " (admin)" : "");
  const nav = document.getElementById("nav");
  nav.innerHTML = Object.entries(VIEWS)
    .filter(([, v]) => !v.admin || isAdmin())
    .map(([k, v]) =>
      `<button class="${state.view === k ? "active" : ""}"
               onclick="go('${k}')">${v.label}</button>`).join("");
  (VIEWS[state.view] || VIEWS.nodes).render();
}
function go(view) { state.view = view; render(); }

/* ---------- login + ssh signup ------------------------------------------- */
function renderLogin(main, tab = "login") {
  main.innerHTML = `
    <div id="login-view" class="card">
      <h2>tpuhive</h2>
      <div class="tabs">
        <button class="${tab === "login" ? "primary" : "ghost"}"
                onclick="renderLogin(document.getElementById('main'),'login')">Log in</button>
        <button class="${tab === "signup" ? "primary" : "ghost"}"
                onclick="renderLogin(document.getElementById('main'),'signup')">SSH sign up</button>
      </div>
      <div id="login-body"></div>
      <p class="muted" id="li-err"></p>
    </div>`;
  const body = main.querySelector("#login-body");
  if (tab === "login") {
    body.innerHTML = `
      <input id="li-user" placeholder="username" autocomplete="username">
      <input id="li-pass" type="password" placeholder="password"
             autocomplete="current-password">
      <button class="primary" style="width:100%" onclick="doLogin()">Log in</button>`;
    body.querySelector("#li-pass").addEventListener("keydown",
      e => e.key === "Enter" && doLogin());
  } else {
    body.innerHTML = `
      <p class="muted">Prove you own a unix account on a managed host: install
      the manager key below in that account's <code>~/.ssh/authorized_keys</code>,
      then sign up with the same username.</p>
      <pre class="keyline" id="su-key">loading key…</pre>
      <input id="su-user" placeholder="unix username">
      <input id="su-email" placeholder="email">
      <input id="su-pass" type="password" placeholder="password"
             autocomplete="new-password">
      <button class="primary" style="width:100%" onclick="doSshSignup()">Sign up</button>`;
    api("/user/authorized_keys_entry")
      .then(b => body.querySelector("#su-key").textContent = b.authorizedKeysEntry)
      .catch(e => body.querySelector("#su-key").textContent = e.message);
  }
}
async function doLogin() {
  try {
    const body = await api("/user/login", { json: {
      username: document.getElementById("li-user").value,
      password: document.getElementById("li-pass").value } });
    state.user = body.user; state.access = body.accessToken;
    state.refresh = body.refreshToken; persist(); render();
  } catch (e) {
    document.getElementById("li-err").textContent = e.message;
    document.getElementById("li-err").className = "err";
  }
}
async function doSshSignup() {
  try {
    await api("/user/ssh_signup", { json: {
      username: document.getElementById("su-user").value,
      email: document.getElementById("su-email").value,
      password: document.getElementById("su-pass").value } });
    toast("account created — log in now");
    renderLogin(mainEl(), "login");
  } catch (e) {
    document.getElementById("li-err").textContent = e.message;
    document.getElementById("li-err").className = "err";
  }
}

/* ---------- boot --------------------------------------------------------- */
async function boot() {
  await loadConfig();
  restore();
  render();
}
