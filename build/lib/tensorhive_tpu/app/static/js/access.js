"use strict";
/* access control: restrictions + schedules administration.
   Reference: the restriction & schedule admin views the reference UI ships
   (controllers/restriction.py apply/remove against users, groups, resources,
   hostnames, schedules; RestrictionSchedule weekday-mask windows). Read-only
   for non-admins (mutations are admin-gated server-side). */

const DAY_LABELS = ["Mo", "Tu", "We", "Th", "Fr", "Sa", "Su"]; // mask digit 1..7

let accessUsers = [];          // admin-only cache (id -> username display)
let accessGroups = [];
let accessResources = [];
let accessSchedules = [];
let accessOpenId = null;       // expanded restriction drawer

function renderAccess(main) {
  main.innerHTML = `<div class="panel-2col">
    <div class="card">
      <div class="row"><h3 style="margin:0">Restrictions</h3><span style="flex:1"></span>
        ${isAdmin() ? `<button class="primary"
          onclick="openRestrictionDialog()">New restriction</button>` : ""}</div>
      <p class="muted" style="margin:.3rem 0">A user may only reserve chips
        granted by an active restriction (direct, via group, or global).</p>
      <div id="restriction-list" style="margin-top:.5rem"></div>
    </div>
    <div class="card">
      <div class="row"><h3 style="margin:0">Schedules</h3><span style="flex:1"></span>
        ${isAdmin() ? `<button class="primary"
          onclick="openScheduleDialog()">New schedule</button>` : ""}</div>
      <p class="muted" style="margin:.3rem 0">Weekday + hour windows that
        limit when an attached restriction is active.</p>
      <div id="schedule-list" style="margin-top:.5rem"></div>
    </div>
  </div>
  <dialog id="access-dialog"></dialog>`;
  loadAccess().catch(e => toast(e.message, true));
}

async function loadAccess() {
  const wants = [
    api("/restrictions"), api("/schedules"), api("/resources"),
    isAdmin() ? api("/users") : Promise.resolve([]),
    api("/groups").catch(() => []),
  ];
  const [restrictions, schedules, resources, users, groups] = await Promise.all(wants);
  accessSchedules = schedules; accessResources = resources;
  accessUsers = users; accessGroups = groups;
  drawSchedules(schedules);
  drawRestrictions(restrictions);
}

/* ---------- schedules ---------------------------------------------------- */
function scheduleLabel(schedule) {
  const days = [...schedule.scheduleDays]
    .map(d => DAY_LABELS[parseInt(d, 10) - 1] || "?").join(" ");
  return `${days} · ${schedule.hourStart}–${schedule.hourEnd}`;
}
function drawSchedules(schedules) {
  const el = document.getElementById("schedule-list");
  if (!el) return;
  el.innerHTML = schedules.length ? `
    <table><tr><th>id</th><th>window</th>${isAdmin() ? "<th></th>" : ""}</tr>
    ${schedules.map(schedule => `<tr>
      <td>${schedule.id}</td><td>${esc(scheduleLabel(schedule))}</td>
      ${isAdmin() ? `<td class="row">
        <button class="ghost small"
          onclick="openScheduleDialog(${schedule.id})">edit</button>
        <button class="ghost small danger"
          onclick="deleteSchedule(${schedule.id})">✕</button></td>` : ""}
      </tr>`).join("")}</table>` :
    `<p class="muted">No schedules yet.</p>`;
}
async function openScheduleDialog(id) {
  let schedule = null;
  if (id) {
    try { schedule = await api("/schedules/" + id); }
    catch (e) { return toast(e.message, true); }
  }
  const mask = schedule ? schedule.scheduleDays : "12345";
  const dialog = document.getElementById("access-dialog");
  dialog.innerHTML = `<h3>${schedule ? "Edit schedule #" + id : "New schedule"}</h3>
    <label>Days</label>
    <div class="daypick">${DAY_LABELS.map((label, i) => `
      <label>${label}<input type="checkbox" class="sd-day" value="${i + 1}"
        ${mask.includes(String(i + 1)) ? "checked" : ""}></label>`).join("")}</div>
    <label>From</label><input id="sd-start" type="time"
      value="${esc(schedule ? schedule.hourStart : "08:00")}">
    <label>To</label><input id="sd-end" type="time"
      value="${esc(schedule ? schedule.hourEnd : "20:00")}">
    <div class="row" style="margin-top:1rem">
      <button class="primary" onclick="saveSchedule(${id || "null"})">
        ${schedule ? "Save" : "Create"}</button>
      <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
    </div>`;
  dialog.showModal();
}
async function saveSchedule(id) {
  const body = {
    scheduleDays: [...document.querySelectorAll(".sd-day:checked")]
      .map(el => el.value).join(""),
    hourStart: document.getElementById("sd-start").value,
    hourEnd: document.getElementById("sd-end").value };
  try {
    if (id) await api("/schedules/" + id, { method: "PUT", json: body });
    else await api("/schedules", { json: body });
    document.getElementById("access-dialog").close(); loadAccess();
  } catch (e) { toast(e.message, true); }
}
async function deleteSchedule(id) {
  try { await api("/schedules/" + id, { method: "DELETE" }); loadAccess(); }
  catch (e) { toast(e.message, true); }
}

/* ---------- restrictions ------------------------------------------------- */
const userName = id => {
  const user = accessUsers.find(u => u.id === id);
  return user ? user.username : "user #" + id;
};
const groupName = id => {
  const group = accessGroups.find(g => g.id === id);
  return group ? group.name : "group #" + id;
};

function drawRestrictions(restrictions) {
  const el = document.getElementById("restriction-list");
  if (!el) return;
  el.innerHTML = restrictions.map(r => `
    <details class="drawer" ${accessOpenId === r.id ? "open" : ""}
        ontoggle="accessOpenId = this.open ? ${r.id} : null">
      <summary><b style="color:var(--text)">${esc(r.name)}</b>
        <span class="muted">#${r.id}</span>
        ${r.isGlobal ? '<span class="badge on">global</span>' : ""}
        <span class="muted">${fmtDt(r.startsAt)} →
          ${r.endsAt ? fmtDt(r.endsAt) : "∞"}</span></summary>
      ${restrictionBody(r)}
    </details>`).join("") || `<p class="muted">No restrictions yet.</p>`;
}

function restrictionBody(r) {
  const admin = isAdmin();
  const rm = (kind, key, label) => admin ? `<button class="ghost small danger"
    onclick="restrictionRemove(${r.id}, '${kind}', '${jsArg(String(key))}')">✕</button>` : "";
  const assignedScheduleIds = new Set((r.schedules || []).map(s => s.id));
  const assignedResourceUids = new Set((r.resources || []).map(res => res.uid));
  const freeSchedules = accessSchedules.filter(s => !assignedScheduleIds.has(s.id));
  const freeResources = accessResources.filter(res => !assignedResourceUids.has(res.uid));
  const assignedUserIds = new Set(r.users || []);
  const assignedGroupIds = new Set(r.groups || []);
  const freeUsers = accessUsers.filter(u => !assignedUserIds.has(u.id));
  const freeGroups = accessGroups.filter(g => !assignedGroupIds.has(g.id));
  const hostnames = [...new Set(accessResources.map(res => res.hostname))];
  const addRow = (selectId, options, onclick, label) => admin && options.length ? `
    <div class="row" style="margin:.25rem 0">
      <select id="${selectId}-${r.id}" style="flex:1">${options}</select>
      <button class="ghost small" onclick="${onclick}">${label}</button>
    </div>` : "";
  return `
    ${isAdmin() ? `<div class="row" style="margin:.4rem 0">
      <button class="ghost small" onclick="openRestrictionDialog(${r.id})">edit</button>
      <button class="ghost small danger"
        onclick="deleteRestriction(${r.id})">delete</button></div>` : ""}
    <label>Users</label>
    <div class="assign-list">${(r.users || []).map(id => `
      <div class="tagrow"><span>${esc(userName(id))}</span>
        ${rm("users", id)}</div>`).join("")
      || '<span class="muted">none directly</span>'}</div>
    ${addRow("ra-user", freeUsers.map(u =>
        `<option value="${u.id}">${esc(u.username)}</option>`).join(""),
      `restrictionApply(${r.id}, 'users',
        document.getElementById('ra-user-${r.id}').value)`, "Apply to user")}
    <label>Groups</label>
    <div class="assign-list">${(r.groups || []).map(id => `
      <div class="tagrow"><span>${esc(groupName(id))}</span>
        ${rm("groups", id)}</div>`).join("")
      || '<span class="muted">none</span>'}</div>
    ${addRow("ra-group", freeGroups.map(g =>
        `<option value="${g.id}">${esc(g.name)}</option>`).join(""),
      `restrictionApply(${r.id}, 'groups',
        document.getElementById('ra-group-${r.id}').value)`, "Apply to group")}
    <label>Chips</label>
    <div class="assign-list">${(r.resources || []).map(res => `
      <div class="tagrow"><span>${esc(res.uid)}</span>
        ${rm("resources", res.uid)}</div>`).join("")
      || `<span class="muted">${r.isGlobal ? "global — all chips" : "none"}</span>`}</div>
    ${addRow("ra-res", freeResources.map(res =>
        `<option value="${esc(res.uid)}">${esc(res.uid)}</option>`).join(""),
      `restrictionApply(${r.id}, 'resources',
        document.getElementById('ra-res-${r.id}').value)`, "Apply to chip")}
    ${addRow("ra-host", hostnames.map(h =>
        `<option value="${esc(h)}">${esc(h)}</option>`).join(""),
      `restrictionApply(${r.id}, 'hosts',
        document.getElementById('ra-host-${r.id}').value)`, "Apply whole host")}
    <label>Schedules</label>
    <div class="assign-list">${(r.schedules || []).map(schedule => `
      <div class="tagrow"><span>${esc(scheduleLabel(schedule))}</span>
        ${rm("schedules", schedule.id)}</div>`).join("")
      || '<span class="muted">always active within the window</span>'}</div>
    ${addRow("ra-sched", freeSchedules.map(schedule =>
        `<option value="${schedule.id}">${esc(scheduleLabel(schedule))}</option>`).join(""),
      `restrictionApply(${r.id}, 'schedules',
        document.getElementById('ra-sched-${r.id}').value)`, "Attach schedule")}`;
}

async function restrictionApply(id, kind, key) {
  try {
    await api(`/restrictions/${id}/${kind}/${encodeURIComponent(key)}`,
      { method: "PUT" });
    loadAccess();
  } catch (e) { toast(e.message, true); }
}
async function restrictionRemove(id, kind, key) {
  try {
    await api(`/restrictions/${id}/${kind}/${encodeURIComponent(key)}`,
      { method: "DELETE" });
    loadAccess();
  } catch (e) { toast(e.message, true); }
}

function openRestrictionDialog(id) {
  const existing = id ? { promise: api("/restrictions/" + id) } : null;
  const show = r => {
    const dialog = document.getElementById("access-dialog");
    dialog.innerHTML = `<h3>${r ? "Edit restriction #" + r.id : "New restriction"}</h3>
      <label>Name</label><input id="rs-name" value="${esc(r ? r.name : "")}">
      <label>Starts at</label><input id="rs-start" type="datetime-local"
        value="${r && r.startsAt ? toLocalInput(new Date(r.startsAt))
                                 : toLocalInput(new Date())}">
      <label>Ends at <span class="muted">(empty = no end)</span></label>
      <input id="rs-end" type="datetime-local"
        value="${r && r.endsAt ? toLocalInput(new Date(r.endsAt)) : ""}">
      <label class="inline"><input id="rs-global" type="checkbox"
        ${r && r.isGlobal ? "checked" : ""}>
        global <span class="muted">(grants every user every chip)</span></label>
      <div class="row" style="margin-top:1rem">
        <button class="primary" onclick="saveRestriction(${r ? r.id : "null"})">
          ${r ? "Save" : "Create"}</button>
        <button class="ghost" onclick="this.closest('dialog').close()">Cancel</button>
      </div>`;
    dialog.showModal();
  };
  if (existing) existing.promise.then(show).catch(e => toast(e.message, true));
  else show(null);
}
async function saveRestriction(id) {
  const end = document.getElementById("rs-end").value;
  const body = {
    name: document.getElementById("rs-name").value,
    startsAt: fromLocalInput(document.getElementById("rs-start").value),
    endsAt: end ? fromLocalInput(end) : null,
    isGlobal: document.getElementById("rs-global").checked };
  try {
    if (id) await api("/restrictions/" + id, { method: "PUT", json: body });
    else await api("/restrictions", { json: body });
    document.getElementById("access-dialog").close(); loadAccess();
  } catch (e) { toast(e.message, true); }
}
async function deleteRestriction(id) {
  try { await api("/restrictions/" + id, { method: "DELETE" }); loadAccess(); }
  catch (e) { toast(e.message, true); }
}
