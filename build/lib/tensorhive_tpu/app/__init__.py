"""Web application: static SPA + its server (reference: tensorhive/app/web/)."""
