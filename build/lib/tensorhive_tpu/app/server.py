"""Static web-app server.

Reference: tensorhive/app/web/AppServer.py (89 LoC) — a Flask static server
with an embedded gunicorn, catch-all route → index.html, and the API URL
injected into ``dist/static/config.json`` at boot (:44-68). Here: a
werkzeug-served static dir on a daemon thread (the SPA is a single
self-contained page — no gunicorn worker pool needed for a file server),
with ``/config.json`` generated per-request so the API location always
matches the live config.
"""
from __future__ import annotations

import json
import logging
import mimetypes
import threading
from pathlib import Path
from typing import Optional

from werkzeug.serving import make_server
from werkzeug.wrappers import Request, Response

from ..config import Config, get_config

log = logging.getLogger(__name__)

STATIC_DIR = Path(__file__).parent / "static"


class AppServer:
    def __init__(self, config: Optional[Config] = None) -> None:
        self.config = config or get_config()
        self._server = None
        self._thread: Optional[threading.Thread] = None

    # -- wsgi --------------------------------------------------------------
    def wsgi_app(self, environ, start_response):
        request = Request(environ)
        response = self._serve(request.path)
        return response(environ, start_response)

    def _serve(self, path: str) -> Response:
        if path == "/config.json":
            api = self.config.api
            payload = {"apiUrl": f"{api.url_schema}://{{host}}:{api.url_port}/{api.url_prefix}"}
            return Response(json.dumps(payload), content_type="application/json")
        name = path.lstrip("/") or "index.html"
        target = (STATIC_DIR / name).resolve()
        if not target.is_relative_to(STATIC_DIR.resolve()) or not target.is_file():
            target = STATIC_DIR / "index.html"  # SPA catch-all
        content_type = mimetypes.guess_type(str(target))[0] or "application/octet-stream"
        return Response(target.read_bytes(), content_type=content_type)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        cfg = self.config.app_server
        self._server = make_server(cfg.host, cfg.port, self.wsgi_app, threaded=True)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="app-server"
        )
        self._thread.start()
        log.info("web app on %s:%d", cfg.host, self._server.server_port)
        return self._server.server_port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
