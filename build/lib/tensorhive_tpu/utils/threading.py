"""Stoppable service threads and a read/write-locked snapshot store.

Reference: tensorhive/core/utils/StoppableThread.py:8-32 provides a bare
``do_run`` loop with a shutdown flag. The reference shares its infrastructure
dict across threads *without* locks and relies on ``deepcopy`` on the read
path (tensorhive/controllers/nodes.py:15, flagged in SURVEY.md §3.5/§7 as an
implicit concurrency contract to re-implement deliberately). Here the loop
supports interruptible sleeps and the shared state gets an explicit RW lock.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional


class StoppableThread(threading.Thread):
    """Thread running ``do_run()`` repeatedly until ``shutdown()`` is called.

    Unlike the reference (a plain ``while not stopped: do_run()`` loop with
    blocking ``gevent.sleep``, MonitoringService.py:48-54), sleeping goes
    through an :class:`threading.Event` so ``shutdown()`` interrupts a sleep
    immediately instead of waiting out the interval.
    """

    def __init__(self, name: Optional[str] = None) -> None:
        super().__init__(name=name, daemon=True)
        self._stop_event = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> None:  # pragma: no cover - exercised via services tests
        while not self._stop_event.is_set():
            self.do_run()

    def do_run(self) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        self._stop_event.set()

    @property
    def stopped(self) -> bool:
        return self._stop_event.is_set()

    def wait(self, seconds: float) -> bool:
        """Sleep up to ``seconds``; returns True if shutdown was requested."""
        return self._stop_event.wait(seconds)


class RWLock:
    """Writer-preferring readers/writer lock for shared in-memory state."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    class _Guard:
        def __init__(self, acquire: Callable[[], None], release: Callable[[], None]):
            self._acquire, self._release = acquire, release

        def __enter__(self):
            self._acquire()
            return self

        def __exit__(self, *exc):
            self._release()
            return False

    def read(self) -> "RWLock._Guard":
        return self._Guard(self.acquire_read, self.release_read)

    def write(self) -> "RWLock._Guard":
        return self._Guard(self.acquire_write, self.release_write)
