"""UTC-centric time helpers (reference: tensorhive/core/utils/time.py:5-9).

All persisted timestamps are timezone-naive UTC datetimes, matching the
reference's convention (Reservation start/end stored UTC, models/Reservation.py).
"""
from __future__ import annotations

from datetime import datetime, timedelta, timezone
from typing import Optional, Union

# ISO-8601 with 'T' separator; seconds precision is enough for reservations.
_FORMATS = (
    "%Y-%m-%dT%H:%M:%S.%f",
    "%Y-%m-%dT%H:%M:%S",
    "%Y-%m-%dT%H:%M",
    "%Y-%m-%d %H:%M:%S.%f",
    "%Y-%m-%d %H:%M:%S",
)


def utcnow() -> datetime:
    """Naive UTC now (single source of truth for the whole framework)."""
    return datetime.now(timezone.utc).replace(tzinfo=None)


def to_utc_naive(dt: datetime) -> datetime:
    """Normalize any datetime to naive UTC."""
    if dt.tzinfo is not None:
        dt = dt.astimezone(timezone.utc).replace(tzinfo=None)
    return dt


def parse_datetime(value: Union[str, datetime, None]) -> Optional[datetime]:
    """Parse ISO-ish strings (incl. trailing 'Z') into naive UTC datetimes."""
    if value is None or isinstance(value, datetime):
        return to_utc_naive(value) if isinstance(value, datetime) else None
    text = value.strip()
    try:
        # handles naive and offset-aware ISO forms, incl. trailing 'Z' and
        # negative offsets like '-05:00'
        return to_utc_naive(datetime.fromisoformat(text.replace("Z", "+00:00")))
    except ValueError:
        pass
    for fmt in _FORMATS:
        try:
            return datetime.strptime(text, fmt)
        except ValueError:
            continue
    # ValidationError (a ValueError subclass) so API inputs map to 422
    from .exceptions import ValidationError

    raise ValidationError(f"unparseable datetime: {value!r}")


def iso_utc(dt: datetime) -> str:
    """Canonical naive-UTC ISO text for SQL comparison parameters — matches
    exactly how Column.to_sql stores datetimes."""
    return to_utc_naive(dt).isoformat()


def isoformat(dt: Optional[datetime]) -> Optional[str]:
    """Serialize naive-UTC datetime to API form with trailing Z."""
    if dt is None:
        return None
    return dt.replace(microsecond=0).isoformat() + "Z"


def overlaps(a_start: datetime, a_end: datetime, b_start: datetime, b_end: datetime) -> bool:
    """Half-open interval overlap test used by reservation conflict checks
    (reference: tensorhive/models/Reservation.py:120-131)."""
    return a_start < b_end and b_start < a_end


def minutes_between(a: datetime, b: datetime) -> float:
    return (b - a) / timedelta(minutes=1)
