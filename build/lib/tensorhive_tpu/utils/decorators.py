"""Memoization and timing decorators (reference: tensorhive/core/utils/decorators.py).

The reference memoizes on ``str(args)`` (decorators.py:26-53) which silently
collides for distinct objects with equal reprs; here the cache is keyed on the
hashable argument tuple and is explicitly clearable (needed by tests and by
transport reconnects).
"""
from __future__ import annotations

import functools
import logging
import time
from typing import Any, Callable, Dict, Tuple, TypeVar

F = TypeVar("F", bound=Callable[..., Any])
log = logging.getLogger(__name__)


def memoize(fn: F) -> F:
    """Cache results per hashable ``(args, kwargs)``; exposes ``cache_clear``."""
    cache: Dict[Tuple, Any] = {}

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        key = (args, tuple(sorted(kwargs.items())))
        if key not in cache:
            cache[key] = fn(*args, **kwargs)
        return cache[key]

    wrapper.cache = cache  # type: ignore[attr-defined]
    wrapper.cache_clear = cache.clear  # type: ignore[attr-defined]
    return wrapper  # type: ignore[return-value]


def timeit(fn: F) -> F:
    """Debug-log wall time of a call (reference: decorators.py:14-23)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        start = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            log.debug("%s took %.4fs", fn.__qualname__, time.perf_counter() - start)

    return wrapper  # type: ignore[return-value]
