"""Framework exception hierarchy (reference: tensorhive/core/utils/exceptions.py)."""


class TpuHiveError(Exception):
    """Base class for all framework errors."""


class ConfigurationError(TpuHiveError):
    """Raised when a config file or section is invalid/unreadable."""


class TransportError(TpuHiveError):
    """Raised when a remote-execution transport fails (connect/exec)."""


class SpawnError(TransportError):
    """Raised when spawning a detached task process fails."""


class ValidationError(TpuHiveError, ValueError):
    """Raised by entity ``check_assertions`` hooks before persisting
    (reference: tensorhive/models/CRUDModel.py:21 save-time validation)."""


class NotFoundError(TpuHiveError, LookupError):
    """Raised when an entity id does not exist."""


class ForbiddenError(TpuHiveError):
    """Raised when the acting user lacks permission for an operation."""


class ConflictError(TpuHiveError):
    """Raised on uniqueness/overlap conflicts (e.g. reservation overlap,
    reference: tensorhive/models/Reservation.py:120-131 would_interfere)."""


class TelemetryError(TpuHiveError):
    """Raised when the native telemetry collector fails."""
