"""Small shared utilities (reference: tensorhive/core/utils/)."""
