"""Schedule controller (reference: tensorhive/controllers/schedule.py, 135
LoC): RestrictionSchedule CRUD. Editing or deleting a schedule changes the
effective windows of every restriction it is attached to, so both paths
re-verify affected users' reservations (reference schedule.py:97-98, :125)."""
from __future__ import annotations

from ..api import schemas as S
from ..api.app import RequestContext, route
from ..api.schema import arr, obj, s
from ..core import verifier
from ..db.models.schedule import RestrictionSchedule
from ..db.models.user import User


_get_or_404 = RestrictionSchedule.get  # raises NotFoundError (→ 404) itself


def _reverify_attached(schedule: RestrictionSchedule) -> None:
    users = {}
    needs_all = False
    for restriction in schedule.restrictions:
        if restriction.is_global:
            needs_all = True
            break
        for user in restriction.users:
            users.setdefault(user.id, user)
        for group in restriction.groups:
            for user in group.users:
                users.setdefault(user.id, user)
    affected = User.all() if needs_all else users.values()
    for user in affected:
        verifier.reverify_user(user)


@route("/schedules", ["GET"], summary="List schedules", tag="schedules",
       responses={200: arr(S.SCHEDULE)})
def list_schedules(context: RequestContext):
    return [s.as_dict() for s in RestrictionSchedule.all()]


@route("/schedules/<int:schedule_id>", ["GET"], summary="Get one schedule",
       tag="schedules", responses={200: S.SCHEDULE})
def get_schedule(context: RequestContext, schedule_id: int):
    return _get_or_404(schedule_id).as_dict()


@route("/schedules", ["POST"], auth="admin", summary="Create a schedule",
       tag="schedules",
       body=obj(required=["scheduleDays", "hourStart", "hourEnd"],
                scheduleDays=s("string", minLength=1,
                               description="weekday mask, e.g. '12345'"),
                hourStart=s("string", example="08:00"),
                hourEnd=s("string", example="20:00")),
       responses={201: S.SCHEDULE})
def create_schedule(context: RequestContext):
    data = context.json()  # required fields enforced by the route schema
    schedule = RestrictionSchedule(
        schedule_days=data["scheduleDays"],
        hour_start=data["hourStart"],
        hour_end=data["hourEnd"],
    ).save()
    return schedule.as_dict(), 201


@route("/schedules/<int:schedule_id>", ["PUT"], auth="admin",
       summary="Update a schedule", tag="schedules",
       body=obj(scheduleDays=s("string", minLength=1),
                hourStart=s("string"), hourEnd=s("string")),
       responses={200: S.SCHEDULE})
def update_schedule(context: RequestContext, schedule_id: int):
    schedule = _get_or_404(schedule_id)
    data = context.json()
    if "scheduleDays" in data:
        schedule.schedule_days = data["scheduleDays"]
    if "hourStart" in data:
        schedule.hour_start = data["hourStart"]
    if "hourEnd" in data:
        schedule.hour_end = data["hourEnd"]
    schedule.save()
    _reverify_attached(schedule)
    return schedule.as_dict()


@route("/schedules/<int:schedule_id>", ["DELETE"], auth="admin",
       summary="Delete a schedule", tag="schedules", responses={200: S.MSG})
def delete_schedule(context: RequestContext, schedule_id: int):
    schedule = _get_or_404(schedule_id)
    # collect the attached restrictions BEFORE the row (and its links) go away
    attached = schedule.restrictions
    schedule.destroy()
    for restriction in attached:
        users = {u.id: u for u in restriction.users}
        for group in restriction.groups:
            for user in group.users:
                users.setdefault(user.id, user)
        affected = User.all() if restriction.is_global else users.values()
        for user in affected:
            verifier.reverify_user(user)
    return {"msg": "schedule deleted"}
