"""Group controller (reference: tensorhive/controllers/group.py, 175 LoC):
CRUD + member add/remove + the ``is_default`` flag that auto-attaches new
users."""
from __future__ import annotations

from ..api import schemas as S
from ..api.app import RequestContext, route
from ..api.schema import arr, obj, s
from ..db.models.user import Group, User
from ..utils.exceptions import ValidationError


_get_or_404 = Group.get  # Model.get raises NotFoundError (→ 404) itself


@route("/groups", ["GET"], summary="List groups", tag="groups",
       responses={200: arr(S.GROUP)})
def list_groups(context: RequestContext):
    return [group.as_dict() for group in Group.all()]


@route("/groups/<int:group_id>", ["GET"], summary="Get one group", tag="groups",
       responses={200: S.GROUP})
def get_group(context: RequestContext, group_id: int):
    return _get_or_404(group_id).as_dict()


@route("/groups", ["POST"], auth="admin", summary="Create a group", tag="groups",
       body=obj(required=["name"], name=s("string", minLength=1),
                isDefault=s("boolean")),
       responses={201: S.GROUP})
def create_group(context: RequestContext):
    data = context.json()  # required fields enforced by the route schema
    if Group.first_by(name=data["name"]) is not None:
        raise ValidationError(f"group {data['name']!r} already exists")
    group = Group(name=data["name"], is_default=bool(data.get("isDefault"))).save()
    return group.as_dict(), 201


@route("/groups/<int:group_id>", ["PUT"], auth="admin", summary="Update a group",
       tag="groups",
       body=obj(name=s("string", minLength=1), isDefault=s("boolean")),
       responses={200: S.GROUP})
def update_group(context: RequestContext, group_id: int):
    group = _get_or_404(group_id)
    data = context.json()
    if "name" in data:
        group.name = data["name"]
    if "isDefault" in data:
        group.is_default = bool(data["isDefault"])
    group.save()
    return group.as_dict()


@route("/groups/<int:group_id>", ["DELETE"], auth="admin", summary="Delete a group",
       tag="groups", responses={200: S.MSG})
def delete_group(context: RequestContext, group_id: int):
    _get_or_404(group_id).destroy()
    return {"msg": "group deleted"}


@route("/groups/<int:group_id>/users/<int:user_id>", ["PUT"], auth="admin",
       summary="Add a user to a group", tag="groups", responses={200: S.GROUP})
def add_member(context: RequestContext, group_id: int, user_id: int):
    group = _get_or_404(group_id)
    group.add_user(User.get(user_id))
    return group.as_dict()


@route("/groups/<int:group_id>/users/<int:user_id>", ["DELETE"], auth="admin",
       summary="Remove a user from a group", tag="groups", responses={200: S.GROUP})
def remove_member(context: RequestContext, group_id: int, user_id: int):
    group = _get_or_404(group_id)
    group.remove_user(User.get(user_id))
    return group.as_dict()
