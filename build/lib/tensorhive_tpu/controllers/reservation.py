"""Reservation controller.

Reference: tensorhive/controllers/reservation.py (188 LoC): list/filter by
resource uids + time range, create with a ReservationVerifier permission
check (reservation.py:93-96), update with a field whitelist (owner/admin
only), delete (owners may only delete future reservations; admins any).
"""
from __future__ import annotations

from ..api import schemas as S
from ..api.app import RequestContext, route
from ..api.schema import arr, obj, s
from ..core import verifier
from ..db.models.reservation import Reservation
from ..utils.exceptions import ForbiddenError, ValidationError
from ..utils.timeutils import parse_datetime, utcnow


_get_or_404 = Reservation.get  # raises NotFoundError (→ 404) itself


@route("/reservations", ["GET"], summary="List reservations (filterable)",
       tag="reservations", responses={200: arr(S.RESERVATION)},
       query={"resources_ids": s("string", description="comma-separated chip uids"),
              "start": s("string", format="date-time"),
              "end": s("string", format="date-time")})
def list_reservations(context: RequestContext):
    """Query params: ``resources_ids`` (comma-separated uids), ``start``,
    ``end`` (ISO datetimes) — reference filter_by_uuids_and_time_range."""
    args = context.request.args
    uids = [u for u in (args.get("resources_ids") or "").split(",") if u]
    start = parse_datetime(args["start"]) if "start" in args else None
    end = parse_datetime(args["end"]) if "end" in args else None
    reservations = Reservation.filter_by_uids_and_time_range(uids or None, start, end)
    return [r.as_dict() for r in reservations]


@route("/reservations/<int:reservation_id>", ["GET"], summary="Get one reservation",
       tag="reservations", responses={200: S.RESERVATION})
def get_reservation(context: RequestContext, reservation_id: int):
    return _get_or_404(reservation_id).as_dict()


@route("/reservations", ["POST"], summary="Create a reservation", tag="reservations",
       body=obj(required=["title", "resourceId", "start", "end"],
                title=s("string", minLength=1),
                description=s("string"),
                resourceId=s("string"),
                start=s("string", format="date-time"),
                end=s("string", format="date-time")),
       responses={201: S.RESERVATION})
def create_reservation(context: RequestContext):
    data = context.json()  # required fields enforced by the route schema
    user = context.current_user()
    reservation = Reservation(
        title=data["title"],
        description=data.get("description", ""),
        resource_id=data["resourceId"],
        user_id=user.id,
        start=parse_datetime(data["start"]),
        end=parse_datetime(data["end"]),
    )
    if not verifier.is_reservation_allowed(user, reservation):
        raise ForbiddenError(
            "no active restriction grants you this resource for that window"
        )
    reservation.save()  # overlap check runs inside save (would_interfere)
    return reservation.as_dict(), 201


#: fields an owner/admin may change after creation (reference whitelist,
#: controllers/reservation.py update)
_MUTABLE = ("title", "description", "start", "end")


@route("/reservations/<int:reservation_id>", ["PUT"], summary="Update a reservation",
       tag="reservations",
       body=obj(title=s("string", minLength=1), description=s("string"),
                start=s("string", format="date-time"),
                end=s("string", format="date-time")),
       responses={200: S.RESERVATION})
def update_reservation(context: RequestContext, reservation_id: int):
    reservation = _get_or_404(reservation_id)
    if not context.is_admin and reservation.user_id != context.user_id:
        raise ForbiddenError("only the owner or an admin may modify a reservation")
    data = context.json()
    unknown = set(data) - set(_MUTABLE)
    if unknown:
        raise ValidationError(f"immutable or unknown fields: {sorted(unknown)}")
    if "title" in data:
        reservation.title = data["title"]
    if "description" in data:
        reservation.description = data["description"]
    if "start" in data:
        reservation.start = parse_datetime(data["start"])
    if "end" in data:
        reservation.end = parse_datetime(data["end"])
    if not context.is_admin:
        user = context.current_user()
        if not verifier.is_reservation_allowed(user, reservation):
            raise ForbiddenError("your permissions do not cover the new window")
    reservation.save()
    return reservation.as_dict()


@route("/reservations/<int:reservation_id>", ["DELETE"], summary="Delete a reservation",
       tag="reservations", responses={200: S.MSG})
def delete_reservation(context: RequestContext, reservation_id: int):
    reservation = _get_or_404(reservation_id)
    if not context.is_admin:
        if reservation.user_id != context.user_id:
            raise ForbiddenError("only the owner or an admin may delete a reservation")
        if reservation.start <= utcnow():
            # owners may only delete future reservations (reference rule)
            raise ForbiddenError("cannot delete a reservation that already started")
    reservation.destroy()
    return {"msg": "reservation deleted"}
