"""Restriction controller.

Reference: tensorhive/controllers/restriction.py (478 LoC) — CRUD plus
apply/remove against users, groups, resources, whole hostnames, and
schedules; **every permission mutation re-verifies the affected users'
reservations** (restriction.py:139,164,184,214,244,275,306,335 all call
ReservationVerifier.update_user_reservations_statuses).
"""
from __future__ import annotations

from typing import Iterable, List

from ..api import schemas as S
from ..api.app import RequestContext, route
from ..api.schema import arr, obj, s
from ..core import verifier
from ..db.models.resource import Resource
from ..db.models.restriction import Restriction
from ..db.models.schedule import RestrictionSchedule
from ..db.models.user import Group, User
from ..utils.exceptions import NotFoundError
from ..utils.timeutils import parse_datetime


def _get_or_404(restriction_id: int) -> Restriction:
    return Restriction.get(restriction_id)


def _reverify(users: Iterable[User], increased: bool) -> None:
    for user in users:
        verifier.update_user_reservations_statuses(user, increased)


def _reverify_both(users: Iterable[User]) -> None:
    """One sweep per user covering both grant and revoke directions (window
    edits can do either)."""
    for user in users:
        verifier.reverify_user(user)


def _affected_users(restriction: Restriction) -> List[User]:
    users = {user.id: user for user in restriction.users}
    for group in restriction.groups:
        for user in group.users:
            users.setdefault(user.id, user)
    return list(users.values())


@route("/restrictions", ["GET"], summary="List restrictions", tag="restrictions",
       responses={200: arr(S.RESTRICTION)})
def list_restrictions(context: RequestContext):
    return [r.as_dict() for r in Restriction.all()]


@route("/restrictions/<int:restriction_id>", ["GET"], summary="Get one restriction",
       tag="restrictions", responses={200: S.RESTRICTION})
def get_restriction(context: RequestContext, restriction_id: int):
    return _get_or_404(restriction_id).as_dict()


@route("/restrictions", ["POST"], auth="admin", summary="Create a restriction",
       tag="restrictions",
       body=obj(required=["name", "startsAt"],
                name=s("string", minLength=1),
                startsAt=s("string", format="date-time"),
                endsAt=s("string", format="date-time", nullable=True),
                isGlobal=s("boolean")),
       responses={201: S.RESTRICTION})
def create_restriction(context: RequestContext):
    data = context.json()  # required fields enforced by the route schema
    restriction = Restriction(
        name=data["name"],
        starts_at=parse_datetime(data["startsAt"]),
        ends_at=parse_datetime(data["endsAt"]) if data.get("endsAt") else None,
        is_global=bool(data.get("isGlobal")),
    ).save()
    if restriction.is_global:
        _reverify(User.all(), increased=True)
    return restriction.as_dict(), 201


@route("/restrictions/<int:restriction_id>", ["PUT"], auth="admin",
       summary="Update a restriction", tag="restrictions",
       body=obj(name=s("string", minLength=1),
                startsAt=s("string", format="date-time"),
                endsAt=s("string", format="date-time", nullable=True),
                isGlobal=s("boolean")),
       responses={200: S.RESTRICTION})
def update_restriction(context: RequestContext, restriction_id: int):
    restriction = _get_or_404(restriction_id)
    data = context.json()
    if "name" in data:
        restriction.name = data["name"]
    if "startsAt" in data:
        restriction.starts_at = parse_datetime(data["startsAt"])
    if "endsAt" in data:
        restriction.ends_at = parse_datetime(data["endsAt"]) if data["endsAt"] else None
    if "isGlobal" in data:
        restriction.is_global = bool(data["isGlobal"])
    restriction.save()
    # window changes can both grant and revoke
    affected = User.all() if restriction.is_global else _affected_users(restriction)
    _reverify_both(affected)
    return restriction.as_dict()


@route("/restrictions/<int:restriction_id>", ["DELETE"], auth="admin",
       summary="Delete a restriction", tag="restrictions", responses={200: S.MSG})
def delete_restriction(context: RequestContext, restriction_id: int):
    restriction = _get_or_404(restriction_id)
    affected = User.all() if restriction.is_global else _affected_users(restriction)
    restriction.destroy()
    _reverify(affected, increased=False)
    return {"msg": "restriction deleted"}


# -- assignment endpoints ---------------------------------------------------

_user_or_404 = User.get
_group_or_404 = Group.get


def _resource_or_404(uid: str) -> Resource:
    resource = Resource.get_by_uid(uid)
    if resource is None:
        raise NotFoundError(f"resource {uid!r} not found")
    return resource


_schedule_or_404 = RestrictionSchedule.get


@route("/restrictions/<int:restriction_id>/users/<int:user_id>", ["PUT"], auth="admin",
       summary="Apply restriction to a user", tag="restrictions",
       responses={200: S.RESTRICTION})
def apply_to_user(context: RequestContext, restriction_id: int, user_id: int):
    restriction, user = _get_or_404(restriction_id), _user_or_404(user_id)
    restriction.apply_to_user(user)
    _reverify([user], increased=True)
    return restriction.as_dict()


@route("/restrictions/<int:restriction_id>/users/<int:user_id>", ["DELETE"], auth="admin",
       summary="Remove restriction from a user", tag="restrictions",
       responses={200: S.RESTRICTION})
def remove_from_user(context: RequestContext, restriction_id: int, user_id: int):
    restriction, user = _get_or_404(restriction_id), _user_or_404(user_id)
    restriction.remove_from_user(user)
    _reverify([user], increased=False)
    return restriction.as_dict()


@route("/restrictions/<int:restriction_id>/groups/<int:group_id>", ["PUT"], auth="admin",
       summary="Apply restriction to a group", tag="restrictions",
       responses={200: S.RESTRICTION})
def apply_to_group(context: RequestContext, restriction_id: int, group_id: int):
    restriction, group = _get_or_404(restriction_id), _group_or_404(group_id)
    restriction.apply_to_group(group)
    _reverify(group.users, increased=True)
    return restriction.as_dict()


@route("/restrictions/<int:restriction_id>/groups/<int:group_id>", ["DELETE"], auth="admin",
       summary="Remove restriction from a group", tag="restrictions",
       responses={200: S.RESTRICTION})
def remove_from_group(context: RequestContext, restriction_id: int, group_id: int):
    restriction, group = _get_or_404(restriction_id), _group_or_404(group_id)
    restriction.remove_from_group(group)
    _reverify(group.users, increased=False)
    return restriction.as_dict()


@route("/restrictions/<int:restriction_id>/resources/<uid>", ["PUT"], auth="admin",
       summary="Apply restriction to a resource", tag="restrictions",
       responses={200: S.RESTRICTION})
def apply_to_resource(context: RequestContext, restriction_id: int, uid: str):
    restriction, resource = _get_or_404(restriction_id), _resource_or_404(uid)
    restriction.apply_to_resource(resource)
    _reverify(_affected_users(restriction), increased=True)
    return restriction.as_dict()


@route("/restrictions/<int:restriction_id>/resources/<uid>", ["DELETE"], auth="admin",
       summary="Remove restriction from a resource", tag="restrictions",
       responses={200: S.RESTRICTION})
def remove_from_resource(context: RequestContext, restriction_id: int, uid: str):
    restriction, resource = _get_or_404(restriction_id), _resource_or_404(uid)
    restriction.remove_from_resource(resource)
    _reverify(_affected_users(restriction), increased=False)
    return restriction.as_dict()


@route("/restrictions/<int:restriction_id>/hosts/<hostname>", ["PUT"], auth="admin",
       summary="Apply restriction to every chip of a host", tag="restrictions",
       responses={200: S.RESTRICTION})
def apply_to_hostname(context: RequestContext, restriction_id: int, hostname: str):
    restriction = _get_or_404(restriction_id)
    count = restriction.apply_to_resources_by_hostname(hostname)
    if count == 0:
        raise NotFoundError(f"no resources registered for host {hostname!r}")
    _reverify(_affected_users(restriction), increased=True)
    return restriction.as_dict()


@route("/restrictions/<int:restriction_id>/schedules/<int:schedule_id>", ["PUT"],
       auth="admin", summary="Attach a schedule", tag="restrictions",
       responses={200: S.RESTRICTION})
def add_schedule(context: RequestContext, restriction_id: int, schedule_id: int):
    restriction, schedule = _get_or_404(restriction_id), _schedule_or_404(schedule_id)
    restriction.add_schedule(schedule)
    # attaching a schedule narrows the window: permissions decreased
    affected = User.all() if restriction.is_global else _affected_users(restriction)
    _reverify(affected, increased=False)
    return restriction.as_dict()


@route("/restrictions/<int:restriction_id>/schedules/<int:schedule_id>", ["DELETE"],
       auth="admin", summary="Detach a schedule", tag="restrictions",
       responses={200: S.RESTRICTION})
def remove_schedule(context: RequestContext, restriction_id: int, schedule_id: int):
    restriction, schedule = _get_or_404(restriction_id), _schedule_or_404(schedule_id)
    restriction.remove_schedule(schedule)
    affected = User.all() if restriction.is_global else _affected_users(restriction)
    _reverify(affected, increased=True)
    return restriction.as_dict()
