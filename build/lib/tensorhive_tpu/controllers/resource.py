"""Resource controller (reference: tensorhive/controllers/resource.py, 42
LoC): list/get TPU-chip Resource rows, auto-synced from live telemetry
first (resource.py:22-28)."""
from __future__ import annotations

from ..api import schemas as S
from ..api.app import RequestContext, route
from ..api.schema import arr
from ..db.models.resource import Resource
from ..utils.exceptions import NotFoundError
from .nodes import sync_resources_from_infrastructure


@route("/resources", ["GET"], summary="List TPU chip resources", tag="resources",
       responses={200: arr(S.RESOURCE)})
def list_resources(context: RequestContext):
    sync_resources_from_infrastructure()
    return [resource.as_dict() for resource in Resource.all()]


@route("/resources/<uid>", ["GET"], summary="Get one resource by chip uid",
       tag="resources", responses={200: S.RESOURCE})
def get_resource(context: RequestContext, uid: str):
    sync_resources_from_infrastructure()
    resource = Resource.get_by_uid(uid)
    if resource is None:
        raise NotFoundError(f"resource {uid!r} not found")
    return resource.as_dict()
