"""Task controller: CRUD + spawn/terminate/log + status reconciliation.

Reference: tensorhive/controllers/task.py (527 LoC) — the heart is
``synchronize(task_id)`` (:44-94), which reconciles the DB status against
live remote state: a stored ``running`` task whose PID no longer exists
becomes ``terminated``; an unreachable host makes it ``unsynchronized``
(later re-adopted by PID match when the host returns). The
``@synchronize_task_record`` decorator (:97-118) runs it before every
state-dependent operation; ``business_*`` functions are shared with the
scheduler service (job.py:267-310).
"""
from __future__ import annotations

import logging
from typing import Optional

from ..api import schemas as S
from ..api.app import RequestContext, int_arg, route
from ..api.schema import arr, obj, s
from ..core.nursery import Termination, get_ops_factory
from ..db.models.job import Job
from ..db.models.task import CHIP_ENV_VAR, SegmentType, Task, TaskStatus
from ..db.models.user import User
from ..utils.exceptions import (
    ConflictError,
    ForbiddenError,
    SpawnError,
    TransportError,
    ValidationError,
)

log = logging.getLogger(__name__)

_get_or_404 = Task.get  # raises NotFoundError (→ 404) itself


def _task_owner(task: Task) -> User:
    return User.get(Job.get(task.job_id).user_id)


def _assert_owner_or_admin(context: RequestContext, task: Task) -> None:
    job = Job.get(task.job_id)
    if not context.is_admin and job.user_id != context.user_id:
        raise ForbiddenError("only the job owner or an admin may do this")


# -- reconciliation (reference task.py:44-118) ------------------------------

def synchronize(task_id: int) -> Task:
    """Reconcile one task's DB record against live remote state."""
    task = Task.get(task_id)
    if task.status not in (TaskStatus.running, TaskStatus.unsynchronized):
        return task
    owner = _task_owner(task)
    ops = get_ops_factory().ops_for(task.hostname, user=owner.username)
    try:
        alive = ops.running_tasks()
    except TransportError as exc:
        log.warning("cannot synchronize task %d: %s", task_id, exc)
        if task.status is TaskStatus.running:
            task.set_status(TaskStatus.unsynchronized)
        return task
    if task.id in alive:
        # re-adopt (host came back, or daemon restarted while task survived)
        live_pid = alive[task.id]
        if task.pid != live_pid or task.status is not TaskStatus.running:
            task.pid = live_pid
            task.set_status(TaskStatus.running)
    else:
        task.pid = None
        task.set_status(TaskStatus.terminated)
    return task


# -- business operations (shared with the scheduler) ------------------------

def business_spawn(task_id: int) -> Task:
    """Reference task.py:406-441."""
    task = synchronize(task_id)
    if task.status is TaskStatus.running:
        raise ConflictError(f"task {task_id} is already running (pid {task.pid})")
    owner = _task_owner(task)
    ops = get_ops_factory().ops_for(task.hostname, user=owner.username)
    pid = ops.spawn(task.full_command, task.id)
    task.pid = pid
    task.set_status(TaskStatus.running)
    return task


_GRACEFULLY_TO_MODE = {
    True: Termination.interrupt,    # SIGINT: let the training checkpoint
    None: Termination.terminate,    # SIGTERM
    False: Termination.kill,        # SIGKILL
}


def business_terminate(task_id: int, gracefully: Optional[bool] = True) -> Task:
    """Reference task.py:444-489 (gracefully True→SIGINT via ^C, None→screen
    quit, False→kill -9)."""
    task = synchronize(task_id)
    if task.status is not TaskStatus.running or task.pid is None:
        raise ConflictError(f"task {task_id} is not running")
    owner = _task_owner(task)
    ops = get_ops_factory().ops_for(task.hostname, user=owner.username)
    ops.terminate(task.pid, _GRACEFULLY_TO_MODE[gracefully])
    if gracefully is False:
        # SIGKILL is not survivable: record the terminal state immediately
        task.pid = None
        task.set_status(TaskStatus.terminated)
    else:
        # graceful paths let the process wind down; next synchronize()
        # observes the actual exit
        synchronize(task.id)
        task = Task.get(task.id)
    return task


def business_get_log(task_id: int, tail: Optional[int] = None) -> str:
    """Reference task.py:492-523."""
    task = Task.get(task_id)
    owner = _task_owner(task)
    ops = get_ops_factory().ops_for(task.hostname, user=owner.username)
    return ops.fetch_log(task.id, tail=tail)


# -- HTTP endpoints ----------------------------------------------------------

@route("/tasks", ["GET"], summary="List tasks (optionally ?job_id=)", tag="tasks",
       responses={200: arr(S.TASK)}, query={"job_id": s("integer")})
def list_tasks(context: RequestContext):
    # Listing all tasks is admin-only; non-admins may only list tasks of a
    # job they own (fullCommand embeds env-segment values — often secrets).
    # Reference gates per-record reads to owner-or-admin (task.py:141-147).
    job_id = int_arg(context, "job_id")
    if not context.is_admin:
        if job_id is None:
            raise ForbiddenError("only admins may list all tasks; pass ?job_id=")
        job = Job.get(job_id)
        if job.user_id != context.user_id:
            raise ForbiddenError("only the job owner or an admin may list its tasks")
    tasks = Task.filter_by(job_id=job_id) if job_id is not None else Task.all()
    return [task.as_dict() for task in tasks]


@route("/tasks/<int:task_id>", ["GET"], summary="Get one task (synchronized)",
       tag="tasks", responses={200: S.TASK})
def get_task(context: RequestContext, task_id: int):
    _assert_owner_or_admin(context, _get_or_404(task_id))
    return synchronize(task_id).as_dict()


@route("/tasks", ["POST"], summary="Create a task under a job", tag="tasks",
       body=obj(required=["jobId", "hostname", "command"],
                jobId=s("integer"),
                hostname=s("string", minLength=1),
                command=s("string", minLength=1),
                envVariables=arr(obj(required=["name"], name=s("string", minLength=1), value=s("string"))),
                parameters=arr(obj(required=["name"], name=s("string", minLength=1), value=s("string"))),
                chips=arr(s("integer"))),
       responses={201: S.TASK})
def create_task(context: RequestContext):
    data = context.json()  # required fields enforced by the route schema
    job = Job.get(int(data["jobId"]))
    if not context.is_admin and job.user_id != context.user_id:
        raise ForbiddenError("only the job owner or an admin may add tasks")
    task = Task(job_id=job.id, hostname=data["hostname"], command=data["command"]).save()
    for env in data.get("envVariables", []):
        task.add_cmd_segment(env["name"], env.get("value", ""), SegmentType.env_variable)
    for param in data.get("parameters", []):
        task.add_cmd_segment(param["name"], param.get("value", ""), SegmentType.parameter)
    if "chips" in data:
        task.add_cmd_segment(
            CHIP_ENV_VAR,
            ",".join(str(c) for c in data["chips"]),
            SegmentType.env_variable,
        )
    return task.as_dict(), 201


@route("/tasks/<int:task_id>", ["PUT"], summary="Update a task", tag="tasks",
       body=obj(hostname=s("string", minLength=1),
                command=s("string", minLength=1),
                envVariables=arr(obj(required=["name"], name=s("string", minLength=1), value=s("string"))),
                parameters=arr(obj(required=["name"], name=s("string", minLength=1), value=s("string"))),
                removeSegments=arr(s("string"))),
       responses={200: S.TASK})
def update_task(context: RequestContext, task_id: int):
    task = _get_or_404(task_id)
    _assert_owner_or_admin(context, task)
    if task.status is TaskStatus.running:
        raise ConflictError("cannot edit a running task")
    data = context.json()
    if "hostname" in data:
        task.hostname = data["hostname"]
    if "command" in data:
        task.command = data["command"]
    task.save()
    for env in data.get("envVariables", []):
        task.add_cmd_segment(env["name"], env.get("value", ""), SegmentType.env_variable)
    for param in data.get("parameters", []):
        task.add_cmd_segment(param["name"], param.get("value", ""), SegmentType.parameter)
    for name in data.get("removeSegments", []):
        task.remove_cmd_segment(name)
    return task.as_dict()


@route("/tasks/<int:task_id>", ["DELETE"], summary="Delete a task", tag="tasks",
       responses={200: S.MSG})
def delete_task(context: RequestContext, task_id: int):
    task = _get_or_404(task_id)
    _assert_owner_or_admin(context, task)
    task = synchronize(task_id)
    if task.status is TaskStatus.running:
        raise ConflictError("terminate the task before deleting it")
    task.destroy()
    return {"msg": "task deleted"}


@route("/tasks/<int:task_id>/spawn", ["POST"], summary="Spawn the task's process",
       tag="tasks", responses={200: S.TASK})
def spawn(context: RequestContext, task_id: int):
    task = _get_or_404(task_id)
    _assert_owner_or_admin(context, task)
    try:
        return business_spawn(task_id).as_dict()
    except SpawnError as exc:
        raise ConflictError(str(exc))


@route("/tasks/<int:task_id>/terminate", ["POST"], summary="Signal the task's process",
       tag="tasks", body=S.GRACEFULLY_BODY, responses={200: S.TASK})
def terminate(context: RequestContext, task_id: int):
    task = _get_or_404(task_id)
    _assert_owner_or_admin(context, task)
    body = context.json()
    gracefully = body.get("gracefully", True)
    if gracefully not in (True, False, None):
        raise ValidationError("gracefully must be true, false or null")
    return business_terminate(task_id, gracefully).as_dict()


@route("/tasks/<int:task_id>/log", ["GET"], summary="Fetch the task's output log",
       tag="tasks", responses={200: S.TASK_LOG}, query={"tail": s("integer")})
def get_log(context: RequestContext, task_id: int):
    task = _get_or_404(task_id)
    _assert_owner_or_admin(context, task)
    return {"log": business_get_log(task_id, tail=int_arg(context, "tail"))}
