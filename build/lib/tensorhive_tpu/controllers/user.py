"""User controller: CRUD + auth session endpoints.

Reference: tensorhive/controllers/user.py (240 LoC) — CRUD (admin-gated),
login issuing access+refresh JWTs (:182-207), logout blacklisting by jti
(:207-230), refresh (:233-240), and ``ssh_signup`` which authenticates a
signup by proving SSH access to the first configured node with the manager's
key (:99-117); ``authorized_keys_entry`` returns the public key users must
install (:120-123).
"""
from __future__ import annotations

import logging

from ..api import jwt as jwt_module
from ..api import schemas as S
from ..api.app import RequestContext, route
from ..api.schema import arr, obj, s
from ..db.models.user import Group, User
from ..utils.exceptions import ForbiddenError, ValidationError
from ..utils.timeutils import utcnow

log = logging.getLogger(__name__)


def _attach_default_groups(user: User) -> None:
    for group in Group.get_default_groups():
        group.add_user(user)


_get_or_404 = User.get  # Model.get raises NotFoundError (→ 404) itself


# -- CRUD -------------------------------------------------------------------

@route("/users", ["GET"], auth="admin", summary="List all users", tag="users",
       responses={200: arr(S.USER)})
def list_users(context: RequestContext):
    return [user.as_dict() for user in User.all()]


@route("/users/<int:user_id>", ["GET"], summary="Get one user", tag="users",
       responses={200: S.USER})
def get_user(context: RequestContext, user_id: int):
    if not context.is_admin and context.user_id != user_id:
        raise ForbiddenError("only admins may view other accounts")
    return _get_or_404(user_id).as_dict()


@route("/users", ["POST"], auth="admin", summary="Create a user", tag="users",
       body=S.CREATE_USER_BODY, responses={201: S.USER})
def create_user(context: RequestContext):
    data = context.json()  # required fields enforced by the route schema
    if User.find_by_username(data["username"]) is not None:
        raise ValidationError(f"username {data['username']!r} already taken")
    user = User(
        username=data["username"], email=data["email"], password=data["password"]
    ).save()
    user.add_role("user")
    if data.get("admin"):
        user.add_role("admin")
    _attach_default_groups(user)
    return user.as_dict(), 201


@route("/users/<int:user_id>", ["PUT"], summary="Update a user", tag="users",
       body=S.UPDATE_USER_BODY, responses={200: S.USER})
def update_user(context: RequestContext, user_id: int):
    if not context.is_admin and context.user_id != user_id:
        raise ForbiddenError("only admins may modify other accounts")
    user = _get_or_404(user_id)
    data = context.json()
    # field whitelist; role changes are admin-only
    if "email" in data:
        user.email = data["email"]
    if "password" in data:
        user.password = data["password"]
    if "roles" in data:
        if not context.is_admin:
            raise ForbiddenError("only admins may change roles")
        desired = set(data["roles"])
        for name in desired - set(user.roles):
            user.add_role(name)
        for name in set(user.roles) - desired:
            user.remove_role(name)
    user.save()
    return user.as_dict()


@route("/users/<int:user_id>", ["DELETE"], auth="admin", summary="Delete a user",
       tag="users", responses={200: S.MSG})
def delete_user(context: RequestContext, user_id: int):
    _get_or_404(user_id).destroy()
    return {"msg": "user deleted"}


# -- session ---------------------------------------------------------------

@route("/user/login", ["POST"], auth=None, summary="Log in, returns JWT pair",
       tag="auth", body=S.LOGIN_BODY, responses={200: S.TOKEN_PAIR})
def login(context: RequestContext):
    data = context.json()  # required fields enforced by the route schema
    user = User.find_by_username(data["username"])
    if user is None or not user.check_password(data["password"]):
        raise jwt_module.AuthError("invalid credentials")
    user.last_login_at = utcnow()
    user.save()
    return {
        "user": user.as_dict(),
        "accessToken": jwt_module.create_access_token(user.id, user.roles),
        "refreshToken": jwt_module.create_refresh_token(user.id),
    }


@route("/user/logout", ["POST"], auth="logout",
       summary="Revoke the presented access token", tag="auth",
       responses={200: S.MSG})
def logout(context: RequestContext):
    # _authenticate already signature-verified the token (auth="logout")
    jwt_module.revoke_claims(context.claims)
    return {"msg": "access token revoked"}


@route("/user/logout/refresh", ["POST"], auth="logout-refresh",
       summary="Revoke the presented refresh token", tag="auth",
       responses={200: S.MSG})
def logout_refresh(context: RequestContext):
    jwt_module.revoke_claims(context.claims)
    return {"msg": "refresh token revoked"}


@route("/user/refresh", ["POST"], auth="refresh",
       summary="Mint a new access token from a refresh token", tag="auth",
       responses={200: obj(required=["accessToken"], accessToken=s("string"))})
def refresh(context: RequestContext):
    user = context.current_user()
    return {"accessToken": jwt_module.create_access_token(user.id, user.roles)}


# -- ssh signup (reference user.py:99-123) ----------------------------------

@route("/user/ssh_signup", ["POST"], auth=None,
       summary="Sign up by proving SSH access to a managed host", tag="auth",
       body=S.SIGNUP_BODY, responses={201: S.USER})
def ssh_signup(context: RequestContext):
    """The reference verifies the claimed unix account by connecting to the
    first configured node as that user with the manager's key — same here,
    over the transport layer."""
    from ..config import get_config
    from ..core.transport.base import get_transport_manager

    data = context.json()  # required fields enforced by the route schema
    config = get_config()
    if not config.hosts:
        raise ValidationError("no managed hosts configured; signup unavailable")
    if User.find_by_username(data["username"]) is not None:
        raise ValidationError(f"username {data['username']!r} already taken")
    first_host = next(iter(config.hosts))
    transport = get_transport_manager().for_host(first_host, user=data["username"])
    if not transport.test():
        raise ForbiddenError(
            f"could not authenticate as {data['username']!r} on {first_host}; "
            "install the manager key (GET /user/authorized_keys_entry) first"
        )
    user = User(
        username=data["username"], email=data["email"], password=data["password"]
    ).save()
    user.add_role("user")
    _attach_default_groups(user)
    return user.as_dict(), 201


@route("/user/authorized_keys_entry", ["GET"], auth=None,
       summary="Manager public key for ~/.ssh/authorized_keys", tag="auth",
       responses={200: obj(required=["authorizedKeysEntry"],
                           authorizedKeysEntry=s("string"))})
def authorized_keys_entry(context: RequestContext):
    from ..config import get_config
    from ..core.transport.ssh import generate_keypair

    pubkey = generate_keypair(get_config().ssh_key_path)
    return {"authorizedKeysEntry": pubkey}
