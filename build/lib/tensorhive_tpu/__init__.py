"""tensorhive_tpu — a TPU-native cluster resource-management framework.

A from-scratch rebuild of the capabilities of TensorHive (reference:
kivicode/TensorHive-Fixed) with TPUs as the first-class managed resource:

* calendar-based exclusive reservations of TPU chips/slices with conflict
  detection (reference: tensorhive/models/Reservation.py),
* live infrastructure monitoring streaming per-chip HBM / duty-cycle metrics
  (reference: tensorhive/core/monitors/GPUMonitor.py — rebuilt on a native
  telemetry collector instead of ``nvidia-smi`` parsing),
* reservation-violation protection: warn on PTYs, e-mail, or kill intruding
  processes (reference: tensorhive/core/services/ProtectionService.py),
* a job-execution module spawning multi-process distributed training jobs on
  remote hosts (reference: tensorhive/core/task_nursery.py) with
  ``jax.distributed`` / torch-xla / TF_CONFIG launch templates,
* a REST API + JWT auth + CLI, and
* a JAX/pallas compute stack (``models``, ``ops``, ``parallel``) providing the
  flagship workloads (transformer pretraining) that the job module launches
  onto reserved slices.

Unlike the reference (pure Python + nvidia-smi over SSH), the hot telemetry
path binds a C++ collector, and the compute stack is built TPU-first: SPMD via
``jax.sharding.Mesh`` + ``jax.jit``, sequence parallelism via ring attention
over ``shard_map``, bfloat16 matmuls for the MXU.
"""

__version__ = "0.1.0"
