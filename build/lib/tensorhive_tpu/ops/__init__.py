"""TPU compute kernels (pallas) with portable XLA fallbacks."""
from .flash_attention import flash_attention, reference_attention

__all__ = ["flash_attention", "reference_attention"]
