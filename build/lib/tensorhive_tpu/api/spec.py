"""OpenAPI document generation + API index page.

Reference: tensorhive/api/api_specification.yml (3793 lines, 44 paths / 66
operationIds) bound by RestyResolver; swagger UI served at ``/{prefix}/ui/``.
Here the document is generated from the live route registry, so it can never
drift from the implementation; it is served at ``/{prefix}/openapi.json``
with a minimal self-contained HTML explorer at ``/{prefix}/ui/`` (no CDN
assets — managed clusters are often airgapped).
"""
from __future__ import annotations

import json
import re
from typing import Dict, List

from werkzeug.routing import Rule
from werkzeug.wrappers import Request, Response

from .. import __version__

_PATH_PARAM_RE = re.compile(r"<(?:(?P<conv>[^:<>]+):)?(?P<name>[^<>]+)>")


def _openapi_path(path: str) -> str:
    return _PATH_PARAM_RE.sub(lambda m: "{%s}" % m.group("name"), path)


def _path_params(path: str) -> List[Dict]:
    params = []
    for match in _PATH_PARAM_RE.finditer(path):
        conv = match.group("conv") or "string"
        params.append({
            "name": match.group("name"),
            "in": "path",
            "required": True,
            "schema": {"type": "integer" if conv == "int" else "string"},
        })
    return params


def build_openapi(url_prefix: str, endpoints: Dict[str, "Endpoint"]) -> Dict:  # noqa: F821
    from .schema import components

    paths: Dict[str, Dict] = {}
    for ep in endpoints.values():
        item = paths.setdefault(_openapi_path(ep.path), {})
        for method in ep.methods:
            if method == "OPTIONS":
                continue
            responses: Dict[str, Dict] = {}
            for status, schema in (ep.responses or {200: None}).items():
                entry: Dict = {"description": "success" if status < 400 else "error"}
                if schema is not None:
                    entry["content"] = {"application/json": {"schema": schema}}
                responses[str(status)] = entry
            operation = {
                "summary": ep.summary or "",
                "tags": [ep.tag],
                "responses": responses,
            }
            if ep.body is not None and method in ("POST", "PUT", "PATCH"):
                operation["requestBody"] = {
                    "required": True,
                    "content": {"application/json": {"schema": ep.body}},
                }
                operation["responses"].setdefault(
                    "422", {"description": "request body failed schema validation"}
                )
            if ep.auth is not None:
                operation["security"] = [{"bearerAuth": []}]
                operation["responses"]["401"] = {"description": "unauthorized"}
            if ep.auth == "admin":
                operation["responses"]["403"] = {"description": "admin role required"}
            params = _path_params(ep.path)
            for name, schema in (ep.query or {}).items():
                params.append({
                    "name": name, "in": "query", "required": False, "schema": schema,
                })
            if params:
                operation["parameters"] = params
            item[method.lower()] = operation
    return {
        "openapi": "3.0.3",
        "info": {"title": "tpuhive API", "version": __version__},
        "servers": [{"url": f"/{url_prefix}" if url_prefix else "/"}],
        "components": {
            "securitySchemes": {
                "bearerAuth": {"type": "http", "scheme": "bearer", "bearerFormat": "JWT"}
            },
            "schemas": components(),
        },
        "paths": paths,
    }


def spec_rules(url_prefix: str, endpoints: Dict[str, "Endpoint"]) -> List[Rule]:  # noqa: F821
    prefix = f"/{url_prefix}" if url_prefix else ""

    def serve_spec(request: Request) -> Response:
        doc = build_openapi(url_prefix, endpoints)
        return Response(json.dumps(doc, indent=1), content_type="application/json")

    def serve_ui(request: Request) -> Response:
        doc = build_openapi(url_prefix, endpoints)
        rows = []
        for path, item in sorted(doc["paths"].items()):
            for method, op in item.items():
                auth = "🔒" if op.get("security") else ""
                rows.append(
                    f"<tr><td><code>{method.upper()}</code></td>"
                    f"<td><code>{path}</code></td><td>{op['summary']}</td>"
                    f"<td>{auth}</td></tr>"
                )
        html = _UI_TEMPLATE.format(version=doc["info"]["version"], rows="\n".join(rows))
        return Response(html, content_type="text/html")

    return [
        Rule(f"{prefix}/openapi.json", methods=["GET"], endpoint=serve_spec),
        Rule(f"{prefix}/ui/", methods=["GET"], endpoint=serve_ui),
    ]


_UI_TEMPLATE = """<!doctype html>
<html><head><meta charset="utf-8"><title>tpuhive API</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem auto; max-width: 60rem; }}
 table {{ border-collapse: collapse; width: 100%; }}
 td, th {{ border-bottom: 1px solid #ddd; padding: .4rem .6rem; text-align: left; }}
 code {{ background: #f4f4f4; padding: .1rem .3rem; border-radius: 3px; }}
</style></head>
<body><h1>tpuhive API <small>v{version}</small></h1>
<p>Machine-readable spec: <a href="../openapi.json"><code>openapi.json</code></a></p>
<table><tr><th>Method</th><th>Path</th><th>Summary</th><th>Auth</th></tr>
{rows}
</table></body></html>
"""
