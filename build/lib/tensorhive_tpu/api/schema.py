"""JSON-Schema subset: component registry + server-side validator.

Reference: tensorhive/api/api_specification.yml declares full request/response
JSON schemas for every operation and Connexion enforces them server-side
(``strict_validation=True``, api/APIServer.py:31-44). The rebuild keeps the
schemas next to the routes (no YAML/implementation drift) and validates with
this ~150-line interpreter of the OpenAPI-3.0 schema subset the API actually
uses:

    type (object/array/string/integer/number/boolean), nullable, enum,
    properties / required / additionalProperties, items, minLength,
    maxLength, minimum, maximum, format (annotation only), $ref into
    #/components/schemas/.

Anything outside the subset is rejected at registration time, so the emitted
OpenAPI document is always enforceable — a schema the validator can't check
never ships in the spec.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..utils.exceptions import ValidationError

# -- component registry ------------------------------------------------------

_COMPONENTS: Dict[str, Dict] = {}

_ALLOWED_KEYS = {
    "type", "nullable", "enum", "properties", "required", "additionalProperties",
    "items", "minLength", "maxLength", "minimum", "maximum", "format",
    "description", "example", "$ref", "default",
}
_ALLOWED_TYPES = {"object", "array", "string", "integer", "number", "boolean"}


def _check_schema(schema: Dict, where: str) -> None:
    """Registration-time lint: only the enforceable subset may appear."""
    if not isinstance(schema, dict):
        raise TypeError(f"{where}: schema must be a dict, got {type(schema).__name__}")
    unknown = set(schema) - _ALLOWED_KEYS
    if unknown:
        raise TypeError(f"{where}: unsupported schema keys {sorted(unknown)}")
    if "$ref" in schema:
        ref = schema["$ref"]
        prefix = "#/components/schemas/"
        if not ref.startswith(prefix):
            raise TypeError(f"{where}: $ref must target {prefix}")
        return
    stype = schema.get("type")
    if stype is not None and stype not in _ALLOWED_TYPES:
        raise TypeError(f"{where}: unsupported type {stype!r}")
    for name, sub in (schema.get("properties") or {}).items():
        _check_schema(sub, f"{where}.{name}")
    if "items" in schema:
        _check_schema(schema["items"], f"{where}[]")
    extra = schema.get("additionalProperties")
    if isinstance(extra, dict):
        _check_schema(extra, f"{where}.*")


def component(name: str, schema: Dict) -> Dict:
    """Register a named schema; returns the ``$ref`` to embed elsewhere."""
    _check_schema(schema, name)
    _COMPONENTS[name] = schema
    return {"$ref": f"#/components/schemas/{name}"}


def components() -> Dict[str, Dict]:
    return dict(_COMPONENTS)


def resolve(schema: Dict) -> Dict:
    ref = schema.get("$ref")
    if ref is None:
        return schema
    name = ref.rsplit("/", 1)[-1]
    try:
        return _COMPONENTS[name]
    except KeyError:
        raise TypeError(f"unknown schema component {name!r}")


# -- validation --------------------------------------------------------------

def _type_ok(value: Any, stype: str) -> bool:
    if stype == "object":
        return isinstance(value, dict)
    if stype == "array":
        return isinstance(value, list)
    if stype == "string":
        return isinstance(value, str)
    if stype == "boolean":
        return isinstance(value, bool)
    if stype == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if stype == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    return True


def validate(value: Any, schema: Dict, path: str = "body") -> None:
    """Raise ValidationError (→ HTTP 422) with a precise path on mismatch."""
    schema = resolve(schema)
    if value is None:
        if schema.get("nullable"):
            return
        raise ValidationError(f"{path}: must not be null")
    stype = schema.get("type")
    if stype and not _type_ok(value, stype):
        raise ValidationError(f"{path}: expected {stype}, got {type(value).__name__}")
    if "enum" in schema and value not in schema["enum"]:
        raise ValidationError(f"{path}: must be one of {schema['enum']}")
    if stype == "string":
        if "minLength" in schema and len(value) < schema["minLength"]:
            raise ValidationError(f"{path}: shorter than {schema['minLength']} characters")
        if "maxLength" in schema and len(value) > schema["maxLength"]:
            raise ValidationError(f"{path}: longer than {schema['maxLength']} characters")
    if stype in ("integer", "number"):
        if "minimum" in schema and value < schema["minimum"]:
            raise ValidationError(f"{path}: below minimum {schema['minimum']}")
        if "maximum" in schema and value > schema["maximum"]:
            raise ValidationError(f"{path}: above maximum {schema['maximum']}")
    if stype == "object":
        props = schema.get("properties") or {}
        for name in schema.get("required", ()):
            if name not in value:
                raise ValidationError(f"{path}: missing required field {name!r}")
        extra = schema.get("additionalProperties", True)
        for name, item in value.items():
            if name in props:
                validate(item, props[name], f"{path}.{name}")
            elif extra is False:
                raise ValidationError(f"{path}: unknown field {name!r}")
            elif isinstance(extra, dict):
                validate(item, extra, f"{path}.{name}")
    if stype == "array":
        items = schema.get("items")
        if items is not None:
            for i, item in enumerate(value):
                validate(item, items, f"{path}[{i}]")


# -- tiny builder helpers (keep route declarations readable) -----------------

def obj(required: Optional[List[str]] = None, extra: bool = False, **props: Dict) -> Dict:
    """Object schema; fields are keyword args, ``required`` lists names,
    ``extra`` allows undeclared fields (default: strict)."""
    out: Dict[str, Any] = {"type": "object", "properties": props,
                           "additionalProperties": extra}
    if required:
        out["required"] = list(required)
    return out


def arr(items: Dict) -> Dict:
    return {"type": "array", "items": items}


def s(stype: str, **kw: Any) -> Dict:
    return {"type": stype, **kw}
