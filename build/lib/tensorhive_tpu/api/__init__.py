"""REST API layer (reference: tensorhive/api/ + tensorhive/authorization.py)."""
