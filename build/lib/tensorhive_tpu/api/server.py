"""Threaded WSGI server for the API.

Reference: tensorhive/api/APIServer.py:17-44 — Connexion on a gevent backend,
blocking the main thread (cli.py:143 ``api_server.run_forever()``). Here a
stdlib-threaded werkzeug server: requests are short DB/dict reads, the GIL is
released during sqlite and socket IO, and the monitoring fan-out lives on its
own threads, so thread-per-request is plenty for a control-plane API.
"""
from __future__ import annotations

import logging
from typing import Optional

from werkzeug.serving import make_server

from ..config import Config, get_config
from .app import ApiApp

log = logging.getLogger(__name__)


class APIServer:
    def __init__(self, config: Optional[Config] = None) -> None:
        self.config = config or get_config()
        self.app = ApiApp(url_prefix=self.config.api.url_prefix)
        self._server = None

    def start(self):
        """Bind and serve on a background thread; returns the bound port."""
        import threading

        cfg = self.config.api
        self._server = make_server(cfg.url_hostname, cfg.url_port, self.app, threaded=True)
        thread = threading.Thread(target=self._server.serve_forever, daemon=True,
                                  name="api-server")
        thread.start()
        log.info("API listening on %s:%d/%s", cfg.url_hostname,
                 self._server.server_port, cfg.url_prefix)
        return self._server.server_port

    def run_forever(self) -> None:
        """Blocking variant for the CLI main path (reference run_forever)."""
        cfg = self.config.api
        self._server = make_server(cfg.url_hostname, cfg.url_port, self.app, threaded=True)
        log.info("API listening on %s:%d/%s", cfg.url_hostname,
                 self._server.server_port, cfg.url_prefix)
        try:
            self._server.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self._server.shutdown()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
