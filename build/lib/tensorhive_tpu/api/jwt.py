"""Stdlib HS256 JWT implementation.

Reference: Flask-JWT-Extended usage in tensorhive/authorization.py:15-33
(blacklist loader + roles claim loader) and controllers/user.py:182-240
(login issues access+refresh tokens, logout blacklists each by jti). The
dependency-free rebuild keeps the same token semantics: HS256-signed
access/refresh pairs carrying ``sub`` (user id), ``roles``, ``jti`` (for the
RevokedToken blacklist), ``type``, ``iat``/``exp``.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import uuid
from typing import Any, Dict, Optional

from ..config import get_config
from ..db.models.token import RevokedToken
from ..utils.exceptions import TpuHiveError


class AuthError(TpuHiveError):
    """Invalid/expired/revoked token or malformed credentials (→ HTTP 401)."""


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(data: str) -> bytes:
    padding = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + padding)


def _secret() -> bytes:
    secret = get_config().api.secret_key
    if not secret:
        raise AuthError("api.secret_key is not configured")
    return secret.encode()


def encode(claims: Dict[str, Any]) -> str:
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}, separators=(",", ":")).encode())
    payload = _b64url(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = f"{header}.{payload}".encode()
    signature = _b64url(hmac.new(_secret(), signing_input, hashlib.sha256).digest())
    return f"{header}.{payload}.{signature}"


def decode(
    token: str,
    expected_type: Optional[str] = "access",
    verify_active: bool = True,
) -> Dict[str, Any]:
    """Verify signature (+ expiry + blacklist unless ``verify_active=False``);
    returns the claims dict."""
    try:
        header_b64, payload_b64, signature_b64 = token.split(".")
    except ValueError:
        raise AuthError("malformed token")
    signing_input = f"{header_b64}.{payload_b64}".encode()
    expected = hmac.new(_secret(), signing_input, hashlib.sha256).digest()
    try:
        provided = _b64url_decode(signature_b64)
    except (ValueError, TypeError):
        raise AuthError("malformed token signature")
    if not hmac.compare_digest(expected, provided):
        raise AuthError("invalid token signature")
    try:
        claims = json.loads(_b64url_decode(payload_b64))
    except (ValueError, TypeError):
        raise AuthError("malformed token payload")
    if verify_active:
        if claims.get("exp") is not None and time.time() >= claims["exp"]:
            raise AuthError("token expired")
    if expected_type is not None and claims.get("type") != expected_type:
        raise AuthError(f"wrong token type (expected {expected_type})")
    if verify_active:
        jti = claims.get("jti")
        if jti and RevokedToken.is_jti_blacklisted(jti):
            raise AuthError("token revoked")
    return claims


def create_access_token(user_id: int, roles: list) -> str:
    cfg = get_config().api
    now = time.time()
    return encode({
        "sub": user_id,
        "roles": roles,
        "type": "access",
        "jti": uuid.uuid4().hex,
        "iat": int(now),
        "exp": int(now + cfg.access_token_minutes * 60),
    })


def create_refresh_token(user_id: int) -> str:
    cfg = get_config().api
    now = time.time()
    return encode({
        "sub": user_id,
        "type": "refresh",
        "jti": uuid.uuid4().hex,
        "iat": int(now),
        "exp": int(now + cfg.refresh_token_days * 86400),
    })


def revoke_claims(claims: Dict[str, Any]) -> None:
    """Blacklist an already-verified token by jti (reference logout,
    controllers/user.py:207-230). Idempotent: RevokedToken.add atomically
    no-ops on an already-blacklisted jti, so a repeated POST /user/logout
    (or logout racing expiry) is not a 401 — the logout auth mode verifies
    the signature only (``decode(verify_active=False)``)."""
    jti = claims.get("jti")
    if jti:
        RevokedToken.add(jti)
