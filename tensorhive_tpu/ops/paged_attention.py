"""Fused paged-attention decode kernel: K/V read THROUGH the page table.

PR 7's paged KV cache decoupled serving capacity from context length but
paid for it on the hot path: ``models/decode._paged_attend`` materializes a
contiguous ``[slots, max_pages * page_size, Hkv, Dh]`` copy of every slot's
pages via ``k_pages[page_table]`` on EVERY decode step — on CPU a measured
0.88× tokens/s vs the contiguous layout, and on TPU roughly double the
decode HBM traffic in a regime docs/PERF.md documents as bandwidth-bound.
This kernel deletes the gathered intermediate: the grid walks each slot's
page-table row and streams K/V pages **directly from their physical
locations**, accumulating the attended output with an online softmax.

Mechanics (the idiom of ``ops/flash_attention.py``, adapted to paging):

* **Grid (slots, max_pages_per_slot)**, pages innermost. The page table and
  per-slot positions ride in as **scalar-prefetch operands**
  (``pltpu.PrefetchScalarGridSpec``) so the K/V BlockSpec index maps can
  resolve *logical page j of slot s* to its **physical** page
  ``page_table[s, j]`` before the kernel body runs — the gather IS the
  read, no intermediate buffer ever exists. Both stay traced operands of
  the enclosing jit, so page assignment never recompiles (the same
  discipline as the XLA gather path).
* **Online softmax per page block** in f32 VMEM scratch (running max +
  denominator, exactly ``_fwd_kernel``'s recurrence), finalized once on the
  last page. Per-page masking compares each logical offset
  ``j * page_size + k`` against the slot's position, so trash-page entries
  at logical positions > position contribute exactly 0 by exp-underflow —
  the identical masking argument the gather path relies on.
* **Early exit past the live window**: compute is gated on
  ``j * page_size <= position`` (``pl.when``), and the K/V index map clamps
  ``j`` to the slot's last live page, so blocks past ``position //
  page_size`` re-select the block already resident in VMEM — the pipeline
  issues **no DMA** for them (pallas only fetches when the mapped block
  index changes). Trash-page entries are never even read.
* **GQA-native reads at ``kv_heads`` width**: query heads are grouped
  ``head i -> kv head i // group`` (the training expand's convention) and
  K/V blocks are read unexpanded — the per-kv-head 2D dots keep the MXU on
  ``[group, page_size]`` tiles with no expanded copy, mirroring the
  ``b // group`` index maps of the flash kernels.

Int8 pages (``kv_quant = on`` — docs/SERVING.md "Quantized KV pages"):
the same grid and index maps run over one-byte K/V blocks, with the
per-(page, kv_head) f32 scales riding as two extra scalar-prefetch
operands and each block dequantized in VMEM right after its DMA — the
page's HBM read is the int8 payload, so decode bandwidth drops with the
footprint. ``resolve_paged_kernel``'s ``auto`` keeps the XLA gather under
quantization (interpret-mode correct, on-TPU unbenched); ``on`` forces
the int8 kernel.

Numerics: the online-softmax recurrence rescales partial sums by
``exp(m_old - m_new)`` where the gather path subtracts one global max — the
same math at different accumulation order, so kernel output is within a few
ULP of the gather path (~1e-7 absolute in f32) but NOT bit-identical; the
greedy token stream is unaffected (pinned exactly by the parity tests) and
docs/SERVING.md records the tolerance rationale. Non-TPU backends run the
kernel in interpret mode (CPU tests) or fall back to the XLA gather, chosen
by :func:`resolve_paged_kernel` — the ``[generation_service] paged_kernel``
knob's ``auto`` mode.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

#: per-program VMEM budget, ``RESIDENT_KV_MAX_BYTES``-style: one program
#: holds q + out + one K/V page + the f32 accumulator/stat scratch. Decode
#: pages are tiny (a 16-position page at Hkv=8, Dh=128 in bf16 is 32 KiB),
#: so this only gates pathological page_size/d_head combinations out of
#: ``auto`` — the knob's ``on`` stays an explicit operator override.
PAGED_KERNEL_MAX_BYTES = 4 * 1024 * 1024


def kernel_fits(page_size: int, kv_heads: int, d_head: int, heads: int,
                dtype) -> bool:
    """True when one grid program's working set fits the VMEM budget —
    ``default_blocks``-style sizing, except paging fixes the block shape
    (one physical page) so the heuristic gates dispatch instead of picking
    a block size."""
    itemsize = jnp.dtype(dtype).itemsize
    kv_page = 2 * page_size * kv_heads * d_head * itemsize
    q_out = 2 * heads * d_head * itemsize
    scratch = (heads * d_head + 2 * heads * 128) * 4      # f32 acc + m/l
    return kv_page + q_out + scratch <= PAGED_KERNEL_MAX_BYTES


def resolve_paged_kernel(mode: str, *, page_size: int, kv_heads: int,
                         d_head: int, heads: int, dtype,
                         mesh_devices: int = 1, quant: bool = False) -> str:
    """Resolve the ``[generation_service] paged_kernel`` knob to the
    dispatch actually used: ``"pallas"`` or ``"xla"``.

    ``on`` forces the kernel (interpret mode off-TPU — the CPU test/smoke
    path); ``off`` forces the XLA gather reference; ``auto`` uses the
    kernel on a real TPU when the working set fits VMEM and the gather
    path everywhere else — mirroring how ``use_flash`` keeps the XLA
    reference attention as the portable fallback.

    ``mesh_devices`` is the serving mesh size: ``auto`` stays on the XLA
    gather when the engine is sharded (GSPMD partitions the gather path
    with the cache's NamedSharding for free; handing it the pallas custom
    call instead is correct — the mesh parity tests pin it token-identical
    under ``on`` — but its multi-chip TPU performance is unbenched, so
    auto does not pick it sight unseen; docs/SERVING.md "Multi-chip
    serving"). ``quant`` (``kv_quant = on``) follows the same policy: the
    int8 kernel is pinned correct in interpret mode but its on-TPU
    performance is unbenched, so ``auto`` keeps the XLA gather and ``on``
    remains the explicit operator override."""
    if mode not in ("auto", "on", "off"):
        raise ValueError(
            f"paged_kernel must be auto|on|off, got {mode!r}")
    if mode == "on":
        return "pallas"
    if mode == "off":
        return "xla"
    if (jax.default_backend() == "tpu" and mesh_devices == 1
            and not quant
            and kernel_fits(page_size, kv_heads, d_head, heads, dtype)):
        return "pallas"
    return "xla"


def _decode_kernel(*refs, page_size: int, kv_heads: int,
                   quant: bool = False):
    """Grid (slots, pages), pages innermost. Blocks: q/out [1, H, Dh] per
    slot; k/v [1, page_size, Hkv, Dh] — ONE physical page, selected by the
    index map through the prefetched page table. Scratch (f32): acc
    [H, Dh], m/l [H, 128] (lane-replicated row stats, the flash layout).

    ``quant`` (``kv_quant = on``): K/V blocks are int8 and two extra
    scalar-prefetch operands carry the per-(page, kv_head) f32 scales —
    the block is dequantized here in VMEM right after its DMA, so the
    page's HBM read is the one-byte payload (docs/SERVING.md "Quantized
    KV pages")."""
    if quant:
        (page_table_ref, positions_ref, k_scale_ref, v_scale_ref,
         q_ref, k_ref, v_ref, out_ref, acc_ref, m_ref, l_ref) = refs
    else:
        (page_table_ref, positions_ref, q_ref, k_ref, v_ref,
         out_ref, acc_ref, m_ref, l_ref) = refs
    slot = pl.program_id(0)
    page = pl.program_id(1)
    last_page = pl.num_programs(1) - 1
    position = positions_ref[slot]

    @pl.when(page == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # pages past position // page_size hold nothing visible (and their
    # block index was clamped, so nothing was fetched): skip the compute
    @pl.when(page * page_size <= position)
    def _compute():
        q = q_ref[0]                                    # [H, Dh]
        heads, d_head = q.shape
        group = heads // kv_heads
        scale = d_head ** -0.5
        logical = page * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        visible = logical <= position                   # [1, page_size]
        if quant:
            # the resident block is the page the index map CLAMPED to —
            # recompute its physical id the same way so the right scale
            # row dequantizes it
            live = jnp.maximum(position, 0) // page_size
            phys = page_table_ref[slot, jnp.minimum(page, live)]

            def kv_head(ref, scale_ref, h):
                block = ref[0, :, h, :]                 # [page_size, Dh]
                return block.astype(jnp.float32) * scale_ref[phys, h]
        else:
            def kv_head(ref, scale_ref, h):
                return ref[0, :, h, :]
        # per-kv-head 2D dots (kv_heads is static, the loop unrolls): input
        # dtype on the MXU, f32 accumulation — _online_softmax_block's rule
        scores = jnp.concatenate([
            jnp.dot(q[h * group:(h + 1) * group],
                    kv_head(k_ref, k_scale_ref if quant else None, h).T,
                    preferred_element_type=jnp.float32)
            for h in range(kv_heads)], axis=0) * scale  # [H, page_size]
        scores = jnp.where(visible, scores, NEG_INF)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        block_max = jnp.max(scores, axis=-1)
        # this block always contains a visible key (the gate above), so
        # new_max is finite from the first update and masked scores
        # contribute exp(NEG_INF - finite) == 0 by underflow — no re-mask
        new_max = jnp.maximum(m_prev, block_max)
        correction = jnp.exp(m_prev - new_max)
        probs = jnp.exp(scores - new_max[:, None])      # [H, page_size] f32
        v_dtype = jnp.float32 if quant else v_ref.dtype
        acc_ref[...] = acc_ref[...] * correction[:, None] + jnp.concatenate([
            jnp.dot(probs[h * group:(h + 1) * group].astype(v_dtype),
                    kv_head(v_ref, v_scale_ref if quant else None, h),
                    preferred_element_type=jnp.float32)
            for h in range(kv_heads)], axis=0)
        row_sum = l_prev * correction + jnp.sum(probs, axis=-1)
        m_ref[...] = jnp.broadcast_to(new_max[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(row_sum[:, None], l_ref.shape)

    @pl.when(page == last_page)
    def _finalize():
        row_sum = l_ref[:, 0]
        denom = jnp.where(row_sum == 0.0, 1.0, row_sum)
        out_ref[0] = (acc_ref[...] / denom[:, None]).astype(out_ref.dtype)


def paged_attention(
    q: jax.Array,               # [S, 1, H, Dh]
    k_pages: jax.Array,         # [num_physical, page_size, Hkv, Dh]
    v_pages: jax.Array,
    page_table: jax.Array,      # [S, max_pages_per_slot] int32
    positions: jax.Array,       # [S] int32 — attend to logical <= position
    interpret: Optional[bool] = None,
    k_scales: Optional[jax.Array] = None,   # [num_physical, Hkv] f32
    v_scales: Optional[jax.Array] = None,
) -> jax.Array:
    """Paged decode attention with zero gathered intermediate: the attended
    output of :func:`~tensorhive_tpu.models.decode._paged_attend`'s gather
    path, computed by streaming each slot's pages from their physical
    locations. ``page_table``/``positions`` are values, never shapes —
    callers inside a jit keep the zero-recompile contract.

    ``k_scales``/``v_scales`` switch the kernel to its int8 variant
    (``kv_quant = on``): K/V pages arrive as one-byte payloads and the
    scales ride as two extra scalar-prefetch operands, dequantized
    per-page in VMEM after the DMA — the decode read's HBM traffic is the
    int8 bytes, not a widened copy."""
    from jax.experimental.pallas import tpu as pltpu

    num_slots, _, heads, d_head = q.shape
    page_size, kv_heads = k_pages.shape[1], k_pages.shape[2]
    max_pages = page_table.shape[1]
    quant = k_scales is not None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def q_map(slot, page, *prefetched):
        return (slot, 0, 0)

    def kv_map(slot, page, table, positions, *scales):
        # clamp to the slot's last live page: blocks past the boundary
        # re-select the resident block, so the pipeline fetches nothing
        # for them (pallas only issues a DMA when the index changes) —
        # trash-page entries are never read, not merely masked
        live = jnp.maximum(positions[slot], 0) // page_size
        return (table[slot, jnp.minimum(page, live)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4 if quant else 2,
        grid=(num_slots, max_pages),
        in_specs=[
            pl.BlockSpec((1, heads, d_head), q_map),
            pl.BlockSpec((1, page_size, kv_heads, d_head), kv_map),
            pl.BlockSpec((1, page_size, kv_heads, d_head), kv_map),
        ],
        out_specs=pl.BlockSpec((1, heads, d_head), q_map),
        scratch_shapes=[
            pltpu.VMEM((heads, d_head), jnp.float32),
            pltpu.VMEM((heads, 128), jnp.float32),
            pltpu.VMEM((heads, 128), jnp.float32),
        ],
    )
    operands = [page_table.astype(jnp.int32), positions.astype(jnp.int32)]
    if quant:
        operands += [k_scales.astype(jnp.float32),
                     v_scales.astype(jnp.float32)]
    out = pl.pallas_call(
        functools.partial(_decode_kernel, page_size=page_size,
                          kv_heads=kv_heads, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_slots, heads, d_head), q.dtype),
        interpret=interpret,
    )(*operands, q[:, 0], k_pages, v_pages)
    return out[:, None]
