"""Int8 KV-page quantization: the arithmetic behind ``kv_quant = on``.

The KV cache is the dominant HBM consumer of the serving data plane (PR 7
paging, PR 11 prefix sharing and PR 13 speculation all multiply *sequences
per chip*, but every cached cell is still ``config.dtype``). This module
quantizes paged K/V to **int8 with one f32 scale per (physical page,
kv_head)**, so the same HBM holds strictly more pages — the scale
side-arrays ride in the cache pytree (``models/decode.QuantKVCache``),
indexed by the SAME physical page ids the page tables resolve, and shard
like their pages under a serving mesh (docs/SERVING.md "Quantized KV
pages").

Quantization scheme, in the order the constraints forced it:

* **Symmetric int8, scale = amax / 127 per (page, kv_head).** One scale
  per page keeps the side-array tiny (``2 * kv_heads * 4`` bytes per page
  against ``2 * page_size * kv_heads * d_head`` payload bytes) and lets
  the fused pallas kernel dequantize a whole page in VMEM right after its
  DMA — the page is the DMA unit, so the scale granularity matches the
  bandwidth granularity.
* **Running-max scales, rescale-on-write.** A page fills incrementally
  (decode writes one position per step), so its amax is not known up
  front. Every write takes ``new_scale = max(old_scale, amax(written) /
  127)``: the scale only ever grows, and when it grows the page's already-
  stored values are dequantized and requantized onto the new grid. When
  the scale does NOT grow, requantization is exactly idempotent
  (``round(q * s / s) == q``), so untouched bytes never drift — the only
  error a rescale adds is the coarser grid any per-page scheme would have
  needed anyway.
* **Offset-0 writes reset the running max.** Freed pages go back to the
  pool with their scale rows untouched (scrubbing them would cost a
  device dispatch per release); inheriting a stale scale would make a
  recycled page quantize coarser than a fresh one — history leaking into
  values. A page's offset-0 cell is written exactly when a new ownership
  life begins (sequential decode entering the page, a prefill/COW chunk
  restarting at the page boundary) or when a catch-up window rewrites the
  page's whole live prefix, so any write touching offset 0 REBASES the
  running max at zero: recycled pages behave byte-identically to fresh
  ones, which is what pins slot-recycle ≡ fresh-engine token identity
  under quantization.
* **Dequantize-on-read, everywhere.** Attention always consumes
  ``dequant(stored)``: the XLA gather path dequantizes the gathered page
  run, the pallas kernel dequantizes per page in VMEM (scales ride as
  scalar-prefetch operands, so int8 K/V also HALVES vs bf16 — quarters
  vs f32 — the decode step's HBM read), and the chunk-prefill/speculative
  window passes attend the requantized merge below. A prefix-cache hit
  therefore reads byte-for-byte what the original writer stored, which is
  what pins hit ≡ miss token identity under quantization.
* **Writes never touch pages they do not own.** :func:`row_merge`
  scatters back only pages an in-window write actually landed on
  (everything else drops) — a chunk that starts past the shared-prefix
  boundary cannot requantize a shared page, so the PR 11 COW rule holds
  bit-for-bit under quantization.

Scales are values, never shapes: every array here is a traced operand of
the enclosing jit, so page assignment and scale updates keep the
zero-recompile contract (the ``serving_paged_*_q`` fingerprints).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

#: symmetric int8 grid: stored values live in [-127, 127] (no -128, so
#: negation round-trips and the grid is symmetric around 0)
INT8_MAX = 127.0
#: scale floor — an all-zero page quantizes/dequantizes exactly instead of
#: dividing by zero
SCALE_FLOOR = 1e-8


def resolve_kv_quant(mode: str, paged: bool) -> str:
    """Resolve the ``[generation_service] kv_quant = auto|on|off`` knob at
    engine construction (the ``paged_kernel``/``speculative`` pattern):
    ``auto`` = on for the paged layout (pages are the quantization unit —
    the int8 capacity story IS the default serving story), off for the
    contiguous rollback layout; ``on`` requires paging."""
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"kv_quant must be auto|on|off, got {mode!r}")
    if mode == "on" and not paged:
        raise ValueError(
            "kv_quant=on needs the paged cache layout (the page is the "
            "quantization/scale unit); set paged=true or kv_quant=auto/off")
    return "on" if paged and mode != "off" else "off"


# -- byte accounting (per layer, per page) ------------------------------------

def page_bytes(page_size: int, kv_heads: int, d_head: int,
               itemsize: int) -> int:
    """HBM bytes one layer of one unquantized page costs (K + V)."""
    return 2 * page_size * kv_heads * d_head * int(itemsize)


def quant_page_bytes(page_size: int, kv_heads: int, d_head: int) -> int:
    """HBM bytes one layer of one int8 page costs: K + V payload at one
    byte per cell, plus the two f32 scale rows ([kv_heads] each)."""
    return 2 * page_size * kv_heads * d_head + 2 * kv_heads * 4


# -- write primitives ---------------------------------------------------------

def _requant(values, scales):
    """Snap ``values`` onto the int8 grid of ``scales`` (broadcast-ready)."""
    q = jnp.round(values / scales)
    return jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8)


def step_write(pages_i8: jax.Array, scales: jax.Array, page_ids: jax.Array,
               offsets: jax.Array, values: jax.Array
               ) -> Tuple[jax.Array, jax.Array]:
    """Quantize-on-write for the decode step: one position per slot.

    ``pages_i8`` [P, ps, Hkv, Dh] int8, ``scales`` [P, Hkv] f32 (ONE
    layer's pages + scale row), ``page_ids``/``offsets`` [S], ``values``
    [S, Hkv, Dh]. Each touched page is dequantized, the new position
    inserted, the running-max scale updated, and the WHOLE page
    requantized and scattered back — out-of-range ``page_ids`` (the
    speculative draft's past-limit routing) drop. Duplicate page ids only
    ever name the trash page (parked slots), where any winner is garbage
    by construction."""
    num_slots = page_ids.shape[0]
    slot = jnp.arange(num_slots)
    cur_q = pages_i8[page_ids]                          # [S, ps, Hkv, Dh]
    cur_s = scales[page_ids]                            # [S, Hkv]
    vals = values.astype(jnp.float32)
    deq = cur_q.astype(jnp.float32) * cur_s[:, None, :, None]
    deq = deq.at[slot, offsets].set(vals)
    # offset-0 writes begin a page's ownership life: rebase the running
    # max so a recycled page cannot inherit its previous owner's scale
    base_s = jnp.where((offsets == 0)[:, None], 0.0, cur_s)
    new_s = jnp.maximum(base_s, jnp.maximum(
        jnp.max(jnp.abs(vals), axis=-1) / INT8_MAX, SCALE_FLOOR))
    q = _requant(deq, new_s[:, None, :, None])
    pages_i8 = pages_i8.at[page_ids].set(q, mode="drop")
    scales = scales.at[page_ids].set(new_s, mode="drop")
    return pages_i8, scales


def row_merge(pages_i8: jax.Array, scales: jax.Array, rows: jax.Array,
              values: jax.Array, logical_pos: jax.Array, valid: jax.Array,
              dtype) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Quantize-on-write for a window of positions through page-table rows
    (chunked prefill, the speculative verify/propose windows).

    ``rows`` [B, mp] physical page ids (one slot's row, or the whole step
    table); ``values`` [B, W, Hkv, Dh]; ``logical_pos`` [B, W] pre-clipped
    logical positions; ``valid`` [B, W] masks cells that must not write
    (padding, past-limit). Returns ``(pages_i8, scales, ctx)`` where
    ``ctx`` [B, mp * ps, Hkv, Dh] is the post-write DEQUANTIZED logical
    context — exactly ``dequant(stored)``, including this window's own
    freshly-requantized cells, so the attend sees what any later reader
    will read (the hit ≡ miss identity argument).

    Only pages a valid write landed on are scattered back (the rest
    drop): shared prefix pages and other slots' pages are untouchable by
    construction, preserving the COW rule under quantization."""
    num_physical, ps = pages_i8.shape[0], pages_i8.shape[1]
    hkv, dh = pages_i8.shape[2], pages_i8.shape[3]
    num_rows, mp = rows.shape
    b_idx = jnp.arange(num_rows)[:, None]
    row_q = pages_i8[rows]                              # [B, mp, ps, Hkv, Dh]
    row_s = scales[rows]                                # [B, mp, Hkv]
    deq = row_q.astype(jnp.float32) * row_s[:, :, None, :, None]
    flat = deq.reshape(num_rows, mp * ps, hkv, dh)
    vals = values.astype(jnp.float32)
    write_idx = jnp.where(valid, logical_pos, mp * ps)  # OOB -> dropped
    flat = flat.at[b_idx, write_idx].set(vals, mode="drop")
    page_idx = jnp.where(valid, logical_pos // ps, mp)  # OOB -> dropped
    v_amax = jnp.max(jnp.abs(vals), axis=-1)            # [B, W, Hkv]
    amax_upd = jnp.zeros((num_rows, mp, hkv), jnp.float32).at[
        b_idx, page_idx].max(v_amax, mode="drop")
    touched = jnp.zeros((num_rows, mp), jnp.int32).at[
        b_idx, page_idx].add(valid.astype(jnp.int32), mode="drop") > 0
    # pages whose offset-0 cell this window writes begin (or fully rewrite)
    # an ownership life: rebase their running max at zero — the recycled-
    # page determinism rule of step_write, window-shaped
    reset_idx = jnp.where(valid & (logical_pos % ps == 0), page_idx, mp)
    reset = jnp.zeros((num_rows, mp), jnp.int32).at[
        b_idx, reset_idx].add(1, mode="drop") > 0
    base_s = jnp.where(reset[..., None], 0.0, row_s)
    new_s = jnp.maximum(base_s, jnp.maximum(amax_upd / INT8_MAX,
                                            SCALE_FLOOR))
    merged = flat.reshape(num_rows, mp, ps, hkv, dh)
    q_new = _requant(merged, new_s[:, :, None, :, None])
    write_rows = jnp.where(touched, rows, num_physical)  # OOB -> dropped
    pages_i8 = pages_i8.at[write_rows].set(q_new, mode="drop")
    scales = scales.at[write_rows].set(new_s, mode="drop")
    requant = q_new.astype(jnp.float32) * new_s[:, :, None, :, None]
    ctx_pages = jnp.where(touched[:, :, None, None, None], requant, deq)
    ctx = ctx_pages.reshape(num_rows, mp * ps, hkv, dh).astype(dtype)
    return pages_i8, scales, ctx


# -- tier transitions (docs/SERVING.md "KV-page tiering") ---------------------

def extract_pages(pages_i8: jax.Array, scales: jax.Array,
                  page_ids: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Gather whole pages AND their scale rows for demotion to the host
    tier: ``pages_i8`` [L, P, ps, Hkv, Dh] int8 + ``scales`` [L, P, Hkv]
    f32 over traced ``page_ids`` [W] -> ([L, W, ps, Hkv, Dh],
    [L, W, Hkv]). The scales travel WITH the payload — a page's bytes are
    meaningless without its quantization grid, and a promotion must
    restore both so a host-tier hit dequantizes byte-for-byte what the
    original writer stored (the hit ≡ miss identity, now across tiers).
    Callers pad ``page_ids`` to one fixed width with the trash page and
    discard the padded lanes host-side, so the demote batch size is a
    value, never a shape."""
    return pages_i8[:, page_ids], scales[:, page_ids]


def inject_pages(pages_i8: jax.Array, scales: jax.Array,
                 page_ids: jax.Array, payload: jax.Array,
                 payload_scales: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
    """Scatter promoted page payloads + scale rows back into the device
    cache: the inverse of :func:`extract_pages`, with out-of-range
    ``page_ids`` dropped (``mode="drop"``) so callers pad the promote
    batch to one fixed width with an OOB id — like every other padded
    write in the paged plane, padding must touch no physical page."""
    pages_i8 = pages_i8.at[:, page_ids].set(payload, mode="drop")
    scales = scales.at[:, page_ids].set(payload_scales, mode="drop")
    return pages_i8, scales


# -- read primitive -----------------------------------------------------------

def dequant_gather(pages_i8: jax.Array, scales: jax.Array,
                   page_table: jax.Array, dtype) -> jax.Array:
    """Gather each slot's page run into logical order and dequantize:
    [S, mp] table over [P, ps, Hkv, Dh] int8 pages + [P, Hkv] scales ->
    [S, mp * ps, Hkv, Dh] in the compute dtype — the quantized analog of
    the XLA gather in ``models/decode._paged_attend``."""
    gathered = pages_i8[page_table]                   # [S, mp, ps, Hkv, Dh]
    gathered_s = scales[page_table]                   # [S, mp, Hkv]
    deq = gathered.astype(jnp.float32) * gathered_s[:, :, None, :, None]
    num_slots, mp = page_table.shape
    return deq.reshape(num_slots, mp * pages_i8.shape[1],
                       *pages_i8.shape[2:]).astype(dtype)


# -- quality probe ------------------------------------------------------------

def sim_kv_loss(params, config, tokens: jax.Array, page_size: int,
                quantized: bool = True) -> jax.Array:
    """Teacher-forced mean next-token CE with K/V routed through per-(page,
    kv_head) int8 quantization before attention — the perplexity-delta
    probe the bench ``kv_quant`` block gates on (``quantized=False`` is
    the f32 reference through the IDENTICAL code path, so the delta
    isolates quantization and nothing else).

    The simulation quantizes each page with its final amax where serving
    grows scales incrementally; the incremental path only ever uses
    finer-or-equal grids for early positions, so this bounds the steady-
    state cost honestly. ``tokens`` is [B, L+1] (inputs + shifted targets,
    the ``TransformerLM.loss`` convention)."""
    from ..models.transformer import TransformerLM
    from .flash_attention import reference_attention

    def page_requant(kv):
        # [B, S, Hkv, Dh] -> per (page of page_size positions, kv_head)
        # symmetric int8 round trip
        batch, seq, hkv, dh = kv.shape
        pages = -(-seq // page_size)
        padded = jnp.pad(kv.astype(jnp.float32),
                         ((0, 0), (0, pages * page_size - seq),
                          (0, 0), (0, 0)))
        paged = padded.reshape(batch, pages, page_size, hkv, dh)
        scale = jnp.maximum(
            jnp.max(jnp.abs(paged), axis=(2, 4)) / INT8_MAX, SCALE_FLOOR)
        q = _requant(paged, scale[:, :, None, :, None])
        deq = q.astype(jnp.float32) * scale[:, :, None, :, None]
        return deq.reshape(batch, pages * page_size, hkv, dh
                           )[:, :seq].astype(kv.dtype)

    def attend(q, k, v, layer):
        if quantized:
            k, v = page_requant(k), page_requant(v)
        return reference_attention(q, k, v, causal=True)

    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    dtype = config.dtype
    batch, width = inputs.shape
    x = params["tok_embed"].astype(dtype)[inputs]
    positions = jnp.broadcast_to(jnp.arange(width, dtype=jnp.int32),
                                 (batch, width))
    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, positions, attend,
                                        layer_index=layer_index)
    from ..models.transformer import _rmsnorm

    x = _rmsnorm(x, params["final_norm"]["scale"])
    logits = jnp.einsum("bld,dv->blv", x.astype(dtype),
                        params["w_lm_head"].astype(dtype),
                        preferred_element_type=jnp.float32)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logprobs, targets[..., None],
                                 axis=-1)[..., 0]
    return -jnp.mean(picked)
