"""Flash attention as pallas TPU kernels — forward AND backward.

The hot op of the flagship transformer. All kernels run on a 3D grid
(batch*heads, outer_block, inner_block) with the inner dimension iterating
fastest, so the f32 accumulators live in VMEM scratch across the inner
sweep and K/V (resp. Q/dO) are streamed **block by block through the
BlockSpec index map** — VMEM holds O(block²+block·d) regardless of sequence
length, and the full [Lq, Lk] score matrix never materializes in HBM.

Forward: online softmax (running max + denominator), emitting the output
and the per-row logsumexp (LSE) residual.

Backward (FlashAttention-2 style, two kernels):
  * preprocess (XLA): ``delta = rowsum(dO * O)``
  * dQ kernel, grid (BH, q_blocks, kv_blocks):
      P = exp(S - LSE); dS = P ∘ (dO·Vᵀ - delta); dQ += scale · dS·K
  * dK/dV kernel, grid (BH, kv_blocks, q_blocks):
      dV += Pᵀ·dO;  dK += scale · dSᵀ·Q
recomputing P from the saved LSE instead of materializing the score matrix
(round-1 backward recomputed dense attention through XLA — [B,H,S,S] f32 in
HBM — which dominated the train step and blew HBM at seq ≥ 4k).

Causal masking skips the compute of blocks entirely above/below the
diagonal via ``pl.when`` (their DMA still pipelines; compute is ~halved).
Blocks are MXU/VPU-aligned (multiples of 128 lanes); accumulation is f32
regardless of input dtype (bf16 inputs hit the MXU natively). Non-TPU
backends and odd shapes fall back to an equivalent XLA implementation —
same math, same f32 accumulation — which is also the oracle in tests.
"""
from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _struct(shape, dtype, like) -> jax.ShapeDtypeStruct:
    """Pallas out_shape that survives NEW-style partial-manual shard_map
    (check_vma=True): the output inherits ``like``'s varying-manual-axes
    set — when these kernels run inside the pipeline's manual {pp, sp}
    region (parallel/pipeline.py) a bare ShapeDtypeStruct has vma=None and
    pallas_call refuses it. Outside any manual region vma is empty and
    this is the plain constructor."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def default_blocks(seq_len: int) -> tuple:
    """Per-shape block sizes. Measured on v5e (t2t-base b64×s1024, train_loop
    step timings): 512×512 blocks cut the attention share of the step from
    208 ms to ~118 ms vs the 128×128 round-2 default — fewer, larger grid
    programs amortize per-program pipeline overhead, and the kernels are
    VPU-bound (softmax passes), not VMEM-bound, so bigger tiles cost
    nothing. Capped at seq_len (the sweep showed no further win at 1024)."""
    for block in (512, 256, 128):
        if seq_len % block == 0:
            return (min(block, seq_len),) * 2
    return DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K

#: per-operand VMEM budget for the resident-KV fast path: when K+V (resp.
#: Q+dO) for one batch*head fit comfortably in VMEM, a 2D grid with a
#: dynamic-trip-count fori_loop is faster than the streaming 3D grid — the
#: causal upper triangle is skipped entirely (no DMA, no iteration) and
#: there is no per-block pipeline overhead. Beyond the budget the streaming
#: kernels bound VMEM at O(block²+block·d) for arbitrarily long sequences.
RESIDENT_KV_MAX_BYTES = 4 * 1024 * 1024


def reference_attention(q, k, v, causal: bool = True) -> jax.Array:
    """XLA oracle: plain softmax attention with f32 accumulation.
    q, k, v: [batch, seq, heads, d_head]; GQA (fewer K/V heads) is expanded
    here — this is the oracle/fallback, not the hot path (the pallas kernels
    read KV head ``h // group`` natively, no expanded copy)."""
    if k.shape[2] != q.shape[2]:
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        seq_q, seq_k = scores.shape[2], scores.shape[3]
        mask = jnp.tril(jnp.ones((seq_q, seq_k), bool), seq_k - seq_q)
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _causal_mask(q_start, k_start, block_q, block_k):
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return q_pos >= k_pos


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------




def _fold_scale_into_q(q, scale: float):
    """Fold the softmax scale into q ONCE per block ([block_q, d] elements)
    instead of into the scores (a full [block_q, block_k] VPU pass per KV
    block — the kernels are VPU-bound, docs/PERF.md), returning
    ``(q', residual)`` with ``q'·Kᵀ·residual == scale·q·Kᵀ``.

    The fold only happens when it is EXACT in the input dtype, i.e. the
    scale is a power of two (d_head 16/64/256 → d**-0.5 = 2^-k; d_head
    128 gives 2^-3.5, which would round every bf16 q element, so there the
    scale stays on the f32 scores as the residual)."""
    if scale == 1.0:
        return q, 1.0
    if math.frexp(abs(scale))[0] == 0.5:    # mantissa 1/2 ⇔ power of two
        return q * jnp.asarray(scale, q.dtype), 1.0
    return q, scale


def _online_softmax_block(q, k_blk, v_blk, acc, row_max, row_sum,
                          q_start, k_start, causal: bool, scale: float):
    """Shared forward block math (resident + streaming kernels): one online-
    softmax update against a K/V block. Matmuls run in the INPUT dtype with
    f32 accumulation — upcasting operands to f32 first would push the MXU
    off its native bf16 path (measured ~1 TFLOP/s vs 197 peak on v5e);
    softmax statistics stay f32."""
    block_q, block_k = q.shape[0], k_blk.shape[0]
    q, residual = _fold_scale_into_q(q, scale)
    scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
    if residual != 1.0:
        scores = scores * residual
    if causal:
        mask = _causal_mask(q_start, k_start, block_q, block_k)
        scores = jnp.where(mask, scores, NEG_INF)
    block_max = jnp.max(scores, axis=-1)
    new_max = jnp.maximum(row_max, block_max)
    correction = jnp.exp(row_max - new_max)
    # no re-mask of probs: every sweep this block math serves visits, for
    # any q row, a block containing at least one visible key FIRST (the
    # resident causal sweep starts at kv 0; the streaming grid's first
    # unskipped block is kv 0; the ring's masked-out blocks never reach a
    # kernel), so new_max is finite from the first update and a masked
    # score contributes exp(NEG_INF - finite) == 0 by underflow — the
    # explicit where() was a pure extra VPU pass over S² elements
    probs = jnp.exp(scores - new_max[:, None])
    acc = acc * correction[:, None] + jnp.dot(
        probs.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32)
    row_sum = row_sum * correction + jnp.sum(probs, axis=-1)
    return acc, new_max, row_sum


def _causal_kv_sweep(make_body, carry, q_start, block_q, block_k):
    """Causal KV sweep for a fixed q block: unmasked fori_loop over blocks
    strictly below the diagonal band, then a masked loop over the band —
    the iota/where mask work only pays on band blocks. Shared by the
    resident forward and dq kernels (identical boundary math)."""
    num_full = jax.lax.div(q_start, block_k)
    num_kv = jax.lax.div(q_start + block_q - 1, block_k) + 1
    carry = jax.lax.fori_loop(0, num_full, make_body(False), carry)
    return jax.lax.fori_loop(num_full, num_kv, make_body(True), carry)


def _causal_q_sweep(make_body, carry, k_start, block_q, block_k, num_q):
    """Causal Q sweep for a fixed kv block (dkv kernel): the masked diagonal
    band comes first in the sweep, fully-visible q blocks after it."""
    start_q = jax.lax.div(k_start, block_q)
    band_end = jax.lax.div(k_start + block_k - 1, block_q) + 1
    carry = jax.lax.fori_loop(start_q, band_end, make_body(True), carry)
    return jax.lax.fori_loop(band_end, num_q, make_body(False), carry)


def _kv_resident(seq_len: int, d: int, dtype, factor: int = 1) -> bool:
    """True when one batch*head's K+V (equivalently Q+dO) fit the resident
    VMEM budget. ``factor`` scales the footprint: the GQA dK/dV resident
    kernel holds Q+dO for all ``group`` query heads sharing one KV head."""
    return (2 * factor * seq_len * d * jnp.dtype(dtype).itemsize
            <= RESIDENT_KV_MAX_BYTES)


def _fwd_kernel_resident(q_ref, k_ref, v_ref, out_ref, lse_ref, *,
                         causal: bool, scale: float, block_k: int,
                         seq_len: int):
    """Resident-KV forward: grid (BH, q_blocks); K/V for the whole sequence
    live in VMEM and a fori_loop with a causal-pruned trip count streams
    through them (upper-triangle blocks are never visited at all)."""
    block_q = q_ref.shape[1]
    q_start = pl.program_id(1) * block_q
    q, residual = _fold_scale_into_q(q_ref[0], scale)   # loop-invariant
    d = q_ref.shape[-1]

    def make_body(masked: bool):
        def body(kv_idx, carry):
            acc, row_max, row_sum = carry
            k_start = kv_idx * block_k
            k_blk = k_ref[0, pl.ds(k_start, block_k), :]
            v_blk = v_ref[0, pl.ds(k_start, block_k), :]
            return _online_softmax_block(q, k_blk, v_blk, acc, row_max,
                                         row_sum, q_start, k_start, masked,
                                         residual)
        return body

    carry = (jnp.zeros((block_q, d), jnp.float32),
             jnp.full((block_q,), NEG_INF, jnp.float32),
             jnp.zeros((block_q,), jnp.float32))
    if causal:
        carry = _causal_kv_sweep(make_body, carry, q_start, block_q, block_k)
    else:
        carry = jax.lax.fori_loop(0, seq_len // block_k, make_body(False),
                                  carry)
    acc, row_max, row_sum = carry
    denom = jnp.where(row_sum == 0.0, 1.0, row_sum)
    out_ref[0] = (acc / denom[:, None]).astype(out_ref.dtype)
    lse_ref[0, 0, pl.ds(q_start, block_q)] = (
        row_max + jnp.log(denom)).astype(lse_ref.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, out_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, causal: bool, scale: float):
    """Grid (BH, q_blocks, kv_blocks); kv innermost. Scratch (f32):
    acc [block_q, d], m/l [block_q, 128] (lane-replicated row stats)."""
    block_q, block_k = q_ref.shape[1], k_ref.shape[1]
    q_start = pl.program_id(1) * block_q
    k_start = pl.program_id(2) * block_k
    last_kv = pl.num_programs(2) - 1

    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: blocks entirely above the diagonal contribute nothing
    @pl.when(jnp.logical_or(not causal, k_start <= q_start + block_q - 1))
    def _compute():
        acc, new_max, row_sum = _online_softmax_block(
            q_ref[0], k_ref[0], v_ref[0], acc_ref[...], m_ref[:, 0], l_ref[:, 0],
            q_start, k_start, causal, scale)
        acc_ref[...] = acc
        l_ref[...] = jnp.broadcast_to(row_sum[:, None], l_ref.shape)
        m_ref[...] = jnp.broadcast_to(new_max[:, None], m_ref.shape)

    @pl.when(pl.program_id(2) == last_kv)
    def _finalize():
        row_sum = l_ref[:, 0]
        denom = jnp.where(row_sum == 0.0, 1.0, row_sum)
        out_ref[0] = (acc_ref[...] / denom[:, None]).astype(out_ref.dtype)
        # lse block is the whole [1, 1, seq] row (TPU tiling forbids a
        # (1, block_q) block); write this q block's slice
        lse_ref[0, 0, pl.ds(q_start, block_q)] = (
            m_ref[:, 0] + jnp.log(denom)
        ).astype(lse_ref.dtype)


def _fwd_kernel_resident_bh(q_ref, k_ref, v_ref, out_ref, lse_ref, *,
                            causal: bool, scale: float, block_k: int,
                            seq_len: int):
    """Resident-KV forward over a BLOCK of G heads per program: grid
    (BH // G, q_blocks). Identical math to _fwd_kernel_resident vmapped
    over the leading head dim — G× fewer grid programs amortize
    per-program fixed costs (sequencing + q/out DMA setup) and give the
    MXU a batched [G, block_q, d] × [G, block_k, d] contraction. MHA only
    (the caller guarantees group == 1); experimental, selected via
    TPUHIVE_FLASH_BH_BLOCK (tools/perf_lab.py ``bhblock:G``).

    The carry/epilogue deliberately mirrors _fwd_kernel_resident rather
    than replacing it: the per-head kernel is the measured default path
    and stays untouched while this one is being A/B'd on hardware — if
    bh-blocking graduates to default, collapse the per-head kernel into
    g=1 of this one (GQA's ``b // group`` index map is the one thing to
    port)."""
    g, block_q = q_ref.shape[0], q_ref.shape[1]
    q_start = pl.program_id(1) * block_q
    q, residual = _fold_scale_into_q(q_ref[...], scale)
    d = q_ref.shape[-1]

    def make_body(masked: bool):
        def body(kv_idx, carry):
            acc, row_max, row_sum = carry
            k_start = kv_idx * block_k
            k_blk = k_ref[:, pl.ds(k_start, block_k), :]
            v_blk = v_ref[:, pl.ds(k_start, block_k), :]
            step = jax.vmap(
                lambda qh, kh, vh, acc_h, m, l: _online_softmax_block(
                    qh, kh, vh, acc_h, m, l, q_start, k_start, masked,
                    residual))
            return step(q, k_blk, v_blk, acc, row_max, row_sum)
        return body

    carry = (jnp.zeros((g, block_q, d), jnp.float32),
             jnp.full((g, block_q), NEG_INF, jnp.float32),
             jnp.zeros((g, block_q), jnp.float32))
    if causal:
        carry = _causal_kv_sweep(make_body, carry, q_start, block_q, block_k)
    else:
        carry = jax.lax.fori_loop(0, seq_len // block_k, make_body(False),
                                  carry)
    acc, row_max, row_sum = carry
    denom = jnp.where(row_sum == 0.0, 1.0, row_sum)
    out_ref[...] = (acc / denom[:, :, None]).astype(out_ref.dtype)
    lse_ref[:, 0, pl.ds(q_start, block_q)] = (
        row_max + jnp.log(denom)).astype(lse_ref.dtype)


def _fwd_bh_block(bh: int, group: int, seq_len: int, d: int, dtype) -> int:
    """Head-block size for the experimental batched resident forward:
    TPUHIVE_FLASH_BH_BLOCK (0/unset = off), clamped to divisibility and
    the resident VMEM budget; MHA only."""
    want = int(os.environ.get("TPUHIVE_FLASH_BH_BLOCK", "0") or 0)
    if want <= 1 or group != 1:
        return 1
    g = want
    while g > 1 and (bh % g or not _kv_resident(seq_len, d, dtype, factor=g)):
        g -= 1
    return g


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret", "scale"))
def _flash_fwd_bhsd(q, k, v, causal: bool, block_q: int, block_k: int,
                    interpret: bool, scale: Optional[float] = None):
    """q: [BH, seq, d], k/v: [BHkv, seq, d] → (out, lse [BH, 1, seq] f32).

    GQA runs natively: with ``group = BH // BHkv`` query heads per KV head
    (heads fastest-varying within batch), query program ``b`` reads KV head
    ``b // group`` straight through the BlockSpec index map — no expanded
    K/V copy ever exists, so KV HBM traffic stays ``group``× smaller than
    MHA (the point of GQA; VERDICT r3 weak #4).

    ``scale`` defaults to d**-0.5; callers that compute their own scale
    (parallel/ring.py) pass it through so the two paths share one
    definition."""
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_len, d = q.shape
    group = bh // k.shape[0]
    if scale is None:
        scale = d ** -0.5
    out_shape = [
        _struct(q.shape, q.dtype, q),
        _struct((bh, 1, seq_len), jnp.float32, q),
    ]
    bh_block = _fwd_bh_block(bh, group, seq_len, d, q.dtype)
    if bh_block > 1:
        return pl.pallas_call(
            functools.partial(_fwd_kernel_resident_bh, causal=causal,
                              scale=scale, block_k=block_k, seq_len=seq_len),
            grid=(bh // bh_block, seq_len // block_q),
            in_specs=[
                pl.BlockSpec((bh_block, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((bh_block, seq_len, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((bh_block, seq_len, d), lambda b, i: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((bh_block, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((bh_block, 1, seq_len), lambda b, i: (b, 0, 0)),
            ],
            out_shape=out_shape,
            interpret=interpret,
        )(q, k, v)
    if _kv_resident(seq_len, d, q.dtype):
        return pl.pallas_call(
            functools.partial(_fwd_kernel_resident, causal=causal, scale=scale,
                              block_k=block_k, seq_len=seq_len),
            grid=(bh, seq_len // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, seq_len, d), lambda b, i: (b // group, 0, 0)),
                pl.BlockSpec((1, seq_len, d), lambda b, i: (b // group, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, 1, seq_len), lambda b, i: (b, 0, 0)),
            ],
            out_shape=out_shape,
            interpret=interpret,
        )(q, k, v)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale)
    return pl.pallas_call(
        kernel,
        grid=(bh, seq_len // block_q, seq_len // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, seq_len), lambda b, i, j: (b, 0, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------



def _bwd_probs_ds(q, k_blk, v_blk, do, lse, delta, q_start, k_start,
                  causal: bool, scale: float):
    """Shared backward block math (all four dq/dkv kernels): recompute the
    probabilities from the saved LSE and form dS = P ∘ (dO·Vᵀ − delta).
    Matmuls in the input dtype (f32 accumulation), stats in f32 — see
    _online_softmax_block for why."""
    block_q, block_k = q.shape[0], k_blk.shape[0]
    q, residual = _fold_scale_into_q(q, scale)
    scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
    if residual != 1.0:
        scores = scores * residual
    if causal:
        # masking SCORES (not probs) lets exp produce the zeros directly:
        # exp(NEG_INF - finite lse) underflows to 0 — one where() pass,
        # same as before, but no separate probs pass
        mask = _causal_mask(q_start, k_start, block_q, block_k)
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jnp.exp(scores - lse[:, None])
    dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
    ds = probs * (dp - delta[:, None])
    return probs, ds


def _dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, *, causal: bool, scale: float, block_k: int,
                        seq_len: int):
    """Resident-KV dQ: grid (BH, q_blocks); fori_loop over KV blocks with a
    causal-pruned trip count, dq accumulated in registers/VMEM values."""
    block_q = q_ref.shape[1]
    q_start = pl.program_id(1) * block_q
    q, residual = _fold_scale_into_q(q_ref[0], scale)   # loop-invariant
    do = do_ref[0]
    lse = lse_ref[0, 0, pl.ds(q_start, block_q)]
    delta = delta_ref[0, 0, pl.ds(q_start, block_q)]
    d = q_ref.shape[-1]

    def make_body(masked: bool):
        def body(kv_idx, dq_acc):
            k_start = kv_idx * block_k
            k_blk = k_ref[0, pl.ds(k_start, block_k), :]
            v_blk = v_ref[0, pl.ds(k_start, block_k), :]
            _, ds = _bwd_probs_ds(q, k_blk, v_blk, do, lse, delta,
                                  q_start, k_start, masked, residual)
            return dq_acc + jnp.dot(ds.astype(k_blk.dtype), k_blk,
                                    preferred_element_type=jnp.float32)
        return body

    dq_acc = jnp.zeros((block_q, d), jnp.float32)
    if causal:
        dq_acc = _causal_kv_sweep(make_body, dq_acc, q_start, block_q, block_k)
    else:
        dq_acc = jax.lax.fori_loop(0, seq_len // block_k, make_body(False),
                                   dq_acc)
    dq_ref[0] = (scale * dq_acc).astype(dq_ref.dtype)


def _dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, *, causal: bool, scale: float,
                         block_q: int, seq_len: int):
    """Resident-Q dK/dV: grid (BHkv, kv_blocks); fori_loop over Q blocks
    starting at the diagonal (causal prunes the lower-left triangle).

    GQA: the Q/dO/LSE/delta blocks carry all ``group`` query heads sharing
    this KV head (block shape (group, ...)); their contributions accumulate
    into one dK/dV over a static python loop (group is small and fixed)."""
    block_k = k_ref.shape[1]
    k_start = pl.program_id(1) * block_k
    k_blk = k_ref[0]
    v_blk = v_ref[0]
    d = k_ref.shape[-1]
    group = q_ref.shape[0]

    def make_body(masked: bool, g: int):
        def body(q_idx, carry):
            dk_acc, dv_acc = carry
            q_start = q_idx * block_q
            q = q_ref[g, pl.ds(q_start, block_q), :]
            do = do_ref[g, pl.ds(q_start, block_q), :]
            lse = lse_ref[g, 0, pl.ds(q_start, block_q)]
            delta = delta_ref[g, 0, pl.ds(q_start, block_q)]
            probs, ds = _bwd_probs_ds(q, k_blk, v_blk, do, lse, delta,
                                      q_start, k_start, masked, scale)
            dv_acc = dv_acc + jnp.dot(probs.T.astype(do.dtype), do,
                                      preferred_element_type=jnp.float32)
            dk_acc = dk_acc + jnp.dot(ds.T.astype(q.dtype), q,
                                      preferred_element_type=jnp.float32)
            return dk_acc, dv_acc
        return body

    num_q = seq_len // block_q
    carry = (jnp.zeros((block_k, d), jnp.float32),
             jnp.zeros((block_k, d), jnp.float32))
    for g in range(group):
        make_g = functools.partial(make_body, g=g)
        if causal:
            carry = _causal_q_sweep(make_g, carry, k_start, block_q, block_k,
                                    num_q)
        else:
            carry = jax.lax.fori_loop(0, num_q, make_g(False), carry)
    dk_acc, dv_acc = carry
    dk_ref[0] = (scale * dk_acc).astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc_ref, *, causal: bool, scale: float):
    """Grid (BH, q_blocks, kv_blocks); kv innermost; dq accumulates in
    scratch and is written on the last kv step."""
    block_q, block_k = q_ref.shape[1], k_ref.shape[1]
    q_start = pl.program_id(1) * block_q
    k_start = pl.program_id(2) * block_k
    last_kv = pl.num_programs(2) - 1

    @pl.when(pl.program_id(2) == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    @pl.when(jnp.logical_or(not causal, k_start <= q_start + block_q - 1))
    def _compute():
        k_blk = k_ref[0]
        lse = lse_ref[0, 0, pl.ds(q_start, block_q)]
        delta = delta_ref[0, 0, pl.ds(q_start, block_q)]
        _, ds = _bwd_probs_ds(q_ref[0], k_blk, v_ref[0], do_ref[0], lse, delta,
                              q_start, k_start, causal, scale)
        dq_acc_ref[...] += scale * jnp.dot(ds.astype(k_blk.dtype), k_blk,
                                           preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == last_kv)
    def _finalize():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                *, causal: bool, scale: float, num_q: int):
    """Grid (BHkv, kv_blocks, group*q_blocks); the inner axis sweeps every
    (query head in the group, q block) pair — index t = g*num_q + i — so
    the dk/dv scratch accumulates all query heads sharing this KV head
    before the single write-out. With MHA (group=1) this is exactly the
    former (BH, kv_blocks, q_blocks) kernel."""
    block_q, block_k = q_ref.shape[1], k_ref.shape[1]
    k_start = pl.program_id(1) * block_k
    q_start = jax.lax.rem(pl.program_id(2), num_q) * block_q
    last_q = pl.num_programs(2) - 1

    @pl.when(pl.program_id(2) == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # causal: q blocks entirely above the diagonal see none of this k block
    @pl.when(jnp.logical_or(not causal, q_start + block_q - 1 >= k_start))
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0, pl.ds(q_start, block_q)]
        delta = delta_ref[0, 0, pl.ds(q_start, block_q)]
        probs, ds = _bwd_probs_ds(q, k_ref[0], v_ref[0], do, lse, delta,
                                  q_start, k_start, causal, scale)
        dv_acc_ref[...] += jnp.dot(probs.T.astype(do.dtype), do,
                                   preferred_element_type=jnp.float32)
        dk_acc_ref[...] += scale * jnp.dot(ds.T.astype(q.dtype), q,
                                           preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == last_q)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def flash_bwd_delta(do, out):
    """delta = rowsum(dO ∘ O), [BH, 1, seq] f32 (TPU tiling) — cheap
    elementwise reduce, XLA fuses it. Exposed so ring attention computes it
    ONCE per backward instead of once per ring step."""
    return jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                   axis=-1)[:, None, :]


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret", "scale"))
def _flash_bwd_bhsd(q, k, v, out, lse, do, causal: bool, block_q: int,
                    block_k: int, interpret: bool,
                    scale: Optional[float] = None, delta=None):
    """q/out/do [BH, seq, d], k/v [BHkv, seq, d], lse [BH, 1, seq] f32 →
    (dq, dk, dv). GQA (BHkv < BH) is native throughout: dQ reads KV head
    ``b // group`` via the index maps; dK/dV accumulate the whole group of
    query heads per KV head (resident kernel: (group, ...) input blocks;
    streaming kernel: inner grid axis widened to group*q_blocks). The
    resident fast paths gate independently — dQ on K+V bytes, dK/dV on
    group×(Q+dO) bytes."""
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_len, d = q.shape
    group = bh // k.shape[0]
    if scale is None:
        scale = d ** -0.5
    if delta is None:
        delta = flash_bwd_delta(do, out)

    num_q, num_k = seq_len // block_q, seq_len // block_k
    if _kv_resident(seq_len, d, q.dtype):
        dq = pl.pallas_call(
            functools.partial(_dq_kernel_resident, causal=causal, scale=scale,
                              block_k=block_k, seq_len=seq_len),
            grid=(bh, num_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # q
                pl.BlockSpec((1, seq_len, d), lambda b, i: (b // group, 0, 0)),
                pl.BlockSpec((1, seq_len, d), lambda b, i: (b // group, 0, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # do
                pl.BlockSpec((1, 1, seq_len), lambda b, i: (b, 0, 0)),   # lse
                pl.BlockSpec((1, 1, seq_len), lambda b, i: (b, 0, 0)),   # delta
            ],
            out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            out_shape=_struct(q.shape, q.dtype, q),
            interpret=interpret,
        )(q, k, v, do, lse, delta)
    else:
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, causal=causal, scale=scale),
            grid=(bh, num_q, num_k),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),   # q
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),   # do
                pl.BlockSpec((1, 1, seq_len), lambda b, i, j: (b, 0, 0)),   # lse
                pl.BlockSpec((1, 1, seq_len), lambda b, i, j: (b, 0, 0)),   # delta
            ],
            out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            out_shape=_struct(q.shape, q.dtype, q),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, do, lse, delta)

    bh_kv = k.shape[0]
    if _kv_resident(seq_len, d, q.dtype, factor=group):
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel_resident, causal=causal, scale=scale,
                              block_q=block_q, seq_len=seq_len),
            grid=(bh_kv, num_k),
            in_specs=[
                pl.BlockSpec((group, seq_len, d), lambda b, j: (b, 0, 0)),   # q
                pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),   # k
                pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),   # v
                pl.BlockSpec((group, seq_len, d), lambda b, j: (b, 0, 0)),  # do
                pl.BlockSpec((group, 1, seq_len), lambda b, j: (b, 0, 0)),  # lse
                pl.BlockSpec((group, 1, seq_len), lambda b, j: (b, 0, 0)),  # delta
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            ],
            out_shape=[
                _struct(k.shape, k.dtype, k),
                _struct(v.shape, v.dtype, v),
            ],
            interpret=interpret,
        )(q, k, v, do, lse, delta)
        return dq, dk, dv

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale, num_q=num_q),
        grid=(bh_kv, num_k, group * num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda b, j, t: (b * group + t // num_q, t % num_q, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),   # k
            pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),   # v
            pl.BlockSpec((1, block_q, d),
                         lambda b, j, t: (b * group + t // num_q, t % num_q, 0)),
            pl.BlockSpec((1, 1, seq_len),
                         lambda b, j, t: (b * group + t // num_q, 0, 0)),
            pl.BlockSpec((1, 1, seq_len),
                         lambda b, j, t: (b * group + t // num_q, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),
        ],
        out_shape=[
            _struct(k.shape, k.dtype, k),
            _struct(v.shape, v.dtype, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom VJP plumbing
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_vjp(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_residuals(q, k, v, causal, block_q, block_k, interpret)
    return out


def _to_bhsd(x, batch, seq_len, heads, d):
    return x.transpose(0, 2, 1, 3).reshape(batch * heads, seq_len, d)


def _from_bhsd(x, batch, seq_len, heads, d):
    return x.reshape(batch, heads, seq_len, d).transpose(0, 2, 1, 3)


def _flash_fwd_residuals(q, k, v, causal, block_q, block_k, interpret):
    batch, seq_len, heads, d = q.shape
    kv_heads = k.shape[2]
    out_f, lse = _flash_fwd_bhsd(
        _to_bhsd(q, batch, seq_len, heads, d),
        _to_bhsd(k, batch, seq_len, kv_heads, d),
        _to_bhsd(v, batch, seq_len, kv_heads, d),
        causal, block_q, block_k, interpret,
    )
    return _from_bhsd(out_f, batch, seq_len, heads, d), (out_f, lse)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, (out_f, lse) = _flash_fwd_residuals(
        q, k, v, causal, block_q, block_k, interpret
    )
    del out_f  # save the caller-layout out instead: it lives downstream as
    # an activation anyway, so residualizing the [BH,S,D] copy would hold O
    # twice in HBM until backward
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, residuals, grad_out):
    q, k, v, out, lse = residuals
    batch, seq_len, heads, d = q.shape
    kv_heads = k.shape[2]
    dq, dk, dv = _flash_bwd_bhsd(
        _to_bhsd(q, batch, seq_len, heads, d),
        _to_bhsd(k, batch, seq_len, kv_heads, d),
        _to_bhsd(v, batch, seq_len, kv_heads, d),
        _to_bhsd(out, batch, seq_len, heads, d),
        lse,
        _to_bhsd(grad_out, batch, seq_len, heads, d),
        causal, block_q, block_k, interpret,
    )
    return (
        _from_bhsd(dq, batch, seq_len, heads, d),
        _from_bhsd(dk, batch, seq_len, kv_heads, d),
        _from_bhsd(dv, batch, seq_len, kv_heads, d),
    )


_flash_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused attention with fused backward. q: [batch, seq, heads, d_head];
    k, v: [batch, seq, kv_heads, d_head] with heads % kv_heads == 0 — GQA
    (kv_heads < heads) runs natively in the kernels, reading KV head
    ``h // group`` through the BlockSpec index maps with no expanded copy.

    Uses the pallas kernels when the sequence divides the block sizes and a
    TPU (or interpret mode) is available; otherwise the XLA fallback.
    Block sizes default to the measured-best for the sequence length
    (``default_blocks``).
    """
    batch, seq_len, heads, d = q.shape
    if block_q is None or block_k is None:
        auto_q, auto_k = default_blocks(seq_len)
        block_q = block_q or auto_q
        block_k = block_k or auto_k
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    usable = (
        seq_len % block_q == 0
        and seq_len % block_k == 0
        and v.shape == k.shape
        and k.shape[:2] == q.shape[:2] and k.shape[3] == q.shape[3]
        and heads % k.shape[2] == 0
    )
    if not usable:
        return reference_attention(q, k, v, causal=causal)
    return _flash_vjp(q, k, v, causal, block_q, block_k, interpret)
