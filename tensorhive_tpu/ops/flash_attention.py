"""Flash attention as a pallas TPU kernel.

The hot op of the flagship transformer. Grid is (batch*heads, q_blocks);
each program streams KV blocks through VMEM, maintaining the online-softmax
running max / denominator in f32 scratch so the full [Lq, Lk] score matrix
never materializes in HBM — attention becomes HBM-bandwidth-bound on Q/K/V
reads instead of score-matrix traffic. Causal masking prunes whole KV blocks
above the diagonal (they are skipped, not masked).

Blocks are MXU/VPU-aligned (multiples of 128 lanes); accumulation is f32
regardless of input dtype (bf16 inputs hit the MXU natively). Non-TPU
backends and odd shapes fall back to an equivalent XLA implementation —
same math, same f32 accumulation — which is also the oracle in tests.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def reference_attention(q, k, v, causal: bool = True) -> jax.Array:
    """XLA oracle: plain softmax attention with f32 accumulation.
    q, k, v: [batch, seq, heads, d_head]."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        seq_q, seq_k = scores.shape[2], scores.shape[3]
        mask = jnp.tril(jnp.ones((seq_q, seq_k), bool), seq_k - seq_q)
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, out_ref, *, causal: bool, scale: float,
                  block_k: int, seq_len: int):
    """One (batch*head, q_block) program: stream KV blocks, online softmax.

    q_ref: [1, block_q, d]; k_ref/v_ref: [1, seq_len, d];
    out_ref: [1, block_q, d] (leading 1 = the batch*head block).
    """
    block_q = q_ref.shape[1]
    q_block_idx = pl.program_id(1)
    q_start = q_block_idx * block_q

    q = q_ref[0].astype(jnp.float32) * scale

    def body(kv_idx, carry):
        acc, row_max, row_sum = carry
        k_start = kv_idx * block_k
        k_blk = k_ref[0, pl.ds(k_start, block_k), :]
        v_blk = v_ref[0, pl.ds(k_start, block_k), :]
        scores = jnp.dot(q, k_blk.astype(jnp.float32).T,
                         preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = q_pos >= k_pos
            scores = jnp.where(mask, scores, NEG_INF)
        block_max = jnp.max(scores, axis=-1)
        new_max = jnp.maximum(row_max, block_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[:, None])
        if causal:
            probs = jnp.where(mask, probs, 0.0)
        acc = acc * correction[:, None] + jnp.dot(
            probs, v_blk.astype(jnp.float32), preferred_element_type=jnp.float32
        )
        row_sum = row_sum * correction + jnp.sum(probs, axis=-1)
        return acc, new_max, row_sum

    num_kv_blocks = seq_len // block_k
    if causal:
        # KV blocks entirely above the diagonal contribute nothing: iterate
        # only up to the block containing this Q block's last row
        num_kv_blocks = jax.lax.div(q_start + block_q - 1, block_k) + 1

    d = q_ref.shape[-1]
    acc = jnp.zeros((block_q, d), jnp.float32)
    row_max = jnp.full((block_q,), NEG_INF, jnp.float32)
    row_sum = jnp.zeros((block_q,), jnp.float32)
    acc, row_max, row_sum = jax.lax.fori_loop(
        0, num_kv_blocks, body, (acc, row_max, row_sum)
    )
    denom = jnp.where(row_sum == 0.0, 1.0, row_sum)
    out_ref[0] = (acc / denom[:, None]).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def _flash_attention_bhsd(q, k, v, causal: bool, block_q: int, block_k: int,
                          interpret: bool):
    """q, k, v: [BH, seq, d] — flattened batch*heads leading dim."""
    bh, seq_len, d = q.shape
    scale = d ** -0.5
    grid = (bh, seq_len // block_q)
    kernel = functools.partial(
        _flash_kernel, causal=causal, scale=scale, block_k=block_k,
        seq_len=seq_len,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_vjp(q, k, v, causal, block_q, block_k, interpret):
    batch, seq_len, heads, d = q.shape

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(batch * heads, seq_len, d)

    out = _flash_attention_bhsd(
        to_bhsd(q), to_bhsd(k), to_bhsd(v), causal, block_q, block_k, interpret
    )
    return out.reshape(batch, heads, seq_len, d).transpose(0, 2, 1, 3)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_vjp(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, residuals, grad_out):
    # backward recomputes attention through XLA — forward stays the fused
    # kernel; a dedicated backward kernel is a further optimization
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q, k, v: reference_attention(q, k, v, causal=causal),
                     q, k, v)
    return vjp(grad_out)


_flash_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused attention. q, k, v: [batch, seq, heads, d_head].

    Uses the pallas kernel when the sequence divides the block sizes and a
    TPU (or interpret mode) is available; otherwise the XLA fallback.
    """
    batch, seq_len, heads, d = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    usable = (
        seq_len % block_q == 0
        and seq_len % block_k == 0
        and k.shape == q.shape and v.shape == q.shape
    )
    if not usable:
        return reference_attention(q, k, v, causal=causal)
    return _flash_vjp(q, k, v, causal, block_q, block_k, interpret)
