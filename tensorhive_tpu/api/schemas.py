"""Shared OpenAPI component schemas (model serialization shapes).

Reference: the ``definitions`` blocks of
tensorhive/api/api_specification.yml. Each component mirrors the
corresponding model's ``as_dict`` output exactly (db/models/*), so clients
can codegen from ``/openapi.json`` and the functional tests can assert the
spec and the wire format agree.
"""
from __future__ import annotations

from .schema import arr, component, obj, s

_dt = s("string", format="date-time", nullable=True)
_id = s("integer")

MSG = component("Msg", obj(required=["msg"], msg=s("string")))

USER = component("User", obj(
    required=["id", "username"],
    id=_id,
    username=s("string"),
    email=s("string"),
    createdAt=_dt,
    lastLoginAt=_dt,
    roles=arr(s("string", enum=["user", "admin"])),
))

TOKEN_PAIR = component("TokenPair", obj(
    required=["user", "accessToken", "refreshToken"],
    user=USER,
    accessToken=s("string"),
    refreshToken=s("string"),
))

GROUP = component("Group", obj(
    required=["id", "name"],
    id=_id,
    name=s("string"),
    isDefault=s("boolean"),
    createdAt=_dt,
    users=arr(USER),
))

SCHEDULE = component("Schedule", obj(
    required=["id", "scheduleDays", "hourStart", "hourEnd"],
    id=_id,
    scheduleDays=s("string", description="weekday mask, e.g. '12345'"),
    hourStart=s("string", example="08:00"),
    hourEnd=s("string", example="20:00"),
))

RESOURCE = component("Resource", obj(
    required=["id", "uid", "hostname"],
    id=_id,
    uid=s("string", description="chip uid '{host}:tpu:{index}'"),
    name=s("string"),
    hostname=s("string"),
    acceleratorType=s("string", nullable=True, example="v5litepod-8"),
    sliceName=s("string", nullable=True),
    chipIndex=s("integer", nullable=True),
    topology=s("string", nullable=True, example="2x4",
               description="chip-grid shape of the chip's slice (schema v3)"),
    numChips=s("integer", nullable=True,
               description="total chips in the slice (schema v3)"),
))

RESTRICTION = component("Restriction", obj(
    required=["id", "name", "isGlobal"],
    id=_id,
    name=s("string"),
    startsAt=_dt,
    endsAt=_dt,
    isGlobal=s("boolean"),
    createdAt=_dt,
    schedules=arr(SCHEDULE),
    resources=arr(RESOURCE),
    users=arr(s("integer")),
    groups=arr(s("integer")),
))

RESERVATION = component("Reservation", obj(
    required=["id", "title", "resourceId", "userId", "start", "end"],
    id=_id,
    title=s("string"),
    description=s("string"),
    resourceId=s("string"),
    userId=s("integer"),
    start=s("string", format="date-time"),
    end=s("string", format="date-time"),
    isCancelled=s("boolean"),
    dutyCycleAvg=s("number", nullable=True),
    hbmUtilAvg=s("number", nullable=True),
))

CMD_SEGMENT = component("CmdSegment", obj(
    required=["name", "type"],
    name=s("string"),
    value=s("string", nullable=True),
    type=s("string", enum=["env_variable", "parameter"]),
    index=s("integer"),
))

TASK = component("Task", obj(
    required=["id", "jobId", "hostname", "status", "command"],
    id=_id,
    jobId=s("integer"),
    hostname=s("string"),
    pid=s("integer", nullable=True),
    status=s("string", enum=["not_running", "running", "terminated", "unsynchronized"]),
    command=s("string"),
    fullCommand=s("string"),
    cmdSegments=arr(CMD_SEGMENT),
))

JOB = component("Job", obj(
    required=["id", "name", "userId", "status"],
    id=_id,
    name=s("string"),
    description=s("string"),
    userId=s("integer"),
    status=s("string",
             enum=["not_running", "running", "terminated", "unsynchronized", "pending"]),
    startAt=_dt,
    stopAt=_dt,
    isQueued=s("boolean"),
    tasks=arr(TASK),
))

TASK_LOG = component("TaskLog", obj(required=["log"], log=s("string")))

# node/infrastructure payloads are monitor-shaped (open dictionaries keyed by
# hostname / chip uid); declare the envelope without freezing telemetry keys
CHIP_METRICS = component("ChipMetrics", obj(
    extra=True,
    index=s("integer"),
    processes=arr(obj(extra=True, pid=s("integer"), user=s("string", nullable=True),
                      command=s("string", nullable=True))),
))

NODE = component("Node", obj(
    extra=True,
    TPU={"type": "object", "additionalProperties": CHIP_METRICS,
         "description": "chip uid -> metrics"},
    CPU=obj(extra=True),
))

INFRASTRUCTURE = component("Infrastructure", {
    "type": "object",
    "additionalProperties": NODE,
    "description": "hostname -> node telemetry",
})

# -- common request bodies ---------------------------------------------------

LOGIN_BODY = component("LoginBody", obj(
    required=["username", "password"],
    username=s("string"),
    password=s("string"),
))

CREATE_USER_BODY = component("CreateUserBody", obj(
    required=["username", "email", "password"],
    username=s("string", minLength=3),
    email=s("string"),
    password=s("string", minLength=8),
    admin=s("boolean", description="also grant the admin role"),
))

SIGNUP_BODY = component("SignupBody", obj(
    required=["username", "email", "password"],
    username=s("string", minLength=3,
               description="must match a unix account on the first managed host"),
    email=s("string"),
    password=s("string", minLength=8),
))

UPDATE_USER_BODY = component("UpdateUserBody", obj(
    email=s("string"),
    password=s("string", minLength=8),
    roles=arr(s("string", enum=["user", "admin"])),
))

GRACEFULLY_BODY = component("GracefullyBody", obj(
    gracefully=s("boolean", nullable=True,
                 description="true=SIGINT, null=SIGTERM, false=SIGKILL"),
))
