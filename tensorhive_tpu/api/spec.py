"""OpenAPI document generation + API index page.

Reference: tensorhive/api/api_specification.yml (3793 lines, 44 paths / 66
operationIds) bound by RestyResolver; swagger UI served at ``/{prefix}/ui/``.
Here the document is generated from the live route registry, so it can never
drift from the implementation; it is served at ``/{prefix}/openapi.json``
with a minimal self-contained HTML explorer at ``/{prefix}/ui/`` (no CDN
assets — managed clusters are often airgapped).
"""
from __future__ import annotations

import json
import re
from typing import Dict, List

from werkzeug.routing import Rule
from werkzeug.wrappers import Request, Response

from .. import __version__

_PATH_PARAM_RE = re.compile(r"<(?:(?P<conv>[^:<>]+):)?(?P<name>[^<>]+)>")


def _openapi_path(path: str) -> str:
    return _PATH_PARAM_RE.sub(lambda m: "{%s}" % m.group("name"), path)


def _path_params(path: str) -> List[Dict]:
    params = []
    for match in _PATH_PARAM_RE.finditer(path):
        conv = match.group("conv") or "string"
        params.append({
            "name": match.group("name"),
            "in": "path",
            "required": True,
            "schema": {"type": "integer" if conv == "int" else "string"},
        })
    return params


def build_openapi(url_prefix: str, endpoints: Dict[str, "Endpoint"]) -> Dict:  # noqa: F821
    from .schema import components

    paths: Dict[str, Dict] = {}
    for ep in endpoints.values():
        item = paths.setdefault(_openapi_path(ep.path), {})
        for method in ep.methods:
            if method == "OPTIONS":
                continue
            responses: Dict[str, Dict] = {}
            for status, schema in (ep.responses or {200: None}).items():
                entry: Dict = {"description": "success" if status < 400 else "error"}
                if schema is not None:
                    entry["content"] = {"application/json": {"schema": schema}}
                responses[str(status)] = entry
            operation = {
                "summary": ep.summary or "",
                "tags": [ep.tag],
                "responses": responses,
            }
            if ep.body is not None and method in ("POST", "PUT", "PATCH"):
                operation["requestBody"] = {
                    "required": True,
                    "content": {"application/json": {"schema": ep.body}},
                }
                operation["responses"].setdefault(
                    "422", {"description": "request body failed schema validation"}
                )
            if ep.auth is not None:
                operation["security"] = [{"bearerAuth": []}]
                operation["responses"]["401"] = {"description": "unauthorized"}
            if ep.auth == "admin":
                operation["responses"]["403"] = {"description": "admin role required"}
            params = _path_params(ep.path)
            for name, schema in (ep.query or {}).items():
                params.append({
                    "name": name, "in": "query", "required": False, "schema": schema,
                })
            if params:
                operation["parameters"] = params
            item[method.lower()] = operation
    return {
        "openapi": "3.0.3",
        "info": {"title": "tpuhive API", "version": __version__},
        "servers": [{"url": f"/{url_prefix}" if url_prefix else "/"}],
        "components": {
            "securitySchemes": {
                "bearerAuth": {"type": "http", "scheme": "bearer", "bearerFormat": "JWT"}
            },
            "schemas": components(),
        },
        "paths": paths,
    }


def spec_rules(url_prefix: str, endpoints: Dict[str, "Endpoint"]) -> List[Rule]:  # noqa: F821
    prefix = f"/{url_prefix}" if url_prefix else ""

    def serve_spec(request: Request) -> Response:
        doc = build_openapi(url_prefix, endpoints)
        return Response(json.dumps(doc, indent=1), content_type="application/json")

    def serve_ui(request: Request) -> Response:
        doc = build_openapi(url_prefix, endpoints)
        rows = []
        for path, item in sorted(doc["paths"].items()):
            for method, op in item.items():
                auth = "🔒" if op.get("security") else ""
                rows.append(
                    f"<tr><td><code>{method.upper()}</code></td>"
                    f"<td><code>{path}</code></td><td>{op['summary']}</td>"
                    f"<td>{auth}</td></tr>"
                )
        html = _UI_TEMPLATE.format(version=doc["info"]["version"], rows="\n".join(rows))
        return Response(html, content_type="text/html")

    def serve_docs(request: Request) -> Response:
        # interactive console (reference serves Swagger UI at /{prefix}/ui/,
        # APIServer.py:31). Self-contained single page — no vendored bundle,
        # same dependency-free stance as the SPA: operations render from the
        # live /openapi.json, each with an editable try-it form.
        return Response(_DOCS_PAGE, content_type="text/html")

    return [
        Rule(f"{prefix}/openapi.json", methods=["GET"], endpoint=serve_spec),
        Rule(f"{prefix}/ui/", methods=["GET"], endpoint=serve_ui),
        Rule(f"{prefix}/docs", methods=["GET"], endpoint=serve_docs),
    ]


_DOCS_PAGE = r"""<!doctype html>
<html><head><meta charset="utf-8"><title>tpuhive API console</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 1.5rem auto; max-width: 62rem;
        color: #1c2733; }
 h1 { font-size: 1.4rem; }
 .op { border: 1px solid #d5dde5; border-radius: 6px; margin: .5rem 0; }
 .op > summary { padding: .45rem .7rem; cursor: pointer; display: flex;
                 gap: .7rem; align-items: baseline; }
 .op[open] > summary { border-bottom: 1px solid #e3e9ef; }
 .method { font-weight: 700; font-family: monospace; min-width: 4.2rem; }
 .m-get { color: #1469b3; } .m-post { color: #11805b; }
 .m-put { color: #9636c2; } .m-delete { color: #c22929; }
 .path { font-family: monospace; }
 .summary { color: #5a6b7b; margin-left: auto; font-size: .85rem; }
 .body-panel { padding: .6rem .8rem; }
 label { display: block; font-size: .8rem; margin-top: .45rem; color: #44525f; }
 input, textarea { width: 100%; box-sizing: border-box; font-family: monospace;
   font-size: .85rem; padding: .3rem; border: 1px solid #c3ccd4; border-radius: 4px; }
 textarea { min-height: 6rem; }
 button { margin-top: .6rem; padding: .35rem .9rem; border: 0; background: #1469b3;
          color: #fff; border-radius: 4px; cursor: pointer; }
 pre.result { background: #10151a; color: #cfe3f5; padding: .6rem; border-radius: 4px;
              overflow: auto; max-height: 22rem; white-space: pre-wrap; }
 .status-ok { color: #4fd98f; } .status-err { color: #ff8d8d; }
 #token { font-family: monospace; }
 .topbar { display: flex; gap: 1rem; align-items: end; }
 .topbar > div { flex: 1; }
 .lock { font-size: .8rem; }
</style></head>
<body>
<h1>tpuhive API console</h1>
<div class="topbar">
  <div><label>Bearer token (from <code>POST /user/login</code>)
    <input id="token" placeholder="paste access token — auto-filled after login here"></label></div>
  <div style="flex:0"><span id="opcount"></span></div>
</div>
<div id="ops">loading spec…</div>
<script>
"use strict";
function el(tag, attrs, children) {
  const node = document.createElement(tag);
  for (const key in (attrs || {})) {
    if (key === "text") node.textContent = attrs[key];
    else if (key === "html") node.innerHTML = attrs[key];
    else node.setAttribute(key, attrs[key]);
  }
  (children || []).forEach(function (c) { node.appendChild(c); });
  return node;
}
function sampleFromSchema(schema, spec) {
  if (!schema) return null;
  if (schema.$ref) {
    const name = schema.$ref.split("/").pop();
    return sampleFromSchema(((spec.components || {}).schemas || {})[name], spec);
  }
  if (schema.example !== undefined) return schema.example;
  if (schema.type === "object" || schema.properties) {
    const out = {};
    const props = schema.properties || {};
    for (const key in props) out[key] = sampleFromSchema(props[key], spec);
    return out;
  }
  if (schema.type === "array") return [sampleFromSchema(schema.items, spec)];
  if (schema.type === "integer" || schema.type === "number") return 0;
  if (schema.type === "boolean") return false;
  return "";
}
function buildOp(path, method, op, spec) {
  const params = (op.parameters || []).filter(function (p) { return p.in === "path" || p.in === "query"; });
  const reqSchema = (((op.requestBody || {}).content || {})["application/json"] || {}).schema;
  const panel = el("div", { "class": "body-panel" });
  const inputs = {};
  params.forEach(function (p) {
    const input = el("input", { placeholder: p.schema && p.schema.type || "string" });
    inputs[p.name] = { input: input, where: p.in };
    panel.appendChild(el("label", { text: p.name + " (" + p.in + (p.required ? ", required" : "") + ")" }, [input]));
  });
  let bodyArea = null;
  if (reqSchema) {
    bodyArea = el("textarea", {});
    bodyArea.value = JSON.stringify(sampleFromSchema(reqSchema, spec), null, 1);
    panel.appendChild(el("label", { text: "request body (JSON)" }, [bodyArea]));
  }
  const result = el("pre", { "class": "result", text: "" });
  result.style.display = "none";
  const run = el("button", { text: "Send " + method.toUpperCase() });
  run.addEventListener("click", function () {
    let target = path;
    const query = [];
    for (const name in inputs) {
      const value = inputs[name].input.value;
      if (inputs[name].where === "path") target = target.replace("{" + name + "}", encodeURIComponent(value));
      else if (value) query.push(encodeURIComponent(name) + "=" + encodeURIComponent(value));
    }
    if (query.length) target += "?" + query.join("&");
    const headers = { "Content-Type": "application/json" };
    const token = document.getElementById("token").value.trim();
    if (token) headers["Authorization"] = "Bearer " + token;
    const options = { method: method.toUpperCase(), headers: headers };
    if (bodyArea && options.method !== "GET") options.body = bodyArea.value;
    result.style.display = "block";
    result.textContent = "…";
    fetch(target, options).then(function (resp) {
      return resp.text().then(function (text) {
        let shown = text;
        try { shown = JSON.stringify(JSON.parse(text), null, 1); } catch (err) { /* not JSON */ }
        result.innerHTML = "";
        const cls = resp.ok ? "status-ok" : "status-err";
        result.appendChild(el("span", { "class": cls, text: "HTTP " + resp.status + "\n" }));
        result.appendChild(document.createTextNode(shown));
        if (resp.ok && path.endsWith("/login")) {
          try {
            const doc = JSON.parse(text);
            if (doc.access_token) document.getElementById("token").value = doc.access_token;
          } catch (err) { /* ignore */ }
        }
      });
    }).catch(function (err) { result.textContent = String(err); });
  });
  panel.appendChild(run);
  panel.appendChild(result);
  return el("details", { "class": "op" }, [
    el("summary", {}, [
      el("span", { "class": "method m-" + method, text: method.toUpperCase() }),
      el("span", { "class": "path", text: path }),
      el("span", { "class": "lock", text: op.security ? "🔒" : "" }),
      el("span", { "class": "summary", text: op.summary || "" }),
    ]),
    panel,
  ]);
}
fetch("openapi.json").then(function (r) { return r.json(); }).then(function (spec) {
  const host = document.getElementById("ops");
  host.textContent = "";
  let count = 0;
  Object.keys(spec.paths).sort().forEach(function (path) {
    const item = spec.paths[path];
    Object.keys(item).forEach(function (method) {
      host.appendChild(buildOp(path, method, item[method], spec));
      count += 1;
    });
  });
  document.getElementById("opcount").textContent = count + " operations";
}).catch(function (err) {
  document.getElementById("ops").textContent = "failed to load openapi.json: " + err;
});
</script>
</body></html>
"""

_UI_TEMPLATE = """<!doctype html>
<html><head><meta charset="utf-8"><title>tpuhive API</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem auto; max-width: 60rem; }}
 table {{ border-collapse: collapse; width: 100%; }}
 td, th {{ border-bottom: 1px solid #ddd; padding: .4rem .6rem; text-align: left; }}
 code {{ background: #f4f4f4; padding: .1rem .3rem; border-radius: 3px; }}
</style></head>
<body><h1>tpuhive API <small>v{version}</small></h1>
<p>Machine-readable spec: <a href="../openapi.json"><code>openapi.json</code></a></p>
<table><tr><th>Method</th><th>Path</th><th>Summary</th><th>Auth</th></tr>
{rows}
</table></body></html>
"""
