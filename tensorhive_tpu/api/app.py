"""WSGI application core: routing, auth enforcement, JSON error mapping.

Reference: tensorhive/api/APIServer.py:17-44 builds a Connexion FlaskApp that
resolves ``operationId``s in a 3793-line OpenAPI YAML onto controller
functions, with Flask-JWT-Extended decorators per endpoint. The rebuild
inverts the direction — routes are declared in code next to the controllers
(one ``@route`` per reference operationId) and the OpenAPI document is
*generated* from the registry (api/spec.py) — same spec-driven client
surface, no YAML/implementation drift possible, zero web-framework
dependencies beyond werkzeug's routing/request primitives.

Auth levels mirror the reference exactly: ``auth=None`` (login/signup),
``auth="jwt"`` (@jwt_required), ``auth="admin"`` (@admin_required,
authorization.py:37-45).
"""
from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from werkzeug.exceptions import HTTPException
from werkzeug.routing import Map, Rule
from werkzeug.wrappers import Request, Response

from ..db.models.user import User
from ..observability import get_registry, get_tracer
from ..utils.exceptions import (
    ConflictError,
    ForbiddenError,
    NotFoundError,
    TransportError,
    ValidationError,
)
from . import jwt as jwt_module
from .jwt import AuthError
from .schema import validate as schema_validate

log = logging.getLogger(__name__)

# per-endpoint request accounting: labels are the registered route PATTERN
# (bounded cardinality — path params never leak into labels), the method,
# and the status class ("2xx"/"4xx"/...)
_REQUESTS = get_registry().counter(
    "tpuhive_api_requests_total",
    "API requests dispatched, by route pattern, method and status class.",
    labels=("endpoint", "method", "status"))
_REQUEST_SECONDS = get_registry().histogram(
    "tpuhive_api_request_seconds",
    "API request dispatch latency by route pattern and method.",
    labels=("endpoint", "method"))
_UNHANDLED_ERRORS = get_registry().counter(
    "tpuhive_api_unhandled_errors_total",
    "Requests that hit the catch-all 500 handler, by route pattern — the "
    "exceptions the typed error mapping did not anticipate.",
    labels=("endpoint",))


@dataclasses.dataclass
class Endpoint:
    """One registered operation (≈ one operationId in the reference spec)."""

    path: str
    methods: List[str]
    handler: Callable
    auth: Optional[str]          # None | "jwt" | "admin" | "refresh" | "logout"*
    summary: str
    tag: str
    #: request-body schema (api/schema.py subset); validated server-side
    #: before the handler runs — malformed bodies 422 from the schema layer
    body: Optional[Dict] = None
    #: response schemas per status code (emitted in the OpenAPI doc)
    responses: Optional[Dict[int, Dict]] = None
    #: query-parameter schemas by name (documentation; int coercion stays
    #: in int_arg so malformed values 422 consistently)
    query: Optional[Dict[str, Dict]] = None


_REGISTRY: List[Endpoint] = []


def route(path: str, methods: List[str], auth: Optional[str] = "jwt",
          summary: str = "", tag: str = "",
          body: Optional[Dict] = None,
          responses: Optional[Dict[int, Dict]] = None,
          query: Optional[Dict[str, Dict]] = None) -> Callable:
    """Register a controller function as an API operation."""

    def decorate(fn: Callable) -> Callable:
        _REGISTRY.append(Endpoint(
            path=path,
            methods=[m.upper() for m in methods],
            handler=fn,
            auth=auth,
            summary=summary or (fn.__doc__ or "").strip().splitlines()[0] if fn.__doc__ else summary,
            tag=tag or fn.__module__.rsplit(".", 1)[-1],
            body=body,
            responses=responses,
            query=query,
        ))
        return fn

    return decorate


def registered_endpoints() -> List[Endpoint]:
    _load_controllers()
    return list(_REGISTRY)


_controllers_loaded = False
_load_lock = threading.Lock()


def _load_controllers() -> None:
    """Import every controller module so @route decorators run (reference:
    RestyResolver scans tensorhive.controllers, api/APIServer.py:31)."""
    global _controllers_loaded
    with _load_lock:
        if _controllers_loaded:
            return
        from ..controllers import ALL_MODULES  # noqa: F401  (import side effect)

        _controllers_loaded = True


class RequestContext:
    """Per-request state handed to controllers needing the acting user."""

    def __init__(self, request: Request, claims: Optional[Dict[str, Any]]) -> None:
        self.request = request
        self.claims = claims or {}

    @property
    def user_id(self) -> Optional[int]:
        return self.claims.get("sub")

    @property
    def roles(self) -> List[str]:
        return self.claims.get("roles", [])

    @property
    def is_admin(self) -> bool:
        return "admin" in self.roles

    def current_user(self) -> User:
        user = User.get_or_none(self.user_id) if self.user_id is not None else None
        if user is None:
            raise AuthError("token subject no longer exists")
        return user

    _json_cache: Optional[Dict[str, Any]] = None

    def json(self) -> Dict[str, Any]:
        if self._json_cache is None:
            try:
                data = json.loads(self.request.get_data(as_text=True) or "{}")
            except json.JSONDecodeError:
                raise ValidationError("request body is not valid JSON")
            if not isinstance(data, dict):
                raise ValidationError("request body must be a JSON object")
            self._json_cache = data
        return self._json_cache


class ApiApp:
    """The WSGI application."""

    def __init__(self, url_prefix: str = "api") -> None:
        _load_controllers()
        self.url_prefix = url_prefix.strip("/")
        rules = []
        self._endpoints: Dict[str, Endpoint] = {}
        for i, ep in enumerate(_REGISTRY):
            name = f"ep{i}"
            self._endpoints[name] = ep
            prefixed = f"/{self.url_prefix}{ep.path}" if self.url_prefix else ep.path
            rules.append(Rule(prefixed, methods=ep.methods, endpoint=name))
        from .spec import spec_rules

        rules.extend(spec_rules(self.url_prefix, self._endpoints))
        self.url_map = Map(rules)

    # -- dispatch ----------------------------------------------------------
    def wsgi_app(self, environ, start_response):
        request = Request(environ)
        response = self.dispatch(request)
        return response(environ, start_response)

    __call__ = wsgi_app

    def dispatch(self, request: Request) -> Response:
        if request.method == "OPTIONS":
            return self._with_cors(Response(status=204))
        started = time.perf_counter()
        tracer = get_tracer()
        span = tracer.start_span(f"api {request.method} {request.path}",
                                 kind="api", method=request.method)
        try:
            response, endpoint_label = self._dispatch(request)
        except BaseException:
            tracer.end_span(span, status="error")
            raise
        status_class = f"{response.status_code // 100}xx"
        _REQUESTS.labels(endpoint=endpoint_label, method=request.method,
                         status=status_class).inc()
        _REQUEST_SECONDS.labels(endpoint=endpoint_label,
                                method=request.method).observe(
                                    time.perf_counter() - started)
        tracer.end_span(span,
                        status="ok" if response.status_code < 500 else "error",
                        endpoint=endpoint_label,
                        http_status=response.status_code)
        return response

    def _dispatch(self, request: Request) -> "tuple[Response, str]":
        """Route + run one request; returns (response, route-pattern label).

        The label is the REGISTERED pattern (e.g. ``/jobs/<int:job_id>``),
        never the concrete path, keeping metric cardinality bounded."""
        adapter = self.url_map.bind_to_environ(request.environ)
        try:
            endpoint_name, path_args = adapter.match()
        except HTTPException as exc:
            return (self._with_cors(self._error(exc.code or 500, exc.description)),
                    "<unmatched>")
        if callable(endpoint_name):  # spec/static endpoints
            return self._with_cors(endpoint_name(request)), "<spec>"
        endpoint = self._endpoints[endpoint_name]
        try:
            claims = self._authenticate(request, endpoint)
            context = RequestContext(request, claims)
            if endpoint.body is not None and request.method in ("POST", "PUT", "PATCH"):
                # spec-driven request validation (reference: Connexion
                # strict_validation against api_specification.yml schemas)
                schema_validate(context.json(), endpoint.body)
            result = endpoint.handler(context, **path_args)
            if isinstance(result, Response):
                # handlers may produce non-JSON payloads directly (the
                # Prometheus text exposition at /metrics does)
                return self._with_cors(result), endpoint.path
            body, status = result if isinstance(result, tuple) else (result, 200)
            response = Response(
                json.dumps(body, default=str),
                status=status,
                content_type="application/json",
            )
        except AuthError as exc:
            response = self._error(401, str(exc))
        except ForbiddenError as exc:
            response = self._error(403, str(exc))
        except NotFoundError as exc:
            response = self._error(404, str(exc))
        except ConflictError as exc:
            response = self._error(409, str(exc))
        except ValidationError as exc:
            response = self._error(422, str(exc))
        except TransportError as exc:
            response = self._error(502, str(exc))
        except Exception:
            # the catch-all is deliberate (a handler bug must 500, not kill
            # the worker) but never silent: logged with traceback AND
            # counted per route pattern, so a spike is alertable (TH-E)
            log.exception("unhandled error on %s %s", request.method, request.path)
            _UNHANDLED_ERRORS.labels(endpoint=endpoint.path).inc()
            response = self._error(500, "internal server error")
        return self._with_cors(response), endpoint.path

    def _authenticate(self, request: Request, endpoint: Endpoint) -> Optional[Dict]:
        if endpoint.auth is None:
            return None
        header = request.headers.get("Authorization", "")
        if not header.startswith("Bearer "):
            raise AuthError("missing bearer token")
        expected = "refresh" if endpoint.auth in ("refresh", "logout-refresh") else "access"
        # logout endpoints verify the signature only: revocation must be
        # idempotent, so a second logout (or one racing expiry) is a 200
        verify_active = endpoint.auth not in ("logout", "logout-refresh")
        claims = jwt_module.decode(
            header[len("Bearer "):], expected_type=expected, verify_active=verify_active
        )
        if endpoint.auth == "admin" and "admin" not in claims.get("roles", []):
            raise ForbiddenError("admin role required")
        return claims

    @staticmethod
    def _error(status: int, message: str) -> Response:
        return Response(
            json.dumps({"msg": message}), status=status, content_type="application/json"
        )

    @staticmethod
    def _with_cors(response: Response) -> Response:
        """Reference enables blanket CORS for the SPA (APIServer.py CORS)."""
        response.headers["Access-Control-Allow-Origin"] = "*"
        response.headers["Access-Control-Allow-Headers"] = "Authorization, Content-Type"
        response.headers["Access-Control-Allow-Methods"] = "GET, POST, PUT, DELETE, OPTIONS"
        return response


def json_body(context: RequestContext, *required: str) -> Dict[str, Any]:
    """Parse the JSON body and assert required fields are present."""
    data = context.json()
    missing = [field for field in required if field not in data]
    if missing:
        raise ValidationError(f"missing required fields: {', '.join(missing)}")
    return data


def int_arg(context: RequestContext, name: str) -> Optional[int]:
    """Optional integer query parameter; malformed values are 422, not 500."""
    value = context.request.args.get(name)
    if value is None:
        return None
    try:
        return int(value)
    except ValueError:
        raise ValidationError(f"query parameter {name!r} must be an integer")
