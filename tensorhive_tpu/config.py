"""Configuration system: TOML files + env overrides + typed sections.

Reference: tensorhive/config.py (298 LoC) reads three INI files copied into
``~/.config/TensorHive/`` at import time into UPPERCASE class namespaces
(config.py:12-68, :113-262). That design has two gotchas SURVEY.md §5 calls
out: import-time side effects, and a silently-ignored section-name mismatch
between the shipped template and the reader. This rebuild therefore:

* parses lazily via an explicit :func:`get_config` singleton (reloadable in
  tests),
* validates section/key names strictly — unknown keys raise
  :class:`ConfigurationError` instead of falling back to defaults,
* uses TOML (stdlib ``tomllib``) with the same three-file split:
  ``config.toml`` (main), ``hosts.toml`` (inventory), ``mailbot.toml``.

The host inventory is TPU-native: each host is a TPU VM (or worker of a pod
slice) carrying accelerator type/topology metadata the scheduler and the
template engine need (reference hosts are bare ``[hostname] user/port``
sections, tensorhive/config.py:121-153 — topology awareness is the main
addition, per SURVEY.md §7 "chip vs slice granularity" risk).
"""
from __future__ import annotations

import dataclasses
import os

try:
    import tomllib
except ImportError:  # Python < 3.11: stdlib tomllib landed in 3.11
    import tomli as tomllib  # type: ignore[no-redef]
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional

from .utils.exceptions import ConfigurationError

ENV_CONFIG_DIR = "TPUHIVE_CONFIG_DIR"
ENV_PYTEST = "TPUHIVE_PYTEST"
DEFAULT_CONFIG_DIR = "~/.config/tpuhive"

MAIN_CONFIG_NAME = "config.toml"
HOSTS_CONFIG_NAME = "hosts.toml"
MAILBOT_CONFIG_NAME = "mailbot.toml"


def _apply(section: Any, data: Mapping[str, Any], where: str) -> None:
    """Assign TOML keys onto a dataclass instance, strictly."""
    valid = {f.name for f in dataclasses.fields(section)}
    for key, value in data.items():
        if key not in valid:
            raise ConfigurationError(f"unknown key '{key}' in [{where}]")
        setattr(section, key, value)


@dataclasses.dataclass
class DbConfig:
    """Reference: tensorhive/config.py:156-164 (SQLite path; PYTEST → memory)."""
    path: str = "{config_dir}/db.sqlite3"

    def resolved_path(self, config_dir: Path) -> str:
        if os.environ.get(ENV_PYTEST) or os.environ.get("PYTEST"):
            return ":memory:"
        return self.path.format(config_dir=str(config_dir))


@dataclasses.dataclass
class ApiConfig:
    """Reference: tensorhive/config.py:167-198 (API + API_SERVER sections)."""
    title: str = "tpuhive API"
    url_schema: str = "http"
    url_hostname: str = "0.0.0.0"
    url_port: int = 1111
    url_prefix: str = "api"
    secret_key: str = ""            # JWT HMAC key; generated into config on init
    access_token_minutes: int = 60
    refresh_token_days: int = 30


@dataclasses.dataclass
class AppServerConfig:
    """Static web app server (reference: tensorhive/config.py:183-190)."""
    host: str = "0.0.0.0"
    port: int = 5000


@dataclasses.dataclass
class MonitoringConfig:
    """Reference: tensorhive/config.py:200-205 (enable flags + 2.0s interval)."""
    enabled: bool = True
    enable_tpu_monitor: bool = True
    enable_cpu_monitor: bool = True
    interval_s: float = 2.0
    # build + push the native probe binary to managed hosts at boot; hosts
    # where this fails use the inline python fallback automatically
    deploy_native_probe: bool = True


@dataclasses.dataclass
class ProtectionConfig:
    """Reference: tensorhive/config.py:207-214.

    ``level`` mirrors the reference's strictness ladder
    (TensorHiveManager.py:105): 1 = protect reservations, 2 = additionally
    flag unreserved use ("strict"). ``kill_mode``: 0 = never kill,
    1 = kill over the intruder's own account, 2 = sudo kill
    (config.py:213 kill_processes).
    """
    enabled: bool = True
    interval_s: float = 2.0
    level: int = 1
    notify_on_pty: bool = True
    notify_via_email: bool = False
    kill_mode: int = 0


@dataclasses.dataclass
class MailbotConfig:
    """Reference: tensorhive/config.py:216-239 + core/utils/mailer.py."""
    smtp_server: str = ""
    smtp_port: int = 587
    smtp_login: str = ""
    smtp_password: str = ""
    notify_intruder: bool = True
    notify_admin: bool = False
    admin_email: str = ""
    interval_between_notifications_s: float = 900.0
    max_emails_per_interval: int = 50


@dataclasses.dataclass
class UsageLoggingConfig:
    """Reference: tensorhive/config.py:241-252."""
    enabled: bool = True
    interval_s: float = 2.0
    log_dir: str = "{config_dir}/usage_logs"
    log_cleanup_action: int = 2  # 1=remove, 2=hide(dot-prefix), 3=keep (UsageLoggingService.py:18)


@dataclasses.dataclass
class JobSchedulingConfig:
    """Reference: tensorhive/config.py:254-259."""
    enabled: bool = True
    interval_s: float = 30.0
    stop_attempts_after_mins: float = 5.0
    schedule_queued_when_free_mins: float = 30.0


@dataclasses.dataclass
class AlertingConfig:
    """Alert rule engine over the metrics registry (no reference analog —
    the reference had no alerting; docs/OBSERVABILITY.md 'Alerting &
    health'). The webhook sink is enabled by setting ``webhook_url``; every
    delivery carries ``webhook_timeout_s`` and retries at most
    ``webhook_retries`` extra times."""
    enabled: bool = True
    interval_s: float = 5.0
    webhook_url: str = ""
    webhook_timeout_s: float = 5.0
    webhook_retries: int = 2


@dataclasses.dataclass
class GenerationConfig:
    """Continuous-batching inference gateway (docs/SERVING.md; no reference
    analog — the reference manages clusters, it serves no model traffic).

    Disabled by default: enabling allocates a model + a
    ``[layers, slots, max_len, kv_heads, d_head]`` KV cache at boot. The
    slot pool size IS the decode batch size; ``queue_depth`` bounds the
    admission queue (full = 429 + Retry-After). ``top_k``/``eos_token`` use
    0/-1 as "unset" because TOML has no null."""
    enabled: bool = False
    preset: str = "tiny"
    slots: int = 8                   # PER-DP-SHARD slot count: the engine
                                     # serves slots * mesh_dp sequences
    max_len: int = 0                 # 0 = the preset's max_seq_len
    mesh_dp: int = 1                 # serving mesh data-parallel degree:
                                     # shards the slot/page pool so capacity
                                     # scales with chips (docs/SERVING.md
                                     # "Multi-chip serving")
    mesh_tp: int = 1                 # tensor-parallel degree: megatron
                                     # head/ffn/vocab splits; capped by the
                                     # model's kv_heads for K/V sharding
                                     # (GQA guard replicates K/V past it)
    checkpoint_path: str = ""        # orbax checkpoint dir (train_loop
                                     # format); "" serves random init
                                     # params. Shape mismatch disables
                                     # serving with a 503 reason, never
                                     # crashes boot
    paged: bool = True               # false: contiguous per-slot cache
                                     # rollback (docs/SERVING.md)
    page_size: int = 16              # tokens per KV page
    kv_pages: int = 0                # 0 = slots * ceil(max_len / page_size)
                                     # (the contiguous layout's HBM)
    paged_kernel: str = "auto"       # fused paged-attention decode kernel:
                                     # auto = pallas on real TPU, XLA page
                                     # gather elsewhere; on/off force a
                                     # dispatch (docs/SERVING.md)
    kv_quant: str = "auto"           # int8 KV pages with per-(page,
                                     # kv_head) scales (docs/SERVING.md
                                     # "Quantized KV pages"): auto = on
                                     # for the paged layout — same HBM,
                                     # ~2x (bf16) / ~4x (f32) the pages;
                                     # off = byte-identical f32 rollback
    prefix_cache: str = "auto"       # radix shared-prefix page cache
                                     # (docs/SERVING.md "Prefix cache &
                                     # chunked prefill"): auto = on for the
                                     # paged layout; off = byte-identical
                                     # PR 7-10 rollback; on requires paged
    prefix_min_tokens: int = 32      # shortest cached prefix worth a
                                     # shared grant (whole pages only)
    prefill_chunk_tokens: int = 256  # per-tick prefill budget: long
                                     # prompts split into chunks this size
                                     # interleaved with decode steps; 0 =
                                     # one chunk per prompt (prefix-cache
                                     # engines only)
    host_kv_bytes: int = 0           # KV-page tiering (docs/SERVING.md
                                     # "KV-page tiering"): byte budget of
                                     # the host-RAM store cold int8 pages
                                     # spill to on eviction/drain, promoted
                                     # back by async DMA on a radix hit.
                                     # Needs paged + kv_quant + the prefix
                                     # cache; 0 = byte-identical rollback
                                     # (no store, no copy lane)
    speculative: str = "auto"        # draft-model speculative decoding
                                     # (docs/SERVING.md "Speculative
                                     # decoding"): auto = on only on real
                                     # TPU; off = byte-identical rollback.
                                     # Greedy output is token-identical to
                                     # non-speculative either way
    draft_preset: str = ""           # draft model preset (must share the
                                     # vocab); "" = self-draft from the
                                     # target's first draft_layers layers
    draft_layers: int = 0            # self-draft depth (0 = half the
                                     # target's layers, min 1)
    spec_tokens: int = 4             # draft tokens proposed + verified in
                                     # one batched pass per tick
    queue_depth: int = 32
    max_new_tokens: int = 128        # per-request cap
    top_k: int = 0                   # 0 = no top-k sampling filter
    eos_token: int = -1              # -1 = no EOS, run to max_new_tokens
    max_concurrent_per_user: int = 4  # 0 = unlimited
    require_restriction: bool = True  # gate /generate on an active Restriction
    use_flash: bool = True           # false: XLA reference attention prefill
                                     # (runtimes without the pallas kernels)
    interval_s: float = 0.02         # pump tick; do_run budgets inside it
    stream_timeout_s: float = 30.0   # client-side max silent gap
    ttft_slo_s: float = 2.0          # p95 budget the alert pack enforces
    queue_wait_slo_s: float = 1.0    # p95 admission-queue wait budget (the
                                     # queue_wait_slo alert rule; TTFT minus
                                     # this is the prefill share)
    slot_leak_after_s: float = 60.0  # silent-busy-slot alert threshold
    request_ledger_size: int = 256   # bounded per-request trace ring
                                     # (GET /api/admin/requests)
    # -- data-plane fault tolerance (docs/ROBUSTNESS.md "Serving data
    # plane"): per-request deadlines, the engine supervisor's restart
    # budget and the graceful-drain bound
    default_deadline_s: float = 120.0  # per-request wall budget (queue +
                                       # prefill + decode) when the body
                                       # omits deadlineS; 0 = no deadline
    max_deadline_s: float = 600.0    # ceiling for per-request deadlineS
                                     # overrides (422 past it)
    transient_retries: int = 3       # transient pump failures retried
                                     # against the SAME engine per
                                     # incident before escalating to the
                                     # fatal fail-fast + rebuild path
    transient_backoff_s: float = 0.05  # base backoff between transient
                                       # retries (doubles per retry)
    restart_budget: int = 3          # engine rebuilds allowed within
                                     # restart_window_s before the
                                     # crash-loop breaker trips (503 with
                                     # the reason)
    restart_window_s: float = 60.0   # sliding window the budget counts in
    restart_cooldown_s: float = 30.0  # crash-loop breaker cooldown before
                                      # one probe rebuild is allowed
    drain_timeout_s: float = 10.0    # shutdown drain bound: in-flight
                                     # requests get this long to finish
                                     # before being failed fast with a
                                     # terminal chunk
    # -- flight recorder (docs/OBSERVABILITY.md "History, SLOs & flight
    # recorder"): per-tick black box + crash dumps on fatal classification
    flight_recorder: bool = True     # false = byte-identical rollback (no
                                     # ring, no dumps, step() untouched)
    flightrec_ticks: int = 512       # bounded per-tick ring capacity
    flightrec_dumps: int = 8         # crash dumps kept under
                                     # {config_dir}/flightrec before pruning


@dataclasses.dataclass
class HistoryConfig:
    """In-process metrics history (docs/OBSERVABILITY.md "History, SLOs &
    flight recorder"). The HistoryService samples an allowlist of registry
    series into a fixed-memory ring; memory is bounded by ``max_points``
    windows per series regardless of ``retention_s``. When disabled the
    service never starts and ``/api/admin/history`` answers 404."""
    enabled: bool = True
    sample_interval_s: float = 5.0   # HistoryService tick
    retention_s: float = 3600.0      # lookback served by /api/admin/history
    max_points: int = 720            # downsample windows per series; window
                                     # width = retention_s / max_points
    series: str = ""                 # comma-separated series specs replacing
                                     # the shipped allowlist ("" = default)


@dataclasses.dataclass
class AccountingConfig:
    """Per-tenant resource attribution (docs/OBSERVABILITY.md "Tenant
    accounting"). The TenantMeter integrates device-seconds, KV
    byte-seconds, queue-seconds and token counts per serving user plus
    chip-seconds per reservation owner. Disabled = the meter is never
    built, the engine takes its meter-less fast path (byte-identical
    rollback), ``/api/admin/usage`` answers 404 and zero
    ``tpuhive_tenant_*`` series render."""
    enabled: bool = True
    top_k_tenants: int = 8           # tenants exported by name; the rest
                                     # collapse into the 'other' bucket
                                     # (cardinality bound = K+1 children)
    window_s: float = 3600.0         # default /api/admin/usage rollup and
                                     # dominance-alert lookback
    dominance_share: float = 0.5     # tenant_dominates_capacity fires above
                                     # this share of windowed device-seconds
                                     # while queue-wait SLO pressure exists


@dataclasses.dataclass
class SloConfig:
    """SLO objectives + burn-rate evaluation (docs/OBSERVABILITY.md
    "History, SLOs & flight recorder"). Evaluated off the history store;
    disabled = the ``tpuhive_slo_*`` gauges never appear and the burn-rate
    alert rules stay quiet (source None)."""
    enabled: bool = True
    budget_window_s: float = 3600.0  # window error budget is measured over
    availability_target: float = 0.999  # availability objective target
    latency_target: float = 0.99     # queue_wait / ttft objective target


@dataclasses.dataclass
class ProfilingConfig:
    """On-demand device profiling (docs/OBSERVABILITY.md "Request tracing &
    profiling"; no reference analog). Disabled by default: the profiler is a
    process-wide singleton and captures write artifacts to disk, so exposing
    it is an explicit operator decision. When disabled, the
    ``/api/admin/profile*`` endpoints answer 404."""
    enabled: bool = False
    artifact_dir: str = "{config_dir}/profiles"
    max_duration_s: float = 10.0     # per-capture ceiling (absolute cap 60)
    default_duration_s: float = 1.0  # when POST body omits durationS


@dataclasses.dataclass
class AgentConfig:
    """Push-based host membership plane (docs/ROBUSTNESS.md "Host membership
    & leases"; no reference analog — the reference is pull-only). Hosts
    running the ``tpuhive-agent`` push telemetry + a monotonically-sequenced
    heartbeat to ``POST /api/agent/report``; the lease state machine in
    InfrastructureManager (live → suspect → unreachable → deregistered)
    replaces the SSH fan-out for them. ``token`` is the shared bearer secret
    agents present; empty token disables the plane (the endpoint answers
    404, no leases are swept)."""
    enabled: bool = True
    token: str = ""                  # shared agent bearer token; "" = plane off
    heartbeat_interval_s: float = 2.0  # agent-side report cadence
    suspect_after_s: float = 0.0     # missed-heartbeat bound before a live
                                     # lease turns suspect; 0 = 2x heartbeat
    lease_ttl_s: float = 0.0         # lease expiry (suspect -> unreachable,
                                     # last-known-good retained); 0 = 3x
                                     # heartbeat
    deregister_after_s: float = 900.0  # unreachable dwell before the host is
                                       # deregistered (dynamic members only)

    def effective_suspect_after_s(self) -> float:
        return self.suspect_after_s or 2.0 * self.heartbeat_interval_s

    def effective_lease_ttl_s(self) -> float:
        return self.lease_ttl_s or 3.0 * self.heartbeat_interval_s


@dataclasses.dataclass
class SshConfig:
    """Control-plane transport settings (reference: tensorhive/config.py:113-120).

    The resilience knobs (docs/ROBUSTNESS.md) feed
    ``core/transport/resilience.py``: retries are exponential-backoff with
    full jitter and always fit the caller's timeout budget; the per-host
    circuit breaker trips after ``breaker_failure_threshold`` consecutive
    channel failures and cools down ``breaker_cooldown_s`` seconds
    (+ up to ``breaker_cooldown_jitter`` fraction of jitter) before
    granting ``breaker_half_open_probes`` half-open probes.
    """
    timeout_s: float = 10.0
    num_retries: int = 1
    retry_backoff_base_s: float = 0.2
    retry_backoff_max_s: float = 5.0
    breaker_failure_threshold: int = 3
    breaker_cooldown_s: float = 30.0
    breaker_cooldown_jitter: float = 0.1
    breaker_half_open_probes: int = 1
    key_path: str = "{config_dir}/ssh_key"
    # name of transport backend: 'ssh' (openssh binary), 'local' (subprocess on
    # this machine — useful for single-VM installs and the localhost example)
    default_backend: str = "ssh"
    proxy_host: str = ""
    proxy_user: str = ""
    proxy_port: int = 22


@dataclasses.dataclass
class HostConfig:
    """One managed TPU VM / pod-slice worker.

    Reference hosts carry only user+port (tensorhive/config.py:121-136); the
    TPU rebuild adds accelerator metadata so reservations and launch templates
    can reason about slice shapes (SURVEY.md §7 risk "chip vs slice
    granularity"): e.g. a v5e-16 slice = 4 workers x 4 chips each.
    """
    name: str = ""
    address: str = ""            # hostname/IP for the transport
    user: str = ""
    port: int = 22
    backend: str = ""            # override SshConfig.default_backend per host
    accelerator_type: str = ""   # e.g. "v5litepod-16", "v5p-32", "" = CPU-only
    topology: str = ""           # e.g. "4x4"
    chips: int = 0               # chips attached to THIS worker VM
    slice_name: str = ""         # shared label grouping workers of one slice
    worker_index: int = 0        # index of this worker within its slice
    agent: bool = False          # host runs the push agent: excluded from
                                 # the SSH monitoring fan-out, liveness via
                                 # heartbeat lease (docs/ROBUSTNESS.md)

    def __post_init__(self) -> None:
        if not self.address:
            self.address = self.name


@dataclasses.dataclass
class Config:
    config_dir: Path = Path(os.path.expanduser(DEFAULT_CONFIG_DIR))
    db: DbConfig = dataclasses.field(default_factory=DbConfig)
    api: ApiConfig = dataclasses.field(default_factory=ApiConfig)
    app_server: AppServerConfig = dataclasses.field(default_factory=AppServerConfig)
    monitoring: MonitoringConfig = dataclasses.field(default_factory=MonitoringConfig)
    protection: ProtectionConfig = dataclasses.field(default_factory=ProtectionConfig)
    mailbot: MailbotConfig = dataclasses.field(default_factory=MailbotConfig)
    usage_logging: UsageLoggingConfig = dataclasses.field(default_factory=UsageLoggingConfig)
    job_scheduling: JobSchedulingConfig = dataclasses.field(default_factory=JobSchedulingConfig)
    alerting: AlertingConfig = dataclasses.field(default_factory=AlertingConfig)
    generation: GenerationConfig = dataclasses.field(default_factory=GenerationConfig)
    history: HistoryConfig = dataclasses.field(default_factory=HistoryConfig)
    accounting: AccountingConfig = dataclasses.field(default_factory=AccountingConfig)
    slo: SloConfig = dataclasses.field(default_factory=SloConfig)
    profiling: ProfilingConfig = dataclasses.field(default_factory=ProfilingConfig)
    agent: AgentConfig = dataclasses.field(default_factory=AgentConfig)
    ssh: SshConfig = dataclasses.field(default_factory=SshConfig)
    hosts: Dict[str, HostConfig] = dataclasses.field(default_factory=dict)

    # -- derived paths -----------------------------------------------------
    @property
    def db_path(self) -> str:
        return self.db.resolved_path(self.config_dir)

    @property
    def usage_log_dir(self) -> Path:
        return Path(self.usage_logging.log_dir.format(config_dir=str(self.config_dir)))

    @property
    def ssh_key_path(self) -> Path:
        return Path(self.ssh.key_path.format(config_dir=str(self.config_dir)))

    @property
    def profile_artifact_dir(self) -> Path:
        return Path(self.profiling.artifact_dir.format(
            config_dir=str(self.config_dir)))

    @property
    def flightrec_dir(self) -> Path:
        """Where the supervisor writes flight-recorder crash dumps."""
        return Path(self.config_dir) / "flightrec"

    @property
    def slices(self) -> Dict[str, List[HostConfig]]:
        """Group hosts by slice label, ordered by worker_index."""
        groups: Dict[str, List[HostConfig]] = {}
        for host in self.hosts.values():
            label = host.slice_name or host.name
            groups.setdefault(label, []).append(host)
        for members in groups.values():
            members.sort(key=lambda h: h.worker_index)
        return groups


_SECTION_MAP = {
    "db": "db",
    "api": "api",
    "app_server": "app_server",
    "monitoring_service": "monitoring",
    "protection_service": "protection",
    "usage_logging_service": "usage_logging",
    "job_scheduling_service": "job_scheduling",
    "alerting_service": "alerting",
    "generation_service": "generation",
    "history": "history",
    "accounting": "accounting",
    "slo": "slo",
    "profiling": "profiling",
    "agent": "agent",
    "ssh": "ssh",
}


def load_config(config_dir: Optional[os.PathLike] = None) -> Config:
    """Build a Config from TOML files under ``config_dir`` (all optional)."""
    directory = Path(
        config_dir
        or os.environ.get(ENV_CONFIG_DIR)
        or os.path.expanduser(DEFAULT_CONFIG_DIR)
    )
    cfg = Config(config_dir=directory)

    main_path = directory / MAIN_CONFIG_NAME
    if main_path.exists():
        data = _read_toml(main_path)
        for section_name, section_data in data.items():
            attr = _SECTION_MAP.get(section_name)
            if attr is None:
                raise ConfigurationError(
                    f"unknown section [{section_name}] in {main_path}"
                )
            if not isinstance(section_data, Mapping):
                raise ConfigurationError(f"[{section_name}] must be a table")
            _apply(getattr(cfg, attr), section_data, section_name)

    mailbot_path = directory / MAILBOT_CONFIG_NAME
    if mailbot_path.exists():
        data = _read_toml(mailbot_path)
        for section_name, section_data in data.items():
            if section_name != "mailbot":
                raise ConfigurationError(
                    f"unknown section [{section_name}] in {mailbot_path}"
                )
            if not isinstance(section_data, Mapping):
                raise ConfigurationError("[mailbot] must be a table")
            _apply(cfg.mailbot, section_data, "mailbot")

    hosts_path = directory / HOSTS_CONFIG_NAME
    if hosts_path.exists():
        data = _read_toml(hosts_path)
        hosts_table = data.get("hosts", {})
        if not isinstance(hosts_table, Mapping):
            raise ConfigurationError("[hosts] must be a table of tables")
        for name, host_data in hosts_table.items():
            host = HostConfig(name=name)
            _apply(host, host_data, f"hosts.{name}")
            host.__post_init__()
            cfg.hosts[name] = host

    return cfg


def _read_toml(path: Path) -> Dict[str, Any]:
    try:
        with open(path, "rb") as fh:
            return tomllib.load(fh)
    except tomllib.TOMLDecodeError as exc:
        raise ConfigurationError(f"{path}: {exc}") from exc


def write_default_configs(directory: Path, secret_key: str) -> None:
    """Materialize commented template configs (reference: config.py:12-68
    copies in-package templates with 0600 perms on first run)."""
    directory.mkdir(parents=True, exist_ok=True)
    main_path = directory / MAIN_CONFIG_NAME
    if not main_path.exists():
        main_path.write_text(_MAIN_TEMPLATE.format(secret_key=secret_key))
        main_path.chmod(0o600)
    hosts_path = directory / HOSTS_CONFIG_NAME
    if not hosts_path.exists():
        hosts_path.write_text(_HOSTS_TEMPLATE)
        hosts_path.chmod(0o600)
    mailbot_path = directory / MAILBOT_CONFIG_NAME
    if not mailbot_path.exists():
        mailbot_path.write_text(_MAILBOT_TEMPLATE)
        mailbot_path.chmod(0o600)


_MAIN_TEMPLATE = """\
# tpuhive main configuration
[api]
url_port = 1111
secret_key = "{secret_key}"

[monitoring_service]
enabled = true
interval_s = 2.0

[protection_service]
enabled = true
interval_s = 2.0
level = 1
notify_on_pty = true
notify_via_email = false
kill_mode = 0

[usage_logging_service]
enabled = true
interval_s = 2.0

[job_scheduling_service]
enabled = true
interval_s = 30.0
schedule_queued_when_free_mins = 30.0

[alerting_service]
enabled = true
interval_s = 5.0
# webhook_url = "https://hooks.example.com/tpuhive"
# webhook_timeout_s = 5.0
# webhook_retries = 2

[generation_service]
# continuous-batching inference gateway (docs/SERVING.md); enabling
# allocates the model + paged KV page pool at boot
enabled = false
# preset = "tiny"
# slots = 8           # per-dp-shard; total capacity = slots * mesh_dp
# mesh_dp = 1         # multi-chip serving (docs/SERVING.md): shard the
# mesh_tp = 1         # slot/page pool over dp, heads/ffn/vocab over tp
# checkpoint_path = ""  # orbax train_loop checkpoint dir; "" = init params
# paged = true        # false: contiguous per-slot cache rollback
# page_size = 16
# kv_pages = 0        # 0 = equal HBM to the contiguous layout
# paged_kernel = "auto"  # fused decode kernel: auto|on|off
# kv_quant = "auto"   # int8 KV pages + per-page scales: auto|on|off
# prefix_cache = "auto"  # radix shared-prefix page cache: auto|on|off
# prefix_min_tokens = 32
# prefill_chunk_tokens = 256  # per-tick prefill budget (chunked prefill)
# host_kv_bytes = 0   # KV-page tiering: host-RAM spill budget for cold
#                     # int8 pages (0 = off; docs/SERVING.md)
# speculative = "auto"  # draft-lane speculative decoding: auto|on|off
# draft_preset = ""     # "" = self-draft from truncated target layers
# draft_layers = 0      # self-draft depth (0 = half the target's layers)
# spec_tokens = 4       # draft proposals verified per tick
# queue_depth = 32
# max_new_tokens = 128
# max_concurrent_per_user = 4
# require_restriction = true
# ttft_slo_s = 2.0
# queue_wait_slo_s = 1.0
# request_ledger_size = 256   # GET /api/admin/requests ring bound
# flight_recorder = true      # per-tick black box + crash dumps on fatal
# flightrec_ticks = 512       # bounded tick-ring capacity
# flightrec_dumps = 8         # crash dumps kept in {{config_dir}}/flightrec

[history]
# in-process metrics history ring (docs/OBSERVABILITY.md "History, SLOs &
# flight recorder"); GET /api/admin/history answers 404 while disabled
enabled = true
# sample_interval_s = 5.0
# retention_s = 3600.0
# max_points = 720      # memory bound per series, independent of retention
# series = ""           # comma-separated allowlist ("" = shipped default)

[accounting]
# per-tenant chip-second / HBM-byte-second attribution
# (docs/OBSERVABILITY.md "Tenant accounting"); disabled = no meter, no
# tpuhive_tenant_* series, GET /api/admin/usage answers 404
enabled = true
# top_k_tenants = 8     # named tenants in the scrape; rest -> 'other'
# window_s = 3600.0
# dominance_share = 0.5

[slo]
# burn-rate SLO engine over the history store; disabled = no
# tpuhive_slo_* gauges and the slo_burn_* alert rules stay quiet
enabled = true
# budget_window_s = 3600.0
# availability_target = 0.999
# latency_target = 0.99

[profiling]
# on-demand jax.profiler captures via POST /api/admin/profile and the
# live-HBM snapshot at GET /api/admin/profile/memory (docs/OBSERVABILITY.md
# "Request tracing & profiling"); endpoints 404 while disabled
enabled = false
# artifact_dir = "{{config_dir}}/profiles"
# max_duration_s = 10.0
# default_duration_s = 1.0

[agent]
# push-based host membership (docs/ROBUSTNESS.md "Host membership &
# leases"): hosts running tpuhive-agent report over POST /api/agent/report
# and carry a heartbeat lease instead of being SSH-polled. The plane is
# off until a shared bearer token is set.
enabled = true
# token = ""               # shared agent bearer secret ("" = plane off)
# heartbeat_interval_s = 2.0
# suspect_after_s = 0.0    # 0 = 2x heartbeat_interval_s
# lease_ttl_s = 0.0        # 0 = 3x heartbeat_interval_s
# deregister_after_s = 900.0

[ssh]
timeout_s = 10.0
default_backend = "ssh"
# control-plane resilience (docs/ROBUSTNESS.md)
# num_retries = 1
# retry_backoff_base_s = 0.2
# retry_backoff_max_s = 5.0
# breaker_failure_threshold = 3
# breaker_cooldown_s = 30.0
# breaker_cooldown_jitter = 0.1
# breaker_half_open_probes = 1
"""

_HOSTS_TEMPLATE = """\
# tpuhive managed host inventory — one table per TPU VM worker.
# [hosts.my-v5e]
# address = "10.0.0.2"
# user = "tpuhive"
# accelerator_type = "v5litepod-8"
# topology = "2x4"
# chips = 8
# slice_name = "my-v5e"
# worker_index = 0
"""

_MAILBOT_TEMPLATE = """\
[mailbot]
smtp_server = ""
smtp_port = 587
smtp_login = ""
smtp_password = ""
notify_intruder = true
notify_admin = false
admin_email = ""
"""

# ---------------------------------------------------------------------------
_config: Optional[Config] = None


def get_config() -> Config:
    """Lazily-loaded process-wide config; reload with :func:`reset_config`."""
    global _config
    if _config is None:
        _config = load_config()
    return _config


def set_config(cfg: Config) -> None:
    global _config
    _config = cfg


def reset_config() -> None:
    global _config
    _config = None
