"""Bounded ring-buffer span tracer.

Covers the request/tick/span tracing the reference never had (SURVEY.md §5):
API request dispatch, service ticks, monitor updates, transport fan-outs and
job spawns each record a :class:`Span`. Spans carry parent ids via a
per-thread stack, so a probe round-trip initiated inside a monitoring tick
shows up as a child of that tick without any explicit plumbing.

Completed spans land in a fixed-capacity ring buffer (old spans evicted,
O(1) append, no unbounded growth on a busy server) and are dumped by
``GET /api/admin/traces``. Each span gets a process-wide monotone sequence
number at completion time; the dump is ordered by it, so consumers see
monotonically non-decreasing end timestamps even when threads interleave.
"""
from __future__ import annotations

import collections
import itertools
import logging
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Deque, Dict, Iterator, List, Optional

from ..utils import lockwitness

DEFAULT_CAPACITY = 512


@dataclass
class Span:
    span_id: str
    parent_id: Optional[str]
    name: str
    kind: str
    #: wall-clock start (unix seconds) — for humans correlating with logs
    start_ts: float
    #: perf_counter at start — for exact durations
    _started: float = field(repr=False, default=0.0)
    duration_s: Optional[float] = None
    status: str = "ok"
    attrs: Dict[str, str] = field(default_factory=dict)
    #: completion sequence number (monotone across the process)
    seq: int = -1

    def to_dict(self) -> Dict:
        return {
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "kind": self.kind,
            "startTs": round(self.start_ts, 6),
            "durationMs": (round(self.duration_s * 1000, 3)
                           if self.duration_s is not None else None),
            "status": self.status,
            "attrs": dict(self.attrs),
            "seq": self.seq,
        }


class SpanTracer:
    """Thread-safe tracer: start/end pairs or the :meth:`span` context
    manager; completed spans retained in a bounded ring buffer."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._lock = lockwitness.Lock("SpanTracer._lock")
        self._finished: Deque[Span] = collections.deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._seq = itertools.count(1)
        self._local = threading.local()

    # -- thread-local parent stack ------------------------------------------
    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_span(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    # -- explicit API --------------------------------------------------------
    def start_span(self, name: str, kind: str = "internal",
                   **attrs: object) -> Span:
        parent = self.current_span()
        with self._lock:
            span_id = f"{next(self._ids):08x}"
        span = Span(
            span_id=span_id,
            parent_id=parent.span_id if parent else None,
            name=name,
            kind=kind,
            start_ts=time.time(),
            _started=time.perf_counter(),
            attrs={key: str(value) for key, value in attrs.items()},
        )
        self._stack().append(span)
        return span

    def end_span(self, span: Span, status: str = "ok",
                 **attrs: object) -> Span:
        span.duration_s = time.perf_counter() - span._started
        span.status = status
        for key, value in attrs.items():
            span.attrs[key] = str(value)
        stack = self._stack()
        if span in stack:           # tolerate out-of-order ends across threads
            while stack and stack[-1] is not span:
                stack.pop()
            if stack:
                stack.pop()
        with self._lock:
            span.seq = next(self._seq)
            self._finished.append(span)
        return span

    def record_span(self, name: str, kind: str = "internal", *,
                    start_ts: float, duration_s: float, status: str = "ok",
                    parent_id: Optional[str] = None,
                    **attrs: object) -> Span:
        """Append an already-completed span with explicit timing.

        For phases whose boundaries are observed after the fact from a
        different thread than the one that "owns" them (the serving
        engine's queue/prefill/decode phases complete inside the pump
        thread): a start/end pair would push onto the pump thread's parent
        stack and misparent every span the pump opens while a request is
        in flight. Recording retrospectively keeps the per-thread stacks
        untouched while the ring buffer still gets the span — ordering by
        completion ``seq`` like every other span."""
        with self._lock:
            span_id = f"{next(self._ids):08x}"
        span = Span(
            span_id=span_id,
            parent_id=parent_id,
            name=name,
            kind=kind,
            start_ts=start_ts,
            duration_s=duration_s,
            status=status,
            attrs={key: str(value) for key, value in attrs.items()},
        )
        with self._lock:
            span.seq = next(self._seq)
            self._finished.append(span)
        return span

    # -- context-manager API -------------------------------------------------
    @contextmanager
    def span(self, name: str, kind: str = "internal",
             **attrs: object) -> Iterator[Span]:
        span = self.start_span(name, kind, **attrs)
        try:
            yield span
        except BaseException:
            self.end_span(span, status="error")
            raise
        else:
            self.end_span(span, status=span.status)

    # -- reading -------------------------------------------------------------
    def recent(self, limit: Optional[int] = None,
               kind: Optional[str] = None) -> List[Dict]:
        """Completed spans, oldest first (monotone ``seq``/end order)."""
        with self._lock:
            spans = list(self._finished)
        if kind is not None:
            spans = [span for span in spans if span.kind == kind]
        if limit is not None and limit >= 0:
            spans = spans[-limit:]
        return [span.to_dict() for span in spans]

    def __len__(self) -> int:
        with self._lock:
            return len(self._finished)

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


class SpanLogFilter(logging.Filter):
    """Injects the id of the thread's current span into every log record as
    ``record.span_id`` (empty string when no span is open), so a log format
    containing ``%(span_id)s`` makes ``log.exception`` lines joinable
    against the ``GET /api/admin/traces`` dump — the tick or request a
    traceback happened inside is one grep away.

    Attach to a *handler* (cli.setup_logging does), so every record passing
    through it carries the attribute regardless of originating logger.
    """

    def __init__(self, tracer: Optional[SpanTracer] = None) -> None:
        super().__init__()
        self._tracer = tracer

    def filter(self, record: logging.LogRecord) -> bool:
        tracer = self._tracer
        if tracer is None:
            # late-bound so the filter follows tracer swaps in tests
            from . import get_tracer

            tracer = get_tracer()
        span = tracer.current_span()
        record.span_id = span.span_id if span is not None else ""
        return True
