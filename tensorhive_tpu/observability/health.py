"""Machine-readable liveness + readiness, the probe surface an orchestrator
points at (JIRIAF's virtual-kubelet integration provisions against exactly
this kind of per-node health signal, PAPERS arxiv 2502.18596).

Two distinct questions, two endpoints (controllers/observability.py):

* **liveness** (``GET /api/healthz``) — "is the process serving requests?"
  Trivially yes if the handler runs; carries uptime + version so a flapping
  restart loop is visible from the probe alone.
* **readiness** (``GET /api/readyz``) — "should traffic/work be routed
  here?" Component checks with a JSON reason list: the DB answers a real
  query, every registered daemon service is alive AND has ticked within 3x
  its interval (a wedged tick is as dead as a dead thread — it just hasn't
  admitted it yet), and the telemetry probe round is fresh when hosts are
  managed, and every membership lease is live (a silent agent host must
  not receive routed work). Any failing component flips the endpoint to
  503.

Everything takes an explicit ``now`` and manager so tests drive it on a
fake clock with stub services; the controllers call the zero-argument form.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from .metrics import PROCESS_START_TS

#: a service is stale once it has gone this many intervals without a tick
STALE_INTERVALS = 3.0


def liveness() -> Dict:
    from .. import __version__

    return {
        "status": "ok",
        "version": __version__,
        "uptimeS": round(time.time() - PROCESS_START_TS, 3),
    }


def _component(name: str, ok: bool, reason: str = "") -> Dict:
    entry: Dict = {"component": name, "ok": ok}
    if reason:
        entry["reason"] = reason
    return entry


def check_db() -> Dict:
    """The DB must answer a real query — not just exist as a file handle."""
    from ..db.engine import get_engine

    try:
        value = get_engine().scalar("SELECT 1")
    except Exception as exc:  # sqlite3 raises several unrelated types
        return _component("db", False, f"query failed: {exc}")
    if value != 1:
        return _component("db", False, f"SELECT 1 returned {value!r}")
    return _component("db", True)


def check_service(service, now: float) -> Dict:
    """One registered daemon: thread alive and ticking within
    ``STALE_INTERVALS`` x its interval. The freshness reference is the last
    completed tick, or the run-loop start for a service still inside its
    first tick — so a tick that hangs forever goes stale instead of hiding
    behind ``is_alive()``."""
    name = f"service:{service.name}"
    if not service.is_alive():
        return _component(name, False, "thread not alive")
    stale_after = STALE_INTERVALS * float(service.interval_s)
    reference = service.last_tick_ts or service.run_started_ts
    if reference is None:
        return _component(name, False, "run loop not entered yet")
    age = now - reference
    if age > stale_after:
        return _component(
            name, False,
            f"no tick for {age:.1f}s (> {STALE_INTERVALS:.0f}x "
            f"{service.interval_s:g}s interval)")
    return _component(name, True)


def check_transport_breakers(transport_manager) -> Dict:
    """No open circuit breakers: an open breaker means part of the fleet is
    unreachable from this control plane — traffic/work routed here would be
    scheduled against hosts it cannot contact."""
    open_hosts = transport_manager.open_circuit_hosts()
    if open_hosts:
        return _component(
            "transport", False,
            f"circuit open for {len(open_hosts)} host(s): "
            f"{', '.join(open_hosts)}")
    return _component("transport", True)


def check_serving() -> Optional[Dict]:
    """The serving data plane, when this process has one: draining or a
    crash-looped/unavailable engine means generate traffic must not be
    routed here (docs/ROBUSTNESS.md "Serving data plane"). Returns None —
    component omitted — when no supervisor owns a serving plane and
    nothing is draining (processes that never serve stay unaffected)."""
    from ..serving import get_engine, get_serving_state, \
        get_unavailable_reason

    engine = get_engine()
    state = get_serving_state()
    if engine is not None:
        if getattr(engine, "draining", False):
            stats = engine.stats()
            in_flight = stats["slotsBusy"] + stats["queueDepth"]
            return _component(
                "serving", False,
                f"draining ({in_flight} request(s) still in flight)")
        return _component("serving", True)
    if not state["supervisor_active"]:
        return None
    reason = get_unavailable_reason() or "engine not published"
    if state["crash_loop"]:
        return _component("serving", False, f"crash loop: {reason}")
    return _component("serving", False, f"engine unavailable: {reason}")


def check_membership(infrastructure_manager) -> Optional[Dict]:
    """Host membership leases (docs/ROBUSTNESS.md "Host membership &
    leases"): a suspect or expired lease means part of the fleet has gone
    silent — work routed here would be scheduled against hosts whose agents
    stopped heartbeating. Deregistered tombstones and admin drains do NOT
    flip readiness (both are resolved/intentional states), but draining
    hosts are named in the reason so the probe surface shows them. Returns
    None — component omitted — when no hosts are tracked at all."""
    leases = infrastructure_manager.host_leases()
    if not leases:
        return None
    silent = sorted(host for host, lease in leases.items()
                    if lease["state"] in ("suspect", "unreachable"))
    draining = sorted(host for host, lease in leases.items()
                      if lease["draining"] and lease["state"] == "live")
    if silent:
        reason = f"lease suspect/expired for: {', '.join(silent)}"
        if draining:
            reason += f"; draining: {', '.join(draining)}"
        return _component("membership", False, reason)
    if draining:
        return _component("membership", True,
                          f"draining: {', '.join(draining)}")
    return _component("membership", True)


def check_probe_freshness(now: float, interval_s: float) -> Dict:
    """Telemetry freshness off the registry gauge the probe layer stamps
    after every round — no scrape round-trip, same truth Prometheus sees."""
    from . import get_registry

    family = get_registry().get("tpuhive_probe_last_round_timestamp_seconds")
    last_ts = 0.0
    if family is not None:
        children = family.children()
        if children:
            last_ts = children[0][1].value
    if last_ts <= 0:
        return _component("probe", False, "no probe round completed yet")
    age = now - last_ts
    stale_after = STALE_INTERVALS * interval_s
    if age > stale_after:
        return _component(
            "probe", False,
            f"last probe round {age:.1f}s ago (> {stale_after:g}s)")
    return _component("probe", True)


def readiness(manager=None, now: Optional[float] = None,
              ) -> Tuple[bool, List[Dict]]:
    """(ready, component breakdown). ``manager`` defaults to the process
    manager if one was set — a process without a manager (bare API in
    tests/tools) is ready when its DB answers."""
    if now is None:
        now = time.time()
    if manager is None:
        from ..core.managers import manager as manager_module

        manager = manager_module._instance
    components = [check_db()]
    monitoring = None
    if manager is not None and manager.service_manager is not None:
        from ..core.services.monitoring import MonitoringService

        for service in manager.service_manager.services:
            components.append(check_service(service, now))
            if isinstance(service, MonitoringService):
                monitoring = service
    if monitoring is not None and getattr(manager.config, "hosts", None):
        # probe freshness only binds when there are hosts to probe; an
        # empty inventory has no round to be stale
        components.append(check_probe_freshness(now, monitoring.interval_s))
    if (manager is not None and getattr(manager.config, "hosts", None)
            and getattr(manager, "transport_manager", None) is not None):
        components.append(check_transport_breakers(manager.transport_manager))
    if (manager is not None
            and getattr(manager, "infrastructure_manager", None) is not None):
        membership = check_membership(manager.infrastructure_manager)
        if membership is not None:
            components.append(membership)
    serving_component = check_serving()
    if serving_component is not None:
        components.append(serving_component)
    ready = all(component["ok"] for component in components)
    return ready, components
