"""In-process metrics: counters, gauges, histograms + Prometheus exposition.

Design constraints that shaped this (vs. vendoring prometheus_client, which
the image does not ship):

* **Thread-safe under concurrent writers.** Every service daemon, the
  transport fan-out pool, and API request threads write concurrently; each
  metric family serializes its own children behind one lock, so hot paths
  on different families never contend with each other.
* **Idempotent registration.** ``registry.counter(name, ...)`` returns the
  existing family when the name is already registered (services are
  constructed many times in tests); re-registering with a different type or
  label set is a programming error and raises.
* **Fixed bucket boundaries.** Histograms are Prometheus-style cumulative
  buckets chosen at registration; observation is O(log buckets) via bisect.
  A quantile estimator (linear interpolation inside the bucket, the same
  model PromQL's ``histogram_quantile`` uses) backs the p50/p95 service
  introspection without storing raw samples.

Exposition follows the Prometheus text format (version 0.0.4): HELP/TYPE
headers, ``_bucket``/``_sum``/``_count`` expansion for histograms, label
escaping for ``\\``, ``"`` and newlines. Families render sorted by name and
children by label values, so output is deterministic (golden-testable).
"""
from __future__ import annotations

import logging
import math
import os
import threading
import time
from bisect import bisect_left

from ..utils import lockwitness
from typing import (
    Callable,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

log = logging.getLogger(__name__)

#: wall-clock import time of this module ≈ process start (the registry is
#: imported by every entry point before any work happens) — backs the
#: process uptime gauge and the readiness payload
PROCESS_START_TS = time.time()

#: default latency buckets (seconds): 1 ms .. 60 s, roughly log-spaced —
#: covers API dispatch (~ms) through SSH probe round-trips (~100 ms) and
#: scheduler ticks that may take tens of seconds on large clusters.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _format_value(value: float) -> str:
    """Render a sample value: integral floats collapse to integers (counter
    increments stay readable), non-finite values use Prometheus spelling."""
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if math.isnan(value):
        return "NaN"
    as_float = float(value)
    if as_float.is_integer() and abs(as_float) < 1e15:
        return str(int(as_float))
    return repr(as_float)


def _escape_label_value(value: str) -> str:
    return (value.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"'))


def _escape_help(value: str) -> str:
    return value.replace("\\", r"\\").replace("\n", r"\n")


def _render_labels(names: Sequence[str], values: Sequence[str],
                   extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [f'{name}="{_escape_label_value(value)}"'
             for name, value in zip(names, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{_escape_label_value(extra[1])}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


class Counter:
    """Monotonically increasing value (one child of a counter family)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock) -> None:
        self._lock = lock
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        self._value = 0.0


class Gauge:
    """Value that can go up and down (one child of a gauge family)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock) -> None:
        self._lock = lock
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        self._value = 0.0


class Histogram:
    """Fixed-bucket cumulative histogram (one child of a histogram family).

    Standalone use is supported (``Histogram()`` with no arguments) so code
    can keep a private per-instance histogram — Service latency
    introspection does this to stay isolated from other instances sharing
    the same registry label set.
    """

    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count", "_max")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS,
                 lock: Optional[threading.Lock] = None) -> None:
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError("bucket bounds must be strictly increasing")
        self._lock = lock or lockwitness.Lock("Histogram._lock",
                                              export_wait=False)
        self.buckets = bounds
        self._counts = [0] * (len(bounds) + 1)   # +1 for the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._max: Optional[float] = None

    def observe(self, value: float) -> None:
        value = float(value)
        index = bisect_left(self.buckets, value)
        with self._lock:
            self._counts[index] += 1
            self._sum += value
            self._count += 1
            if self._max is None or value > self._max:
                self._max = value

    def snapshot(self) -> Tuple[List[int], float, int, Optional[float]]:
        """(per-bucket counts incl. +Inf, sum, count, max) — consistent."""
        with self._lock:
            return list(self._counts), self._sum, self._count, self._max

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def max(self) -> Optional[float]:
        with self._lock:
            return self._max

    def _reset(self) -> None:
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._max = None

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the q-quantile (0..1) from bucket counts — the same
        linear-interpolation-within-bucket model as PromQL's
        ``histogram_quantile``. Returns None with no observations. The
        estimate is clamped to the observed max so a +Inf-bucket hit cannot
        report an unbounded latency."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile must be within [0, 1]")
        counts, _, total, observed_max = self.snapshot()
        if total == 0:
            return None
        rank = q * total
        cumulative = 0.0
        for index, bucket_count in enumerate(counts):
            if bucket_count == 0:
                continue
            if cumulative + bucket_count >= rank:
                fraction = (rank - cumulative) / bucket_count
                lower = 0.0 if index == 0 else self.buckets[index - 1]
                if index < len(self.buckets):
                    estimate = lower + (self.buckets[index] - lower) * fraction
                else:               # +Inf bucket: no upper bound to lerp to
                    estimate = observed_max if observed_max is not None else lower
                if observed_max is not None:
                    estimate = min(estimate, observed_max)
                return estimate
            cumulative += bucket_count
        return observed_max


_KINDS = ("counter", "gauge", "histogram")


class MetricFamily:
    """A named metric plus its labeled children."""

    def __init__(self, kind: str, name: str, help_text: str,
                 label_names: Sequence[str],
                 buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        assert kind in _KINDS
        self.kind = kind
        self.name = name
        self.help_text = help_text
        self.label_names = tuple(label_names)
        self.bucket_bounds = tuple(buckets)
        self._lock = lockwitness.Lock("MetricFamily._lock",
                                      export_wait=False)
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, **labels: str):
        """Child for one label-value combination (created on first use)."""
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name} expects labels {self.label_names}, got "
                f"{tuple(sorted(labels))}"
            )
        key = tuple(str(labels[name]) for name in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def _make_child(self):
        # children share the family lock: one uncontended lock per family
        # keeps memory per child at two slots and render() consistent
        if self.kind == "counter":
            return Counter(self._lock)
        if self.kind == "gauge":
            return Gauge(self._lock)
        return Histogram(self.bucket_bounds, lock=self._lock)

    def _unlabeled(self):
        """The single child of a label-less family."""
        if self.label_names:
            raise ValueError(f"{self.name} requires labels {self.label_names}")
        return self.labels()

    # label-less convenience: family.inc() / family.set() / family.observe()
    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    def set(self, value: float) -> None:
        self._unlabeled().set(value)

    def dec(self, amount: float = 1.0) -> None:
        self._unlabeled().dec(amount)

    def observe(self, value: float) -> None:
        self._unlabeled().observe(value)

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    def reset_values(self) -> None:
        """Zero every child's value IN PLACE — instrumented modules hold
        child references captured at import, so dropping children would
        silently orphan their writes."""
        with self._lock:
            for child in self._children.values():
                child._reset()

    def retain_children(self, keys: Iterable[Tuple[str, ...]]) -> None:
        """Drop every child whose label tuple is not in ``keys`` — the
        cardinality bound for collector-owned families. Only valid for
        families whose SOLE writer is a render-time collector (e.g. the
        ``tpuhive_tenant_*`` accounting exports): instrumented modules
        holding child references would be silently orphaned, which is
        exactly why :meth:`reset_values` never drops children."""
        keep = set(keys)
        with self._lock:
            for key in list(self._children):
                if key not in keep:
                    del self._children[key]


class MetricsRegistry:
    """Thread-safe collection of metric families + Prometheus rendering."""

    def __init__(self) -> None:
        self._lock = lockwitness.Lock("MetricsRegistry._lock",
                                      export_wait=False)
        self._families: Dict[str, MetricFamily] = {}
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []

    # -- registration (idempotent) ------------------------------------------
    def _register(self, kind: str, name: str, help_text: str,
                  label_names: Sequence[str],
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> MetricFamily:
        with self._lock:
            existing = self._families.get(name)
            if existing is not None:
                if existing.kind != kind or existing.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.label_names}"
                    )
                return existing
            family = MetricFamily(kind, name, help_text, label_names, buckets)
            self._families[name] = family
            return family

    def counter(self, name: str, help_text: str = "",
                labels: Sequence[str] = ()) -> MetricFamily:
        return self._register("counter", name, help_text, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: Sequence[str] = ()) -> MetricFamily:
        return self._register("gauge", name, help_text, labels)

    def histogram(self, name: str, help_text: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> MetricFamily:
        return self._register("histogram", name, help_text, labels, buckets)

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[MetricFamily]:
        with self._lock:
            return [self._families[name] for name in sorted(self._families)]

    def reset_values(self) -> None:
        """Drop every child's value but keep families registered — handles
        instrumented modules that captured family references at import."""
        for family in self.families():
            family.reset_values()

    # -- lazy collectors ----------------------------------------------------
    def register_collector(
            self, collector: Callable[["MetricsRegistry"], None]) -> None:
        """Register a callback run at the START of every :meth:`render` —
        for values that are cheap to read but pointless to poll (process
        RSS, alert firing state): scrapes see fresh numbers, idle processes
        pay nothing. Registration is idempotent per callable."""
        with self._lock:
            if collector not in self._collectors:
                self._collectors.append(collector)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for collector in collectors:
            try:
                collector(self)
            except Exception:
                # a broken collector must not take down the whole scrape;
                # logged so the breakage is visible (TH-E)
                log.exception("metrics collector %r failed", collector)

    # -- exposition ---------------------------------------------------------
    def render(self) -> str:
        """Prometheus text format 0.0.4; deterministic ordering."""
        self._run_collectors()
        lines: List[str] = []
        for family in self.families():
            children = family.children()
            if not children:
                continue
            if family.help_text:
                lines.append(f"# HELP {family.name} "
                             f"{_escape_help(family.help_text)}")
            lines.append(f"# TYPE {family.name} {family.kind}")
            for label_values, child in children:
                if family.kind == "histogram":
                    lines.extend(self._render_histogram(
                        family, label_values, child))
                else:
                    labels = _render_labels(family.label_names, label_values)
                    lines.append(
                        f"{family.name}{labels} {_format_value(child.value)}")
        return "\n".join(lines) + "\n" if lines else ""

    @staticmethod
    def _render_histogram(family: MetricFamily, label_values: Sequence[str],
                          child: Histogram) -> Iterable[str]:
        counts, total_sum, count, _ = child.snapshot()
        cumulative = 0
        for bound, bucket_count in zip(family.bucket_bounds, counts):
            cumulative += bucket_count
            labels = _render_labels(family.label_names, label_values,
                                    extra=("le", _format_value(bound)))
            yield f"{family.name}_bucket{labels} {cumulative}"
        labels = _render_labels(family.label_names, label_values,
                                extra=("le", "+Inf"))
        yield f"{family.name}_bucket{labels} {count}"
        plain = _render_labels(family.label_names, label_values)
        yield f"{family.name}_sum{plain} {_format_value(total_sum)}"
        yield f"{family.name}_count{plain} {count}"


# -- build info + process self-metrics ---------------------------------------

def _read_rss_bytes() -> Optional[float]:
    """Current resident set from /proc/self/status (None where /proc is not
    a Linux procfs — macOS dev laptops)."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) * 1024.0
    except (OSError, ValueError, IndexError):
        return None
    return None


def _count_open_fds() -> Optional[float]:
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return None


def register_process_metrics(registry: "MetricsRegistry",
                             version: str) -> None:
    """Register ``tpuhive_build_info{version}`` plus process self-metrics
    (RSS, thread count, uptime, open fds where /proc exists), all refreshed
    lazily by a collector at exposition time — so scrapes and readiness
    checks can correlate behavior with the running build without any
    background sampler thread."""
    build_info = registry.gauge(
        "tpuhive_build_info",
        "Constant 1, labeled with the running tpuhive version.",
        labels=("version",))
    rss = registry.gauge(
        "tpuhive_process_resident_memory_bytes",
        "Resident set size of this process (from /proc/self/status).")
    threads = registry.gauge(
        "tpuhive_process_threads",
        "Live Python threads in this process.")
    uptime = registry.gauge(
        "tpuhive_process_uptime_seconds",
        "Seconds since the observability layer was imported.")
    open_fds = registry.gauge(
        "tpuhive_process_open_fds",
        "Open file descriptors (from /proc/self/fd; absent without procfs).")

    def _collect(_registry: "MetricsRegistry") -> None:
        # set inside the collector (not once at registration) so
        # reset_values() in tests cannot leave a stale zero behind
        build_info.labels(version=version).set(1.0)
        rss_bytes = _read_rss_bytes()
        if rss_bytes is not None:
            rss.set(rss_bytes)
        threads.set(float(threading.active_count()))
        uptime.set(time.time() - PROCESS_START_TS)
        fds = _count_open_fds()
        if fds is not None:
            open_fds.set(fds)

    registry.register_collector(_collect)


def parse_rendered(text: str) -> Mapping[str, float]:
    """Parse exposition text back into {sample-line-name+labels: value} —
    test helper so assertions don't regex the format by hand."""
    samples: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        key, _, raw = line.rpartition(" ")
        samples[key] = float(raw)
    return samples
