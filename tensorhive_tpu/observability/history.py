"""In-process metrics history: a fixed-memory ring TSDB over the registry.

Every observability layer before this one is point-in-time: a scrape
(PR 1), an alert evaluation (PR 4) or a ledger dump (PR 10) answers "what
is true NOW". The autoscaling loop ROADMAP item 1 plans ("scale-out on
*sustained* queue-wait SLO breach") and the SLO burn-rate engine
(:mod:`.slo`) both need *windowed* history — retained samples, not
instantaneous gauges. This module is that substrate:

* :class:`MetricsHistory` — a thread-safe ring store sampling a
  configurable **allowlist** of registry series (never the whole registry:
  per-slot gauges and histogram buckets would multiply without bound).
  Samples land in time-aligned windows holding ``min/mean/max/last`` plus
  the window's first value, so memory is ``max_points`` windows per series
  — bounded by construction and *independent of retention*: a longer
  ``retention_s`` coarsens the windows instead of growing the store.
* series specs — one string names one series::

      tpuhive_generate_queue_depth                    # family (children sum)
      tpuhive_generate_requests_total{outcome=failed} # one labeled child
      tpuhive_generate_ttft_seconds:count             # histogram count
      tpuhive_generate_ttft_seconds:sum               # histogram sum
      tpuhive_generate_ttft_seconds:le:2.0            # observations <= bound
                                                      # (snaps up to the
                                                      # nearest bucket bound)

  The ``:le:`` form is what lets the SLO engine read "good events" straight
  off a latency histogram (the same cumulative-bucket model PromQL's
  ``histogram_quantile`` uses).
* :func:`MetricsHistory.increase` — counter-reset-aware growth over a
  lookback window (the PR 4 ``increase`` rule semantics: a value drop means
  the process restarted, so the post-reset value counts from zero) — the
  primitive burn rates are computed from.

Reading never *creates* registry children (a typo'd allowlist entry must
not mint empty series into every scrape) and sampling takes one lock per
call, far off any hot path — the :class:`~tensorhive_tpu.core.services
.history.HistoryService` daemon drives it every ``[history]
sample_interval_s`` seconds. Queryable at ``GET /api/admin/history``
(docs/OBSERVABILITY.md "History, SLOs & flight recorder").
"""
from __future__ import annotations

import logging
import time
from bisect import bisect_left
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from .metrics import Counter, Gauge, Histogram, MetricsRegistry

from ..utils import lockwitness

log = logging.getLogger(__name__)

#: shipped retention / resolution: one hour of history in 720 windows of
#: 5 s each — a few hundred bytes per series per window, so even a few
#: dozen allowlisted series stay well under a megabyte
DEFAULT_RETENTION_S = 3600.0
DEFAULT_MAX_POINTS = 720

_MODES = ("value", "count", "sum", "le")


class SeriesSpec:
    """One parsed allowlist entry (see the module docstring grammar)."""

    __slots__ = ("raw", "name", "labels", "mode", "bound")

    def __init__(self, raw: str, name: str, labels: Dict[str, str],
                 mode: str, bound: Optional[float]) -> None:
        self.raw = raw
        self.name = name
        self.labels = labels
        self.mode = mode
        self.bound = bound


def parse_series(spec: str) -> SeriesSpec:
    """Parse ``name[{k=v,...}][:count|:sum|:le:<bound>]``; raises
    ``ValueError`` on malformed specs so a config typo fails loudly at
    boot instead of silently recording nothing."""
    raw = spec.strip()
    rest = raw
    labels: Dict[str, str] = {}
    if "{" in rest:
        if not rest.rstrip(":countsumle.0123456789").endswith("}") \
                and "}" not in rest:
            raise ValueError(f"series spec {raw!r}: unterminated labels")
        head, _, tail = rest.partition("{")
        body, closed, suffix = tail.partition("}")
        if not closed:
            raise ValueError(f"series spec {raw!r}: unterminated labels")
        for pair in body.split(","):
            if not pair.strip():
                continue
            key, eq, value = pair.partition("=")
            if not eq or not key.strip():
                raise ValueError(
                    f"series spec {raw!r}: labels must be k=v pairs")
            labels[key.strip()] = value.strip().strip('"')
        rest = head + suffix
    name, _, mode_part = rest.partition(":")
    name = name.strip()
    if not name:
        raise ValueError(f"series spec {raw!r}: empty metric name")
    mode, bound = "value", None
    if mode_part:
        pieces = mode_part.split(":")
        mode = pieces[0]
        if mode not in _MODES or mode == "value":
            raise ValueError(
                f"series spec {raw!r}: unknown mode {mode!r} "
                "(count|sum|le:<bound>)")
        if mode == "le":
            if len(pieces) != 2:
                raise ValueError(
                    f"series spec {raw!r}: le needs exactly one bound")
            try:
                bound = float(pieces[1])
            except ValueError:
                raise ValueError(
                    f"series spec {raw!r}: le bound {pieces[1]!r} is not "
                    "a number") from None
        elif len(pieces) != 1:
            raise ValueError(f"series spec {raw!r}: trailing garbage")
    return SeriesSpec(raw, name, labels, mode, bound)


def read_series(registry: MetricsRegistry,
                spec: SeriesSpec) -> Optional[float]:
    """Current value of one series, or None while it has no signal (family
    unregistered, no matching children, histogram mode on a non-histogram).
    Matching children are summed; label filters are subset matches —
    exactly the AlertEngine read semantics, and like it this never creates
    children."""
    family = registry.get(spec.name)
    if family is None:
        return None
    total = 0.0
    matched = False
    for label_values, child in family.children():
        labels = dict(zip(family.label_names, label_values))
        if any(labels.get(k) != v for k, v in spec.labels.items()):
            continue
        if isinstance(child, Histogram):
            if spec.mode == "sum":
                total += child.sum
            elif spec.mode == "le":
                counts, _, count, _ = child.snapshot()
                index = bisect_left(child.buckets, spec.bound)
                if index >= len(child.buckets):
                    total += count      # bound past +Inf: everything counts
                else:
                    total += sum(counts[:index + 1])
            else:                       # "value" and "count" both read count
                total += child.count
        elif isinstance(child, (Counter, Gauge)):
            if spec.mode != "value":
                return None     # :count/:sum/:le only mean something on a
                                # histogram — a mismatched spec is no signal
            total += child.value
        else:               # pragma: no cover - no other child kinds exist
            continue
        matched = True
    return total if matched else None


class _Window:
    """One downsample window: min/mean/max/last plus the first value (the
    increase() baseline inside the window)."""

    __slots__ = ("start", "first", "last", "vmin", "vmax", "vsum", "count")

    def __init__(self, start: float, value: float) -> None:
        self.start = start
        self.first = value
        self.last = value
        self.vmin = value
        self.vmax = value
        self.vsum = value
        self.count = 1

    def add(self, value: float) -> None:
        self.last = value
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        self.vsum += value
        self.count += 1


class MetricsHistory:
    """Thread-safe fixed-memory history over an allowlist of registry
    series. ``sample(now)`` is driven by the HistoryService (or a fake
    clock in tests); readers get consistent snapshots under the same
    lock."""

    def __init__(self, series: Sequence[str],
                 registry: Optional[MetricsRegistry] = None,
                 retention_s: float = DEFAULT_RETENTION_S,
                 max_points: int = DEFAULT_MAX_POINTS) -> None:
        if retention_s <= 0:
            raise ValueError(f"retention_s must be > 0, got {retention_s}")
        if max_points < 1:
            raise ValueError(f"max_points must be >= 1, got {max_points}")
        if registry is None:
            from . import get_registry

            registry = get_registry()
        self._registry = registry
        self.retention_s = float(retention_s)
        self.max_points = int(max_points)
        #: window width: retention spread over the point budget — the
        #: memory-bound-independent-of-retention invariant
        self.window_s = self.retention_s / self.max_points
        self._specs: List[SeriesSpec] = []
        seen = set()
        for raw in series:
            spec = parse_series(raw)
            if spec.raw in seen:
                continue
            seen.add(spec.raw)
            self._specs.append(spec)
        self._lock = lockwitness.Lock("MetricsHistory._lock")
        self._data: Dict[str, Deque[_Window]] = {
            spec.raw: deque(maxlen=self.max_points) for spec in self._specs}
        self.samples_taken = 0

    # -- writing ------------------------------------------------------------
    def sample(self, now: Optional[float] = None) -> int:
        """Read every allowlisted series once and fold each value into its
        time-aligned window; evicts windows past retention. Returns how
        many series produced a value this pass."""
        if now is None:
            now = time.time()
        # refresh collector-fed gauges (process RSS, alert firing state)
        # exactly like render() does, so sampling doesn't depend on scrape
        # traffic to materialize those series
        self._registry._run_collectors()
        readings = [(spec, read_series(self._registry, spec))
                    for spec in self._specs]
        sampled = 0
        start = (now // self.window_s) * self.window_s
        cutoff = now - self.retention_s
        with self._lock:
            for spec, value in readings:
                if value is None:
                    continue
                sampled += 1
                windows = self._data[spec.raw]
                if windows and windows[-1].start >= start:
                    # same window (or a clock step backwards): fold in
                    windows[-1].add(value)
                else:
                    windows.append(_Window(start, value))
                while windows and windows[0].start + self.window_s < cutoff:
                    windows.popleft()
            self.samples_taken += 1
            points = sum(len(w) for w in self._data.values())
        _SAMPLES_TOTAL.inc()
        _SERIES_GAUGE.set(float(sampled))
        _POINTS_GAUGE.set(float(points))
        return sampled

    # -- reading ------------------------------------------------------------
    def series_names(self) -> List[str]:
        return [spec.raw for spec in self._specs]

    def query(self, series: Optional[Sequence[str]] = None,
              since: Optional[float] = None,
              step: Optional[float] = None) -> Dict[str, List[Dict]]:
        """Downsampled points per series, oldest first. ``since`` drops
        windows ending before it; ``step`` re-buckets into coarser windows
        (clamped to at least the native window width). Unknown-but-
        well-formed series answer an empty list — the allowlist is the
        contract, not the query."""
        if series is None:
            wanted = [spec.raw for spec in self._specs]
        else:
            wanted = [parse_series(raw).raw for raw in series]
        width = self.window_s if step is None else max(float(step),
                                                      self.window_s)
        result: Dict[str, List[Dict]] = {}
        with self._lock:
            for raw in wanted:
                windows = self._data.get(raw)
                if windows is None:
                    result[raw] = []
                    continue
                buckets: List[_Window] = []
                for window in windows:
                    if since is not None and \
                            window.start + self.window_s <= since:
                        continue
                    start = (window.start // width) * width
                    if buckets and buckets[-1].start == start:
                        merged = buckets[-1]
                        merged.last = window.last
                        merged.vmin = min(merged.vmin, window.vmin)
                        merged.vmax = max(merged.vmax, window.vmax)
                        merged.vsum += window.vsum
                        merged.count += window.count
                    else:
                        clone = _Window(start, window.first)
                        clone.last = window.last
                        clone.vmin = window.vmin
                        clone.vmax = window.vmax
                        clone.vsum = window.vsum
                        clone.count = window.count
                        buckets.append(clone)
                result[raw] = [{
                    "ts": round(b.start, 3),
                    "min": b.vmin,
                    "mean": b.vsum / b.count,
                    "max": b.vmax,
                    "last": b.last,
                    "count": b.count,
                } for b in buckets]
        return result

    def latest(self, series: str) -> Optional[float]:
        with self._lock:
            windows = self._data.get(series)
            if not windows:
                return None
            return windows[-1].last

    def increase(self, series: str, window_s: float,
                 now: Optional[float] = None) -> Optional[float]:
        """Counter growth over the lookback window, counter-reset aware
        (PR 4 ``increase`` semantics: a drop means a restart, so the
        post-reset value itself counts as growth from zero). Baseline is
        the newest sample at or before the window start; with no sample
        that old, the oldest in-window first-value anchors instead. None
        while the series has no samples at all."""
        if now is None:
            now = time.time()
        cutoff = now - float(window_s)
        with self._lock:
            windows = self._data.get(series)
            if not windows:
                return None
            baseline: Optional[float] = None
            values: List[float] = []
            for window in windows:
                if window.start + self.window_s <= cutoff:
                    baseline = window.last
                    continue
                values.append(window.first)
                values.append(window.last)
        if not values:
            return 0.0 if baseline is not None else None
        total = 0.0
        prev = baseline if baseline is not None else values[0]
        for value in values:
            if value >= prev:
                total += value - prev
            else:                       # counter reset: count from zero
                total += value
            prev = value
        return total

    def points_retained(self) -> int:
        with self._lock:
            return sum(len(w) for w in self._data.values())

    def clear(self) -> None:
        with self._lock:
            for windows in self._data.values():
                windows.clear()
            self.samples_taken = 0


# -- default allowlist --------------------------------------------------------

def default_series(generation=None) -> List[str]:
    """The shipped allowlist: the serving SLO signals (queue depth, slot
    occupancy, pages, request outcomes, the queue-wait/TTFT good-event
    buckets the :mod:`.slo` objectives read) plus service liveness
    counters — the sustained-signal set the future autoscaler consumes.
    ``generation`` supplies the SLO thresholds the ``:le:`` bounds snap
    to (defaults match GenerationConfig)."""
    ttft_slo_s = getattr(generation, "ttft_slo_s", 2.0)
    queue_wait_slo_s = getattr(generation, "queue_wait_slo_s", 1.0)
    return [
        "tpuhive_generate_queue_depth",
        "tpuhive_generate_slots_busy",
        "tpuhive_generate_kv_pages_free",
        "tpuhive_generate_tokens_total",
        "tpuhive_generate_requests_total{outcome=completed}",
        "tpuhive_generate_requests_total{outcome=cancelled}",
        "tpuhive_generate_requests_total{outcome=failed}",
        "tpuhive_generate_requests_total{outcome=timeout}",
        f"tpuhive_generate_queue_wait_seconds:le:{queue_wait_slo_s:g}",
        "tpuhive_generate_queue_wait_seconds:count",
        f"tpuhive_generate_ttft_seconds:le:{ttft_slo_s:g}",
        "tpuhive_generate_ttft_seconds:count",
        "tpuhive_service_ticks_total",
        "tpuhive_service_tick_failures_total",
        "tpuhive_process_resident_memory_bytes",
        # tenant accounting aggregates (docs/OBSERVABILITY.md "Tenant
        # accounting"): a bare family name SUMS its children, so these
        # are the all-tenant totals — per-tenant windows come from
        # /api/admin/usage, not the history ring (cardinality policy)
        "tpuhive_tenant_device_seconds_total",
        "tpuhive_tenant_kv_byte_seconds_total",
        "tpuhive_tenant_queue_seconds_total",
    ]


# -- process-wide store -------------------------------------------------------
_history: Optional[MetricsHistory] = None
_history_lock = lockwitness.Lock(
    "tensorhive_tpu.observability.history._history_lock")


def get_metrics_history() -> MetricsHistory:
    """Process-wide history store (what the HistoryService samples and
    ``GET /api/admin/history`` serves); built lazily so the allowlist and
    retention read the materialized config."""
    global _history
    with _history_lock:
        if _history is None:
            retention_s = DEFAULT_RETENTION_S
            max_points = DEFAULT_MAX_POINTS
            series: Optional[List[str]] = None
            generation = None
            try:
                from ..config import get_config

                config = get_config()
                retention_s = config.history.retention_s
                max_points = config.history.max_points
                generation = config.generation
                if config.history.series.strip():
                    series = [part for part in
                              config.history.series.split(",")
                              if part.strip()]
            except Exception:
                # bare library use: the shipped defaults, like the alert
                # pack's fallback posture
                log.warning("metrics history: config unavailable, using "
                            "shipped defaults", exc_info=True)
            if series is None:
                series = default_series(generation)
            _history = MetricsHistory(series, retention_s=retention_s,
                                      max_points=max_points)
        return _history


def set_metrics_history(history: Optional[MetricsHistory]) -> None:
    """Replace (or with None: drop, to be lazily rebuilt) the process-wide
    store — test isolation and custom allowlists."""
    global _history
    with _history_lock:
        _history = history


# -- self-metrics -------------------------------------------------------------

def _register_exports() -> Tuple[object, object, object]:
    from . import get_registry

    registry = get_registry()
    samples = registry.counter(
        "tpuhive_history_samples_total",
        "Sampling passes the metrics-history store has taken.")
    series = registry.gauge(
        "tpuhive_history_series",
        "Allowlisted series that produced a value in the last sampling "
        "pass (series without signal yet are skipped, not stored).")
    points = registry.gauge(
        "tpuhive_history_points",
        "Downsample windows currently retained across all series — "
        "bounded by series x max_points regardless of retention_s.")
    return samples, series, points


_SAMPLES_TOTAL, _SERIES_GAUGE, _POINTS_GAUGE = _register_exports()
