"""SLO objectives and Google-SRE multi-window burn-rate evaluation.

An :class:`SloObjective` is a declarative target ratio over good/total
event series (history-store specs, :mod:`.history` grammar): "99% of
requests wait in queue <= ``queue_wait_slo_s``" is *good* =
``queue_wait_seconds:le:1`` over *total* = ``queue_wait_seconds:count``.
The engine evaluates objectives off :class:`~.history.MetricsHistory`
windows — never raw instantaneous gauges — with the SRE-workbook
multi-window multi-burn-rate recipe:

===========  ==================  =========  ========
severity     windows (AND)       burn rate  action
===========  ==================  =========  ========
page         5m **and** 1h       >= 14.4    ``slo_burn_fast`` (critical)
warn         30m **and** 6h      >= 6.0     ``slo_burn_slow`` (warning)
===========  ==================  =========  ========

A burn rate of 1.0 spends exactly the error budget over the budget
window; 14.4 exhausts a 30-day budget in ~2 days. The short window makes
the alert resolve quickly once the breach stops; the AND with the long
window keeps one bad scrape from paging. Both signals feed the PR 4
AlertEngine as ``source`` rules (None while ``[slo]`` is disabled or no
traffic has landed, which keeps the rules quiet rather than firing on
absence) and are exported as::

    tpuhive_slo_error_budget_remaining{objective}
    tpuhive_slo_burn_rate{objective,window}

— the exact sustained-breach signal ROADMAP item 1's autoscaler consumes.
Objective names in :func:`default_objective_pack` are part of the TH-X
docs contract (docs/OBSERVABILITY.md objective table).
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .history import MetricsHistory, get_metrics_history, parse_series

from ..utils import lockwitness

log = logging.getLogger(__name__)

#: multi-window pairs (short AND long, seconds) and their burn thresholds —
#: straight from the SRE workbook's 99.9%/30d recipe, which transfers to
#: any budget window because burn rate is budget-relative
FAST_WINDOWS: Tuple[float, float] = (300.0, 3600.0)
SLOW_WINDOWS: Tuple[float, float] = (1800.0, 21600.0)
FAST_BURN = 14.4
SLOW_BURN = 6.0


def window_label(seconds: float) -> str:
    """Human window label for the ``window`` gauge label ("5m", "1h")."""
    seconds = float(seconds)
    if seconds >= 3600.0 and seconds % 3600.0 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds >= 60.0 and seconds % 60.0 == 0:
        return f"{int(seconds // 60)}m"
    return f"{seconds:g}s"


@dataclass(frozen=True)
class SloObjective:
    """One service-level objective: ``target`` fraction of events must be
    good. ``good``/``total`` are history-series specs; multiple specs sum
    (availability counts completed+cancelled as good). Events, not time:
    an idle service spends no budget."""

    name: str
    target: float
    good: Tuple[str, ...]
    total: Tuple[str, ...]
    description: str = ""

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("SloObjective needs a name")
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"objective {self.name!r}: target must be in (0, 1), "
                f"got {self.target}")
        if not self.good or not self.total:
            raise ValueError(
                f"objective {self.name!r}: good and total series required")
        for spec in (*self.good, *self.total):
            parse_series(spec)      # malformed specs fail at construction


class SloEngine:
    """Evaluates objectives against the history store. Stateless between
    calls (all state lives in the history windows), so evaluation order
    and frequency don't affect results — a property the exactly-once
    alert tests lean on."""

    def __init__(self, objectives: Sequence[SloObjective],
                 history: Optional[MetricsHistory] = None,
                 budget_window_s: float = 3600.0) -> None:
        if budget_window_s <= 0:
            raise ValueError(
                f"budget_window_s must be > 0, got {budget_window_s}")
        names = [o.name for o in objectives]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate objective names in {names}")
        self.objectives: Tuple[SloObjective, ...] = tuple(objectives)
        self._history = history
        self.budget_window_s = float(budget_window_s)

    @property
    def history(self) -> MetricsHistory:
        return self._history if self._history is not None \
            else get_metrics_history()

    # -- arithmetic ---------------------------------------------------------
    def _sum_increase(self, specs: Sequence[str], window_s: float,
                      now: float) -> Optional[float]:
        values = [self.history.increase(spec, window_s, now)
                  for spec in specs]
        values = [v for v in values if v is not None]
        return sum(values) if values else None

    def bad_fraction(self, objective: SloObjective, window_s: float,
                     now: Optional[float] = None) -> Optional[float]:
        """Fraction of events in the window that were bad; None while the
        window holds no events (no traffic is not a breach)."""
        if now is None:
            now = time.time()
        total = self._sum_increase(objective.total, window_s, now)
        if total is None or total <= 0.0:
            return None
        good = self._sum_increase(objective.good, window_s, now) or 0.0
        return min(1.0, max(0.0, 1.0 - good / total))

    def burn_rate(self, objective: SloObjective, window_s: float,
                  now: Optional[float] = None) -> Optional[float]:
        """How fast the error budget burns: bad fraction over the budget
        the target allows. 1.0 = exactly on budget."""
        bad = self.bad_fraction(objective, window_s, now)
        if bad is None:
            return None
        return bad / (1.0 - objective.target)

    def budget_remaining(self, objective: SloObjective,
                         now: Optional[float] = None) -> Optional[float]:
        """Error budget left over the budget window: 1.0 = untouched,
        0.0 = spent, negative = overspent."""
        burn = self.burn_rate(objective, self.budget_window_s, now)
        if burn is None:
            return None
        return 1.0 - burn

    def _multiwindow_burn(self, objective: SloObjective,
                          windows: Tuple[float, float],
                          now: float) -> Optional[float]:
        # the AND of the pair: both windows must burn, so the signal is
        # the smaller of the two (one quiet window keeps it low)
        rates = [self.burn_rate(objective, w, now) for w in windows]
        if any(r is None for r in rates):
            return None
        return min(rates)       # type: ignore[type-var]

    def fast_burn(self, now: Optional[float] = None) -> Optional[float]:
        """Worst fast-pair (5m AND 1h) burn across objectives — the
        ``slo_burn_fast`` alert source. None while nothing has signal."""
        return self._worst(FAST_WINDOWS, now)

    def slow_burn(self, now: Optional[float] = None) -> Optional[float]:
        """Worst slow-pair (30m AND 6h) burn across objectives — the
        ``slo_burn_slow`` alert source."""
        return self._worst(SLOW_WINDOWS, now)

    def _worst(self, windows: Tuple[float, float],
               now: Optional[float]) -> Optional[float]:
        if now is None:
            now = time.time()
        rates = [self._multiwindow_burn(o, windows, now)
                 for o in self.objectives]
        rates = [r for r in rates if r is not None]
        return max(rates) if rates else None

    # -- evaluation / export ------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> Dict[str, Dict]:
        """Compute every objective's budget + per-window burn rates and
        mirror the non-None values into the ``tpuhive_slo_*`` gauges
        (labeled children appear only once a signal exists, so a fresh
        process scrapes no misleading zeros)."""
        if now is None:
            now = time.time()
        result: Dict[str, Dict] = {}
        for objective in self.objectives:
            burn_rates: Dict[str, Optional[float]] = {}
            for window_s in sorted(set(FAST_WINDOWS + SLOW_WINDOWS)):
                label = window_label(window_s)
                burn = self.burn_rate(objective, window_s, now)
                burn_rates[label] = burn
                if burn is not None:
                    _BURN_GAUGE.labels(objective=objective.name,
                                       window=label).set(burn)
            remaining = self.budget_remaining(objective, now)
            if remaining is not None:
                _BUDGET_GAUGE.labels(objective=objective.name).set(remaining)
            result[objective.name] = {
                "target": objective.target,
                "description": objective.description,
                "budgetRemaining": remaining,
                "burnRates": burn_rates,
            }
        return result


# -- default pack -------------------------------------------------------------

def default_objective_pack(config=None) -> List[SloObjective]:
    """The shipped objectives over the serving plane's existing metrics.
    Latency thresholds come from the ``[generation_service]`` SLO knobs
    (the same values the PR 4 p95 alerts compare against), with the alert
    pack's fallback posture when config is unavailable."""
    ttft_slo_s = 2.0
    queue_wait_slo_s = 1.0
    availability_target = 0.999
    latency_target = 0.99
    if config is None:
        try:
            from ..config import get_config

            config = get_config()
        except Exception:
            log.warning("SLO pack: config unavailable, using shipped "
                        "defaults", exc_info=True)
            config = None
    if config is not None:
        ttft_slo_s = config.generation.ttft_slo_s
        queue_wait_slo_s = config.generation.queue_wait_slo_s
        availability_target = config.slo.availability_target
        latency_target = config.slo.latency_target
    requests = "tpuhive_generate_requests_total{{outcome={}}}"
    return [
        SloObjective(
            name="queue_wait",
            target=latency_target,
            good=(f"tpuhive_generate_queue_wait_seconds:le:"
                  f"{queue_wait_slo_s:g}",),
            total=("tpuhive_generate_queue_wait_seconds:count",),
            description="Requests admitted to a slot within "
                        "queue_wait_slo_s of submit.",
        ),
        SloObjective(
            name="ttft",
            target=latency_target,
            good=(f"tpuhive_generate_ttft_seconds:le:{ttft_slo_s:g}",),
            total=("tpuhive_generate_ttft_seconds:count",),
            description="Requests whose first token lands within "
                        "ttft_slo_s of submit.",
        ),
        SloObjective(
            name="availability",
            target=availability_target,
            good=(requests.format("completed"),
                  requests.format("cancelled")),
            total=(requests.format("completed"),
                   requests.format("cancelled"),
                   requests.format("failed"),
                   requests.format("timeout")),
            description="Requests that finish without a server-side "
                        "failure or deadline timeout (client cancels "
                        "count as good).",
        ),
    ]


# -- process-wide engine + alert sources --------------------------------------
_engine: Optional[SloEngine] = None
_engine_lock = lockwitness.Lock(
    "tensorhive_tpu.observability.slo._engine_lock")


def _slo_enabled() -> bool:
    try:
        from ..config import get_config

        return bool(get_config().slo.enabled)
    except Exception:
        log.debug("SLO: config unavailable, defaulting enabled", exc_info=True)
        return True     # bare library use: on, matching SloConfig default


def get_slo_engine() -> SloEngine:
    """Process-wide engine over the default objective pack, built lazily
    from config (same lifecycle as the history store)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            budget_window_s = 3600.0
            try:
                from ..config import get_config

                budget_window_s = get_config().slo.budget_window_s
            except Exception:
                log.warning("SLO engine: config unavailable, using default "
                            "budget window", exc_info=True)
            _engine = SloEngine(default_objective_pack(),
                                budget_window_s=budget_window_s)
        return _engine


def set_slo_engine(engine: Optional[SloEngine]) -> None:
    global _engine
    with _engine_lock:
        _engine = engine


def fast_burn_signal(now: Optional[float] = None) -> Optional[float]:
    """AlertRule source for ``slo_burn_fast``: worst fast-pair burn, or
    None (rule stays quiet) while ``[slo]`` is off or there is no
    traffic."""
    if not _slo_enabled():
        return None
    return get_slo_engine().fast_burn(now)


def slow_burn_signal(now: Optional[float] = None) -> Optional[float]:
    """AlertRule source for ``slo_burn_slow`` — slow-pair counterpart of
    :func:`fast_burn_signal`."""
    if not _slo_enabled():
        return None
    return get_slo_engine().slow_burn(now)


# -- gauge export -------------------------------------------------------------

def _register_exports():
    from . import get_registry

    registry = get_registry()
    budget = registry.gauge(
        "tpuhive_slo_error_budget_remaining",
        "Error budget left over [slo] budget_window_s per objective "
        "(1 = untouched, 0 = spent, negative = overspent).",
        labels=("objective",))
    burn = registry.gauge(
        "tpuhive_slo_burn_rate",
        "Budget burn rate per objective and lookback window "
        "(1 = spending exactly the budget; the alert pack pages at "
        "14.4, warns at 6).",
        labels=("objective", "window"))

    def _collect_slo_gauges(_registry) -> None:
        # refresh at scrape time so /api/metrics is current even between
        # HistoryService ticks; cheap (reads in-memory windows only)
        if not _slo_enabled():
            return
        try:
            get_slo_engine().evaluate()
        except Exception:       # pragma: no cover - defensive
            log.exception("SLO gauge refresh failed")

    registry.register_collector(_collect_slo_gauges)
    return budget, burn


_BUDGET_GAUGE, _BURN_GAUGE = _register_exports()
