"""Per-request serving trace ledger: see inside every generate request.

The serving SLO metrics (docs/SERVING.md) are aggregates — a TTFT histogram
says *that* latency regressed, never *which* request or *which phase*
(queue wait vs prefill vs decode). This module is the request-scoped view:
every ``SlotEngine.submit`` mints a ``request_id``, the engine stamps each
phase transition into a :class:`RequestRecord`, and completed records land
in a bounded, thread-safe ring exposed at ``GET /api/admin/requests``.

Design constraints, in the order they forced the shape:

* **The engine's lock is hot.** Ledger calls happen inside the scheduler
  loop (some under the engine lock), so every method here is a handful of
  dict/deque operations behind one leaf lock — the ledger never calls back
  into the engine, never blocks, never allocates device memory.
* **Bounded by construction.** Completed records live in a
  ``deque(maxlen=capacity)``; in-flight records are keyed by id and bounded
  by the engine's own admission limits (queue_depth + slots). A busy
  gateway can run forever without the ledger growing.
* **Phases are engine-clock durations, wall-clock anchors.** The engine
  drives a monotonic (or fake, in tests) clock; the record stores durations
  from *that* clock so fake-clock tests are exact, and anchors them to one
  ``time.time()`` wall stamp taken at submit so humans can correlate with
  logs and spans.
* **Rejections are requests too.** Queue-full and rate-limit rejections get
  a record with their outcome — admission-control tuning needs to see what
  was shed, not just what ran (docs/OBSERVABILITY.md "Request tracing").

The ledger is process-wide like the tracer/registry (one serving plane per
process); ``reset_observability()`` clears it for test isolation.
"""
from __future__ import annotations

import collections
import itertools
import statistics
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from ..utils import lockwitness

DEFAULT_CAPACITY = 256

#: terminal outcomes a record can carry (mirrors
#: ``tpuhive_generate_requests_total{outcome=...}``). ``failed`` is the
#: supervisor's fail-fast path (engine fault → terminal error chunk);
#: ``timeout`` is a per-request deadline expiring in queue, mid-prefill or
#: mid-decode (docs/ROBUSTNESS.md "Serving data plane").
OUTCOMES = ("completed", "cancelled", "failed", "timeout",
            "rejected_queue", "rejected_ratelimit")


@dataclass
class RequestRecord:
    """One generate request's lifecycle, phase by phase.

    Durations are milliseconds measured on the engine clock; ``None`` means
    the request never reached that phase (a queue-full rejection has no
    prefill, a cancel mid-queue has no TTFT).
    """

    request_id: str
    #: wall-clock submit stamp (unix seconds) — the anchor every span and
    #: log line correlates against
    submitted_ts: float
    prompt_tokens: int
    max_new_tokens: int
    temperature: float
    user_key: Optional[str] = None
    outcome: Optional[str] = None          # None while in flight
    slot: Optional[int] = None
    kv_pages: Optional[int] = None         # pages granted (paged engines)
    queue_ms: Optional[float] = None
    #: prompt tokens the prefix cache let prefill skip (None: prefix cache
    #: off or the engine predates it; 0: a full miss)
    cached_tokens: Optional[int] = None
    #: prefill chunks dispatched (None: legacy whole-prompt prefill path;
    #: 0: full-prefix hit, nothing to prefill)
    prefill_chunks: Optional[int] = None
    prefill_bucket: Optional[int] = None
    #: "hit" (bucket executable reused) or "miss" (compiled) — joins the
    #: ``tpuhive_decode_compile_total`` fingerprint story per request
    prefill_compile: Optional[str] = None
    prefill_ms: Optional[float] = None
    #: KV-page tiering (docs/SERVING.md "KV-page tiering"): pages promoted
    #: from the host store instead of recomputed (None: tier off; 0: tier
    #: on, no host hit) and the promotion DMA's wall share of TTFT — split
    #: OUT of prefill_ms so slow joins triage to copy bandwidth vs
    #: recompute honestly
    host_hit_pages: Optional[int] = None
    promote_ms: Optional[float] = None
    ttft_ms: Optional[float] = None
    decode_ms: Optional[float] = None      # first token -> last token
    total_ms: Optional[float] = None
    #: speculative decoding lane (docs/SERVING.md "Speculative decoding"):
    #: draft tokens proposed for this request / accepted by the batched
    #: verify (None: lane off or the request predates it; acceptance
    #: measures draft agreement per verify, emission may truncate shorter
    #: at EOS or the max_new budget)
    draft_tokens: Optional[int] = None
    accepted_tokens: Optional[int] = None
    #: tenant accounting (docs/OBSERVABILITY.md "Tenant accounting"):
    #: the TenantMeter's per-request resource-time integrals, finalized
    #: at request end (None: [accounting] off or the row predates it)
    device_seconds: Optional[float] = None
    kv_byte_seconds: Optional[float] = None
    tokens: int = 0
    finished_ts: Optional[float] = None
    #: raw inter-token gaps (ms); bounded by max_new_tokens <= the engine cap
    _gaps_ms: List[float] = field(default_factory=list, repr=False)

    def intertoken_p50_ms(self) -> Optional[float]:
        if not self._gaps_ms:
            return None
        return round(statistics.median(self._gaps_ms), 3)

    def to_dict(self) -> Dict:
        def ms(value: Optional[float]) -> Optional[float]:
            return None if value is None else round(value, 3)

        return {
            "requestId": self.request_id,
            "outcome": self.outcome,             # null while in flight
            "submittedTs": round(self.submitted_ts, 6),
            "finishedTs": (round(self.finished_ts, 6)
                           if self.finished_ts is not None else None),
            "promptTokens": self.prompt_tokens,
            "maxNewTokens": self.max_new_tokens,
            "temperature": self.temperature,
            "userKey": self.user_key,
            "slot": self.slot,
            "kvPages": self.kv_pages,
            "queueMs": ms(self.queue_ms),
            "cachedTokens": self.cached_tokens,
            "prefillChunks": self.prefill_chunks,
            "prefillBucket": self.prefill_bucket,
            "prefillCompile": self.prefill_compile,
            "prefillMs": ms(self.prefill_ms),
            "hostHitPages": self.host_hit_pages,
            "promoteMs": ms(self.promote_ms),
            "ttftMs": ms(self.ttft_ms),
            "decodeMs": ms(self.decode_ms),
            "totalMs": ms(self.total_ms),
            "draftTokens": self.draft_tokens,
            "acceptedTokens": self.accepted_tokens,
            "acceptanceRate": (round(self.accepted_tokens
                                     / self.draft_tokens, 4)
                               if self.draft_tokens else None),
            "deviceSeconds": (round(self.device_seconds, 6)
                              if self.device_seconds is not None else None),
            "kvByteSeconds": (round(self.kv_byte_seconds, 3)
                              if self.kv_byte_seconds is not None else None),
            "tokens": self.tokens,
            "intertokenP50Ms": self.intertoken_p50_ms(),
        }


class RequestLedger:
    """Thread-safe request lifecycle store: in-flight records by id, a
    bounded ring of finished ones, oldest evicted first."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._lock = lockwitness.Lock("RequestLedger._lock")
        self._capacity = capacity
        self._finished: Deque[RequestRecord] = collections.deque(
            maxlen=capacity)
        self._inflight: Dict[str, RequestRecord] = {}
        self._ids = itertools.count(1)
        #: distinguishes engines/restarts within one process so ids never
        #: collide across ledger resets (tests build many engines)
        self._epoch = itertools.count(1)
        self._epoch_tag = next(self._epoch)

    @property
    def capacity(self) -> int:
        return self._capacity

    def set_capacity(self, capacity: int) -> None:
        """Re-bound the finished ring (config ``request_ledger_size``);
        retains the newest records that still fit."""
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        with self._lock:
            self._capacity = capacity
            self._finished = collections.deque(self._finished,
                                               maxlen=capacity)

    # -- lifecycle ---------------------------------------------------------
    def new_request_id(self) -> str:
        with self._lock:
            return f"g{self._epoch_tag:x}-{next(self._ids):08x}"

    def begin(self, request_id: str, *, prompt_tokens: int,
              max_new_tokens: int, temperature: float,
              user_key: Optional[str] = None,
              submitted_ts: Optional[float] = None) -> RequestRecord:
        record = RequestRecord(
            request_id=request_id,
            submitted_ts=(time.time() if submitted_ts is None
                          else submitted_ts),
            prompt_tokens=prompt_tokens,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            user_key=user_key,
        )
        with self._lock:
            self._inflight[request_id] = record
        return record

    def get(self, request_id: str) -> Optional[RequestRecord]:
        """The record, in flight or finished (None once evicted)."""
        with self._lock:
            record = self._inflight.get(request_id)
            if record is not None:
                return record
            for finished in self._finished:
                if finished.request_id == request_id:
                    return finished
            return None

    def finish(self, record: RequestRecord, outcome: str,
               finished_ts: Optional[float] = None) -> None:
        """Move a record to the finished ring exactly once; later calls
        (e.g. a cancel racing completion) are ignored."""
        with self._lock:
            if record.outcome is not None:
                return
            record.outcome = outcome
            record.finished_ts = (time.time() if finished_ts is None
                                  else finished_ts)
            self._inflight.pop(record.request_id, None)
            self._finished.append(record)

    def discard(self, record: RequestRecord) -> None:
        """Drop an in-flight record without recording an outcome (used when
        submit-side validation fails after the record was minted)."""
        with self._lock:
            self._inflight.pop(record.request_id, None)

    # -- reading -----------------------------------------------------------
    def recent(self, limit: Optional[int] = None,
               outcome: Optional[str] = None,
               user: Optional[str] = None) -> List[Dict]:
        """Finished records, newest first; ``outcome=`` and ``user=``
        (exact ``userKey`` match) filters compose."""
        with self._lock:
            records = list(self._finished)
        records.reverse()
        if outcome is not None:
            records = [r for r in records if r.outcome == outcome]
        if user is not None:
            records = [r for r in records if r.user_key == user]
        if limit is not None and limit >= 0:
            records = records[:limit]
        return [record.to_dict() for record in records]

    def in_flight(self) -> List[Dict]:
        """Requests currently queued or running, oldest submit first."""
        with self._lock:
            records = sorted(self._inflight.values(),
                             key=lambda r: r.submitted_ts)
        return [record.to_dict() for record in records]

    def __len__(self) -> int:
        with self._lock:
            return len(self._finished)

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()
            self._inflight.clear()
            self._epoch_tag = next(self._epoch)


_ledger = RequestLedger()


def get_request_ledger() -> RequestLedger:
    """Process-wide request ledger (what /api/admin/requests dumps)."""
    return _ledger
