"""Declarative alert rules evaluated directly against the metrics registry.

PR 1 made the control plane *measurable*; nothing in-process *evaluated*
those measurements — a dead daemon or a climbing failure counter was only
visible if a human happened to read a scrape. This module closes the loop
from measured to actionable, the way cluster managers in related work treat
health as a control signal rather than a dashboard afterthought (JIRIAF
provisions against live node health, arxiv 2502.18596; Tally depends on
continuously detecting interference, arxiv 2410.07381):

* :class:`AlertRule` — a declarative rule over one registry family (or an
  arbitrary ``source`` callable for signals the registry cannot carry, like
  thread liveness). Kinds: ``threshold`` (instantaneous comparison),
  ``increase`` (growth over a lookback window — counter-reset aware),
  ``absent`` (the signal is missing entirely), ``stale`` (a unix-timestamp
  gauge has not been refreshed within ``threshold`` seconds).
* :class:`AlertEngine` — evaluates rules straight off the in-process
  registry (no scrape round-trip), driving one state machine per rule::

      inactive -> pending -(held for `for_s`)-> firing -> resolved

  ``for_s`` debounces flapping signals; sinks are notified exactly once on
  ``pending -> firing`` and once on ``firing -> resolved``. Every
  transition (including pending entries that never fire) lands in a bounded
  history ring for ``GET /api/admin/alerts``.
* sinks — :class:`LogSink` (always on, structured single-line JSON payload
  so log lines are machine-joinable) and :class:`WebhookSink` (JSON POST
  with a hard timeout and bounded retry; failures are counted, never
  raised into the evaluating tick).

Firing state is mirrored into ``tpuhive_alerts_firing{rule,severity}``
gauges at exposition time (a registry collector), so an external Prometheus
sees exactly the same truth the in-process engine acts on.

Evaluation takes an explicit ``now`` so tests drive the whole lifecycle on
a fake clock; the :class:`AlertingService` daemon (core/services/alerting)
calls it on the wall clock every tick.
"""
from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import (
    Callable,
    Deque,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from .metrics import Counter, Gauge, Histogram, MetricsRegistry

from ..utils import lockwitness

log = logging.getLogger(__name__)

#: comparators a threshold rule may use
_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}

_KINDS = ("threshold", "increase", "absent", "stale")

#: alert lifecycle states
INACTIVE, PENDING, FIRING, RESOLVED = "inactive", "pending", "firing", "resolved"

HISTORY_CAPACITY = 256


@dataclass(frozen=True)
class AlertRule:
    """One declarative rule. ``metric`` names a registry family; ``labels``
    filters its children (subset match — a child matches when every filter
    pair is present among its labels); matching children are summed
    (histograms contribute their observation count). ``source`` overrides
    the registry read entirely for non-metric signals; it returns the
    current value or None for "no signal"."""

    name: str
    severity: str = "warning"            # "info" | "warning" | "critical"
    kind: str = "threshold"
    metric: str = ""
    labels: Mapping[str, str] = field(default_factory=dict)
    op: str = ">"
    threshold: float = 0.0
    #: lookback for ``increase`` rules (seconds)
    window_s: float = 300.0
    #: how long the condition must hold before pending becomes firing
    for_s: float = 0.0
    description: str = ""
    source: Optional[Callable[[], Optional[float]]] = None

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown rule kind {self.kind!r}")
        if self.op not in _OPS:
            raise ValueError(f"unknown comparator {self.op!r}")
        if not self.metric and self.source is None:
            raise ValueError(f"rule {self.name!r} needs a metric or a source")


@dataclass
class AlertState:
    """Mutable per-rule lifecycle state."""

    status: str = INACTIVE
    since: Optional[float] = None        # when the current status was entered
    pending_since: Optional[float] = None
    last_value: Optional[float] = None
    fired_count: int = 0
    #: (ts, value) samples for increase rules, oldest first
    history: Deque[Tuple[float, float]] = field(default_factory=deque)


class AlertSink:
    """Receives one dict per notification-worthy transition."""

    name = "sink"

    def notify(self, event: Dict) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class LogSink(AlertSink):
    """Always-on structured sink: one JSON payload per line so alert log
    lines are machine-parseable and joinable against the span ids the
    tracing filter injects."""

    name = "log"

    def notify(self, event: Dict) -> None:
        payload = json.dumps(event, sort_keys=True, default=str)
        if event.get("to") == FIRING:
            log.warning("ALERT firing: %s", payload)
        else:
            log.info("ALERT resolved: %s", payload)


class WebhookSink(AlertSink):
    """POST each transition as JSON to ``url``.

    Every request carries ``timeout_s`` (a wedged receiver must cost a
    bounded wait, never a hung alerting tick — the same TH-B contract as
    transport calls) and failures retry at most ``retries`` extra times
    back-to-back before being counted and dropped; alert delivery is
    best-effort by design, the log sink is the durable record.
    """

    name = "webhook"

    def __init__(self, url: str, timeout_s: float = 5.0,
                 retries: int = 2) -> None:
        if not url:
            raise ValueError("webhook sink needs a url")
        self.url = url
        self.timeout_s = float(timeout_s)
        self.retries = max(0, int(retries))

    def notify(self, event: Dict) -> None:
        body = json.dumps(event, sort_keys=True, default=str).encode()
        request = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        last_error: Optional[Exception] = None
        for _attempt in range(self.retries + 1):
            try:
                with urllib.request.urlopen(request,
                                            timeout=self.timeout_s) as resp:
                    resp.read()
                return
            except (urllib.error.URLError, OSError, ValueError) as exc:
                last_error = exc
        _WEBHOOK_FAILURES.inc()
        log.warning("webhook sink gave up after %d attempts on %s: %s",
                    self.retries + 1, self.url, last_error)


class AlertEngine:
    """Evaluates a rule set against a registry; thread-safe.

    ``evaluate(now)`` advances every rule's state machine and returns the
    notification-worthy transitions (entered ``firing`` / ``resolved``) for
    the caller to fan out to sinks — sink I/O deliberately happens OUTSIDE
    the engine lock.
    """

    def __init__(self, rules: Sequence[AlertRule],
                 registry: Optional[MetricsRegistry] = None) -> None:
        names = [rule.name for rule in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        self.rules: Tuple[AlertRule, ...] = tuple(rules)
        if registry is None:
            from . import get_registry

            registry = get_registry()
        self._registry = registry
        self._lock = lockwitness.Lock("AlertEngine._lock")
        self._states: Dict[str, AlertState] = {
            rule.name: AlertState() for rule in self.rules}
        self._transitions: Deque[Dict] = deque(maxlen=HISTORY_CAPACITY)

    # -- signal reading -----------------------------------------------------
    def _read_value(self, rule: AlertRule) -> Optional[float]:
        if rule.source is not None:
            return rule.source()
        family = self._registry.get(rule.metric)
        if family is None:
            return None
        total = 0.0
        matched = False
        for label_values, child in family.children():
            labels = dict(zip(family.label_names, label_values))
            if any(labels.get(k) != v for k, v in rule.labels.items()):
                continue
            matched = True
            if isinstance(child, (Counter, Gauge)):
                total += child.value
            elif isinstance(child, Histogram):
                total += child.count
        return total if matched else None

    def _breached(self, rule: AlertRule, state: AlertState,
                  value: Optional[float], now: float) -> bool:
        if rule.kind == "absent":
            return value is None
        if value is None:
            # no signal yet: threshold/increase/stale rules stay quiet until
            # the subsystem they watch produces its first sample
            state.history.clear()
            return False
        if rule.kind == "threshold":
            return _OPS[rule.op](value, rule.threshold)
        if rule.kind == "stale":
            # value is a unix timestamp gauge; 0 means "never happened yet"
            return value > 0 and (now - value) > rule.threshold
        # increase: growth over the lookback window, counter-reset aware
        history = state.history
        if history and value < history[-1][1]:
            history.clear()              # counter reset (process restart)
        history.append((now, value))
        while history and history[0][0] < now - rule.window_s:
            history.popleft()
        increase = value - history[0][1]
        return _OPS[rule.op](increase, rule.threshold)

    # -- lifecycle ----------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """Advance every rule; returns transitions sinks must be told about
        (``pending -> firing`` and ``firing -> resolved``), in rule order."""
        if now is None:
            now = time.time()
        # signal reading happens OUTSIDE the engine lock: rule.source()
        # callables reach into the serving engine, the service manager and
        # the SLO/history stores — each with locks of its own. Holding
        # self._lock across those calls couples this engine's lock to code
        # it does not control (TH-LOCK check (c)); the state machines only
        # need the snapshot.
        values = {rule.name: self._read_value(rule) for rule in self.rules}
        notifications: List[Dict] = []
        with self._lock:
            for rule in self.rules:
                state = self._states[rule.name]
                value = values[rule.name]
                state.last_value = value
                breached = self._breached(rule, state, value, now)
                event = self._advance(rule, state, breached, value, now)
                if event is not None:
                    notifications.append(event)
        return notifications

    def _advance(self, rule: AlertRule, state: AlertState, breached: bool,
                 value: Optional[float], now: float) -> Optional[Dict]:
        """One state-machine step; returns the notification event if this
        step entered ``firing`` or ``resolved``."""
        if breached:
            if state.status in (INACTIVE, RESOLVED):
                self._transition(rule, state, PENDING, value, now)
                state.pending_since = now
            if (state.status == PENDING
                    and now - (state.pending_since or now) >= rule.for_s):
                return self._transition(rule, state, FIRING, value, now)
            return None
        if state.status == PENDING:
            # condition cleared before the for-duration elapsed: debounced,
            # no notification was ever sent so none is owed
            self._transition(rule, state, INACTIVE, value, now)
            state.pending_since = None
        elif state.status == FIRING:
            state.pending_since = None
            return self._transition(rule, state, RESOLVED, value, now)
        return None

    def _transition(self, rule: AlertRule, state: AlertState, to: str,
                    value: Optional[float], now: float) -> Dict:
        event = {
            "rule": rule.name,
            "severity": rule.severity,
            "from": state.status,
            "to": to,
            "ts": round(now, 3),
            "value": value,
            "description": rule.description,
        }
        state.status = to
        state.since = now
        if to == FIRING:
            state.fired_count += 1
        self._transitions.append(event)
        return event

    # -- reading ------------------------------------------------------------
    def firing(self) -> List[str]:
        with self._lock:
            return [name for name, state in self._states.items()
                    if state.status == FIRING]

    def export_gauges(self) -> None:
        """Mirror firing state into ``tpuhive_alerts_firing`` children (one
        per rule, 1.0 while firing) — called by the registry collector at
        exposition time so scrapes always carry the full rule set."""
        with self._lock:
            for rule in self.rules:
                _FIRING_GAUGE.labels(
                    rule=rule.name, severity=rule.severity,
                ).set(1.0 if self._states[rule.name].status == FIRING else 0.0)

    def dump(self) -> Dict:
        """Full rule/state dump for ``GET /api/admin/alerts``."""
        with self._lock:
            rules = []
            for rule in self.rules:
                state = self._states[rule.name]
                rules.append({
                    "name": rule.name,
                    "severity": rule.severity,
                    "kind": rule.kind,
                    "metric": rule.metric or None,
                    "labels": dict(rule.labels),
                    "op": rule.op,
                    "threshold": rule.threshold,
                    "windowS": rule.window_s,
                    "forS": rule.for_s,
                    "description": rule.description,
                    "status": state.status,
                    "since": state.since,
                    "lastValue": state.last_value,
                    "firedCount": state.fired_count,
                })
            return {
                "rules": rules,
                "firing": [r["name"] for r in rules if r["status"] == FIRING],
                "transitions": list(self._transitions),
            }


# -- default rule pack -------------------------------------------------------

def _dead_service_count() -> Optional[float]:
    """Registered daemon services whose thread is not alive (None before a
    manager exists — nothing to watch yet)."""
    from ..core.managers import manager as manager_module

    # read the module global (never get_manager(): that would CONSTRUCT a
    # manager as a side effect of evaluating an alert rule)
    manager = manager_module._instance
    if manager is None or manager.service_manager is None:
        return None
    services = manager.service_manager.services
    if not services:
        return None
    return float(sum(1 for service in services if not service.is_alive()))


def _open_breaker_count() -> Optional[float]:
    """Hosts whose transport circuit breaker is currently open (None before
    a transport manager exists — nothing to watch yet)."""
    from ..core.transport import base as transport_base

    # read the module global (never get_transport_manager(): that would
    # CONSTRUCT a manager as a side effect of evaluating an alert rule)
    manager = transport_base._manager
    if manager is None:
        return None
    return float(len(manager.resilience.open_hosts()))


def _stale_host_counter(stale_after_s: float) -> Callable[[], Optional[float]]:
    """Source callable: managed hosts whose last-known-good telemetry
    snapshot is older than ``stale_after_s`` (or that never produced one
    and are marked unreachable)."""

    def _stale_host_count() -> Optional[float]:
        from ..core.managers import manager as manager_module

        manager = manager_module._instance
        if manager is None:
            return None
        health = manager.infrastructure_manager.host_health()
        if not health:
            return None
        return float(sum(
            1 for entry in health.values()
            if (entry["staleness_s"] is not None
                and entry["staleness_s"] > stale_after_s)
            or entry["state"] == "unreachable"))

    return _stale_host_count


def _lease_state_counter(state: str) -> Callable[[], Optional[float]]:
    """Source callable: hosts whose membership lease is in ``state``
    (docs/ROBUSTNESS.md "Host membership & leases"). None before a manager
    exists or while no leases are tracked — an empty inventory has no
    membership to alert on. Static (SSH-pulled) hosts never leave ``live``,
    so these only ever count agent-managed hosts."""

    def _lease_state_count() -> Optional[float]:
        from ..core.managers import manager as manager_module

        manager = manager_module._instance
        if manager is None:
            return None
        leases = manager.infrastructure_manager.host_leases()
        if not leases:
            return None
        return float(sum(1 for lease in leases.values()
                         if lease["state"] == state))

    return _lease_state_count


def _serving_queue_saturation() -> Optional[float]:
    """Admission-queue fill fraction of the serving engine (None while no
    engine is installed — serving disabled is not an alertable state)."""
    from ..serving import get_engine

    engine = get_engine()
    if engine is None:
        return None
    return engine.queue_saturation()


def _serving_ttft_p95() -> Optional[float]:
    """p95 submit-to-first-token latency in seconds (None before the first
    completed prefill — an idle gateway has no TTFT to breach)."""
    from ..serving import get_engine

    engine = get_engine()
    if engine is None:
        return None
    return engine.ttft_p95_s()


def _serving_queue_wait_p95() -> Optional[float]:
    """p95 admission-queue wait in seconds (None before the first join).
    Split out of TTFT so the alert names WHICH phase ate the budget:
    queue wait over SLO means admission/capacity tuning, TTFT over SLO
    with queue wait under it means prefill cost (docs/OBSERVABILITY.md
    "Request tracing & profiling")."""
    from ..serving import get_engine

    engine = get_engine()
    if engine is None:
        return None
    return engine.queue_wait_p95_s()


def _serving_kv_page_saturation() -> Optional[float]:
    """KV page-pool fill fraction of the paged serving engine (None while
    no engine is installed OR the engine runs the contiguous rollback
    layout — neither is an alertable state). 1.0 means admission is
    page-bound: requests queue-wait or 429 until a running sequence
    releases pages (docs/SERVING.md "Paged KV cache")."""
    from ..serving import get_engine

    engine = get_engine()
    if engine is None:
        return None
    return engine.kv_page_saturation()


def _serving_spec_acceptance() -> Optional[float]:
    """Lifetime draft-token acceptance rate of the speculative lane (None
    while no engine is installed, the lane is off, or too few tokens have
    been proposed to judge — engine.spec_acceptance_rate debounces). A low
    rate means draft compute is being spent without shortening decode
    (docs/SERVING.md 'Speculative decoding')."""
    from ..serving import get_engine

    engine = get_engine()
    if engine is None:
        return None
    return engine.spec_acceptance_rate()


def _tenant_dominance() -> Optional[float]:
    """Largest single-tenant share of attributed device-seconds over the
    accounting window, gated on queue-wait SLO pressure — the
    noisy-neighbor signal item 4's ProtectionService will enforce
    against. None (quiet) while [accounting] is off, no engine runs, the
    queue is healthy, or nothing was attributed (docs/OBSERVABILITY.md
    "Tenant accounting")."""
    from .accounting import dominance_signal

    return dominance_signal()


def _engine_crash_loop() -> Optional[float]:
    """Source callable: 1.0 while the generation supervisor's crash-loop
    breaker is open (restart budget exhausted — the plane is 503ing with
    the reason), 0.0 while supervised and healthy, None when no supervisor
    owns this process's serving plane (docs/ROBUSTNESS.md 'Serving data
    plane')."""
    from ..serving import get_serving_state

    state = get_serving_state()
    if not state["supervisor_active"]:
        return None
    return 1.0 if state["crash_loop"] else 0.0


def _serving_stalled_slot_counter(
        leak_after_s: float) -> Callable[[], Optional[float]]:
    """Source callable: busy slots that have emitted nothing for
    ``leak_after_s`` — occupancy that traffic cannot explain, i.e. a leaked
    or wedged slot starving admission."""

    def _stalled_slot_count() -> Optional[float]:
        from ..serving import get_engine

        engine = get_engine()
        if engine is None:
            return None
        return float(engine.stalled_slots(leak_after_s))

    return _stalled_slot_count


def _slo_fast_burn() -> Optional[float]:
    """Source callable: worst fast-pair (5m AND 1h) SLO burn rate across
    the default objectives (observability/slo.py). None — the rule stays
    quiet — while [slo] is disabled or no traffic has landed in the
    history windows yet (no traffic is not a breach)."""
    from .slo import fast_burn_signal

    return fast_burn_signal()


def _slo_slow_burn() -> Optional[float]:
    """Source callable: worst slow-pair (30m AND 6h) SLO burn rate —
    slow-window counterpart of :func:`_slo_fast_burn`."""
    from .slo import slow_burn_signal

    return slow_burn_signal()


def default_rule_pack(monitoring_interval_s: Optional[float] = None,
                      alert_interval_s: float = 5.0) -> List[AlertRule]:
    """The signals the registry already records (docs/OBSERVABILITY.md),
    promoted to rules. ``for_s`` debounces are expressed in multiples of the
    alerting tick so one noisy sample never pages."""
    if monitoring_interval_s is None:
        try:
            from ..config import get_config

            monitoring_interval_s = get_config().monitoring.interval_s
        except Exception:
            # config not materialized yet (bare library use): fall back to
            # the shipped default rather than refusing to build the pack
            log.warning("default_rule_pack: config unavailable, assuming "
                        "2s monitoring interval", exc_info=True)
            monitoring_interval_s = 2.0
    probe_stale_after = 3.0 * float(monitoring_interval_s)
    try:
        from ..config import get_config

        generation = get_config().generation
        ttft_slo_s = generation.ttft_slo_s
        queue_wait_slo_s = generation.queue_wait_slo_s
        slot_leak_after_s = generation.slot_leak_after_s
    except Exception:
        # same fallback posture as the monitoring interval above: bare
        # library use gets the shipped serving SLO defaults
        log.warning("default_rule_pack: config unavailable, assuming "
                    "2s TTFT SLO / 60s slot-leak threshold", exc_info=True)
        ttft_slo_s, queue_wait_slo_s, slot_leak_after_s = 2.0, 1.0, 60.0
    try:
        from ..config import get_config

        dominance_share = get_config().accounting.dominance_share
    except Exception:
        # same fallback posture: the shipped [accounting] default
        log.warning("default_rule_pack: config unavailable, assuming 0.5 "
                    "tenant dominance share", exc_info=True)
        dominance_share = 0.5
    return [
        AlertRule(
            name="service_down", severity="critical",
            kind="threshold", op=">", threshold=0.0, for_s=0.0,
            source=_dead_service_count,
            description="a registered daemon service thread is not alive"),
        AlertRule(
            name="service_tick_overruns", severity="warning",
            kind="increase", metric="tpuhive_service_tick_overruns_total",
            op=">", threshold=0.0, window_s=120.0,
            for_s=2 * alert_interval_s,
            description="service ticks overran their interval in the last "
                        "2 minutes (interval starvation)"),
        AlertRule(
            name="probe_failures", severity="warning",
            kind="increase", metric="tpuhive_probe_failures_total",
            op=">", threshold=0.0, window_s=120.0,
            for_s=2 * alert_interval_s,
            description="per-host probe failures (unreachable/unparseable) "
                        "in the last 2 minutes"),
        AlertRule(
            name="probe_round_stale", severity="critical",
            kind="stale", metric="tpuhive_probe_last_round_timestamp_seconds",
            threshold=probe_stale_after, for_s=alert_interval_s,
            description="no probe round completed within 3x the monitoring "
                        "interval — telemetry is blind"),
        AlertRule(
            name="transport_breaker_open", severity="critical",
            kind="threshold", op=">", threshold=0.0, for_s=0.0,
            source=_open_breaker_count,
            description="a host's transport circuit breaker is open — the "
                        "control plane is refusing to contact it until the "
                        "cool-down elapses (docs/ROBUSTNESS.md)"),
        AlertRule(
            name="host_snapshot_stale", severity="warning",
            kind="threshold", op=">", threshold=0.0,
            for_s=alert_interval_s,
            source=_stale_host_counter(probe_stale_after),
            description="a managed host's last-known-good telemetry "
                        "snapshot is older than 3x the monitoring interval "
                        "— its infra data is being served stale"),
        AlertRule(
            name="host_lease_suspect", severity="warning",
            kind="threshold", op=">", threshold=0.0, for_s=0.0,
            source=_lease_state_counter("suspect"),
            description="an agent-managed host missed heartbeats past the "
                        "suspect window — its membership lease is degrading "
                        "(docs/ROBUSTNESS.md 'Host membership & leases')"),
        AlertRule(
            name="host_lease_expired", severity="critical",
            kind="threshold", op=">", threshold=0.0, for_s=0.0,
            source=_lease_state_counter("unreachable"),
            description="an agent-managed host's membership lease expired — "
                        "no heartbeat within the TTL; the host takes no new "
                        "work and its running jobs are being reaped "
                        "(docs/ROBUSTNESS.md 'Host membership & leases')"),
        AlertRule(
            name="job_spawn_failures", severity="warning",
            kind="increase", metric="tpuhive_job_spawn_failures_total",
            op=">", threshold=0.0, window_s=300.0,
            for_s=alert_interval_s,
            description="scheduled job spawns failed in the last 5 minutes"),
        AlertRule(
            name="protection_violations", severity="warning",
            kind="threshold", metric="tpuhive_protection_active_violations",
            op=">", threshold=0.0, for_s=2 * alert_interval_s,
            description="reservation intruders present in the latest "
                        "protection tick"),
        AlertRule(
            name="api_5xx", severity="warning",
            kind="increase", metric="tpuhive_api_unhandled_errors_total",
            op=">", threshold=0.0, window_s=300.0,
            for_s=0.0,
            description="requests hit the catch-all 500 handler in the last "
                        "5 minutes"),
        AlertRule(
            name="decode_compile_miss_growth", severity="warning",
            kind="increase", metric="tpuhive_decode_compile_total",
            labels={"event": "miss"},
            op=">", threshold=4.0, window_s=300.0,
            for_s=0.0,
            description="decode executables keep compiling — prompt shapes "
                        "are escaping the prefill buckets (docs/PERF.md)"),
        AlertRule(
            name="generate_queue_saturated", severity="warning",
            kind="threshold", op=">=", threshold=1.0,
            for_s=2 * alert_interval_s,
            source=_serving_queue_saturation,
            description="the serving admission queue has been full — new "
                        "generation requests are being 429'd "
                        "(docs/SERVING.md)"),
        AlertRule(
            name="generate_ttft_slo", severity="warning",
            kind="threshold", op=">", threshold=ttft_slo_s,
            for_s=2 * alert_interval_s,
            source=_serving_ttft_p95,
            description="p95 time-to-first-token is over the "
                        "[generation_service] ttft_slo_s budget — prefill "
                        "queueing is eating the latency SLO"),
        AlertRule(
            name="generate_queue_wait_slo", severity="warning",
            kind="threshold", op=">", threshold=queue_wait_slo_s,
            for_s=2 * alert_interval_s,
            source=_serving_queue_wait_p95,
            description="p95 admission-queue wait is over the "
                        "[generation_service] queue_wait_slo_s budget — "
                        "TTFT is being eaten in the queue, not in prefill; "
                        "add capacity or shed load (docs/SERVING.md)"),
        AlertRule(
            name="kv_pages_exhausted", severity="warning",
            kind="threshold", op=">=", threshold=1.0,
            for_s=2 * alert_interval_s,
            source=_serving_kv_page_saturation,
            description="the paged KV pool is fully allocated — new "
                        "generation requests are queue-waiting (or 429ing) "
                        "for pages to be released; raise kv_pages or shed "
                        "long-context load (docs/SERVING.md)"),
        AlertRule(
            name="prefix_cache_thrash", severity="warning",
            kind="increase",
            metric="tpuhive_generate_prefix_evictions_total",
            op=">", threshold=64.0, window_s=300.0,
            for_s=alert_interval_s,
            description="prefix-cache pages are being evicted faster than "
                        "the shared-prefix working set can stay warm — "
                        "admissions keep reclaiming what the next hit "
                        "needs; raise kv_pages or shorten prompts "
                        "(docs/SERVING.md 'Prefix cache & chunked "
                        "prefill')"),
        AlertRule(
            name="host_kv_thrash", severity="warning",
            kind="increase",
            metric="tpuhive_generate_host_kv_demotions_total",
            op=">", threshold=64.0, window_s=300.0,
            for_s=alert_interval_s,
            description="KV pages are spilling to the host tier faster "
                        "than the device working set can stay resident — "
                        "the pool is churning through demote/promote "
                        "round-trips instead of serving from HBM; raise "
                        "kv_pages, raise host_kv_bytes, or shed "
                        "long-context load (docs/SERVING.md 'KV-page "
                        "tiering')"),
        AlertRule(
            name="spec_acceptance_low", severity="warning",
            kind="threshold", op="<", threshold=0.1,
            for_s=2 * alert_interval_s,
            source=_serving_spec_acceptance,
            description="the speculative draft lane's acceptance rate is "
                        "under 10% — draft passes are being paid without "
                        "shortening decode; lower spec_tokens, deepen "
                        "draft_layers / pick a better draft_preset, or "
                        "set speculative=off (docs/SERVING.md "
                        "'Speculative decoding')"),
        AlertRule(
            name="generate_slot_leak", severity="critical",
            kind="threshold", op=">", threshold=0.0,
            for_s=alert_interval_s,
            source=_serving_stalled_slot_counter(slot_leak_after_s),
            description="a busy serving slot has emitted nothing for "
                        "slot_leak_after_s — occupancy without progress "
                        "starves admission (docs/SERVING.md)"),
        AlertRule(
            name="engine_crash_loop", severity="critical",
            kind="threshold", op=">", threshold=0.0, for_s=0.0,
            source=_engine_crash_loop,
            description="the serving engine's restart budget is exhausted "
                        "— the crash-loop breaker is open and /api/generate "
                        "is 503ing with the reason until a cooldown-gated "
                        "rebuild succeeds (docs/ROBUSTNESS.md 'Serving "
                        "data plane')"),
        AlertRule(
            name="generate_deadline_timeouts", severity="warning",
            kind="increase",
            metric="tpuhive_generate_deadline_timeouts_total",
            op=">", threshold=0.0, window_s=300.0,
            for_s=0.0,
            description="generation requests hit their per-request "
                        "deadline in the last 5 minutes (queue, prefill or "
                        "mid-decode) — capacity is short of the latency "
                        "budget; add slots/pages or shed load "
                        "(docs/ROBUSTNESS.md 'Serving data plane')"),
        AlertRule(
            name="slo_burn_fast", severity="critical",
            kind="threshold", op=">=", threshold=14.4, for_s=0.0,
            source=_slo_fast_burn,
            description="an SLO's error budget is burning >= 14.4x over "
                        "BOTH the 5m and 1h windows — at this rate a "
                        "30-day budget is gone in ~2 days; page now "
                        "(docs/OBSERVABILITY.md 'History, SLOs & flight "
                        "recorder')"),
        AlertRule(
            name="slo_burn_slow", severity="warning",
            kind="threshold", op=">=", threshold=6.0, for_s=0.0,
            source=_slo_slow_burn,
            description="an SLO's error budget is burning >= 6x over "
                        "BOTH the 30m and 6h windows — a sustained slow "
                        "leak that exhausts the budget well before the "
                        "window rolls (docs/OBSERVABILITY.md 'History, "
                        "SLOs & flight recorder')"),
        AlertRule(
            name="tenant_dominates_capacity", severity="warning",
            kind="threshold", op=">", threshold=dominance_share,
            for_s=2 * alert_interval_s,
            source=_tenant_dominance,
            description="one tenant holds more than [accounting] "
                        "dominance_share of attributed device-seconds "
                        "over the accounting window WHILE p95 queue wait "
                        "breaches its SLO — a noisy neighbor is crowding "
                        "out the queue; quiet when accounting is off or "
                        "the queue is healthy (docs/OBSERVABILITY.md "
                        "'Tenant accounting')"),
    ]


# -- process-wide engine -----------------------------------------------------
_engine: Optional[AlertEngine] = None
_engine_lock = lockwitness.Lock(
    "tensorhive_tpu.observability.alerts._engine_lock")


def get_alert_engine() -> AlertEngine:
    """Process-wide engine over the default rule pack (what the
    AlertingService evaluates and /api/admin/alerts dumps); built lazily so
    the rule pack reads the materialized config."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = AlertEngine(default_rule_pack())
        return _engine


def set_alert_engine(engine: Optional[AlertEngine]) -> None:
    """Replace (or with None: drop, to be lazily rebuilt) the process-wide
    engine — test isolation and custom rule packs."""
    global _engine
    with _engine_lock:
        _engine = engine


def _collect_alert_gauges(registry: MetricsRegistry) -> None:
    """Registry collector: refresh the firing gauges at exposition time. The
    engine is built on first scrape if nothing built it earlier, so
    ``tpuhive_alerts_firing`` children exist in every scrape."""
    get_alert_engine().export_gauges()


def _register_exports() -> Tuple[object, object]:
    from . import get_registry

    registry = get_registry()
    firing = registry.gauge(
        "tpuhive_alerts_firing",
        "1 while the named alert rule is firing, else 0.",
        labels=("rule", "severity"))
    webhook_failures = registry.counter(
        "tpuhive_alert_webhook_failures_total",
        "Alert webhook deliveries dropped after exhausting retries.")
    registry.register_collector(_collect_alert_gauges)
    return firing, webhook_failures


_FIRING_GAUGE, _WEBHOOK_FAILURES = _register_exports()
