"""Unified observability layer: metrics registry + span tracer.

The reference's only runtime profiling was hand-rolled ``perf_counter``
bookkeeping inside each service loop (MonitoringService.py:38-54; SURVEY.md
§5 Tracing) — numbers that died in debug logs. This package gives the whole
control plane one place where hot-path latencies are measured and exported:

* :mod:`.metrics` — a thread-safe in-process registry (counters, gauges,
  fixed-bucket histograms) rendered in Prometheus text format at
  ``GET /api/metrics`` (controllers/observability.py).
* :mod:`.tracing` — a bounded ring-buffer span tracer with parent ids,
  dumped at ``GET /api/admin/traces`` (admin-auth).

Metric naming scheme: ``tpuhive_<subsystem>_<what>_<unit>`` — documented in
docs/OBSERVABILITY.md. Everything here is stdlib-only so workload-side code
(telemetry.py) can import it on the training-loop path without pulling in
the API stack.
"""
from __future__ import annotations

from .metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from .tracing import Span, SpanTracer

_registry = MetricsRegistry()
_tracer = SpanTracer()


def get_registry() -> MetricsRegistry:
    """Process-wide metrics registry (what /api/metrics renders)."""
    return _registry


def get_tracer() -> SpanTracer:
    """Process-wide span tracer (what /api/admin/traces dumps)."""
    return _tracer


def reset_observability() -> None:
    """Zero all metric values and drop recorded spans (test isolation).

    Metric families and their child references stay valid — instrumented
    modules hold family/child handles created at import time, so a reset
    must clear values in place rather than discard the objects.
    """
    _registry.reset_values()
    _tracer.clear()


__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Span",
    "SpanTracer",
    "get_registry",
    "get_tracer",
    "reset_observability",
]
