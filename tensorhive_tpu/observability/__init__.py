"""Unified observability layer: metrics registry + span tracer.

The reference's only runtime profiling was hand-rolled ``perf_counter``
bookkeeping inside each service loop (MonitoringService.py:38-54; SURVEY.md
§5 Tracing) — numbers that died in debug logs. This package gives the whole
control plane one place where hot-path latencies are measured and exported:

* :mod:`.metrics` — a thread-safe in-process registry (counters, gauges,
  fixed-bucket histograms) rendered in Prometheus text format at
  ``GET /api/metrics`` (controllers/observability.py).
* :mod:`.tracing` — a bounded ring-buffer span tracer with parent ids,
  dumped at ``GET /api/admin/traces`` (admin-auth).

Metric naming scheme: ``tpuhive_<subsystem>_<what>_<unit>`` — documented in
docs/OBSERVABILITY.md. Everything here is stdlib-only so workload-side code
(telemetry.py) can import it on the training-loop path without pulling in
the API stack.
"""
from __future__ import annotations

from .metrics import (
    DEFAULT_BUCKETS,
    PROCESS_START_TS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    register_process_metrics,
)
from .requests import RequestLedger, RequestRecord, get_request_ledger
from .tracing import Span, SpanLogFilter, SpanTracer

_registry = MetricsRegistry()
_tracer = SpanTracer()

from .. import __version__ as _version  # noqa: E402  (cheap: pure-constant module)

# build info + lazy process self-metrics (RSS/threads/uptime/fds) on the
# process-wide registry, refreshed by a collector at exposition time
register_process_metrics(_registry, _version)

# per-device live-HBM gauges refreshed at scrape time — inert (and jax-free)
# unless [profiling] is enabled AND jax is already in the process
from .profiling import hbm_collector as _hbm_collector  # noqa: E402

_registry.register_collector(_hbm_collector)


def get_registry() -> MetricsRegistry:
    """Process-wide metrics registry (what /api/metrics renders)."""
    return _registry


def get_tracer() -> SpanTracer:
    """Process-wide span tracer (what /api/admin/traces dumps)."""
    return _tracer


def reset_observability() -> None:
    """Zero all metric values, drop recorded spans, and discard alert-engine
    state (test isolation).

    Metric families and their child references stay valid — instrumented
    modules hold family/child handles created at import time, so a reset
    must clear values in place rather than discard the objects. The alert
    engine by contrast is dropped outright (rebuilt lazily on next use) —
    its rule thresholds derive from config, which tests swap per-case.
    """
    _registry.reset_values()
    _tracer.clear()
    _ledger_singleton = get_request_ledger()
    _ledger_singleton.clear()
    from .alerts import set_alert_engine

    set_alert_engine(None)
    # same lazy-rebuild contract for the history store and SLO engine:
    # their allowlist/objectives derive from config, which tests swap
    from .history import set_metrics_history
    from .slo import set_slo_engine

    set_metrics_history(None)
    set_slo_engine(None)
    # the tenant meter rebuilds lazily from [accounting] too
    from .accounting import set_tenant_meter

    set_tenant_meter(None)


__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "PROCESS_START_TS",
    "RequestLedger",
    "RequestRecord",
    "Span",
    "SpanLogFilter",
    "SpanTracer",
    "get_registry",
    "get_request_ledger",
    "get_tracer",
    "register_process_metrics",
    "reset_observability",
]
