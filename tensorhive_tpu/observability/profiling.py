"""On-demand device profiling: bounded trace captures + live-HBM snapshots.

The perf campaign (ROADMAP item 5, docs/PERF.md) needs on-chip evidence —
which kernels a decode step actually runs, where HBM goes — but shelling
into a serving host to wrap code in ``jax.profiler.trace`` is not an
operator workflow. This module gives the admin API two capture surfaces:

* :func:`capture_trace` (``POST /api/admin/profile``) — run
  ``jax.profiler.start_trace``/``stop_trace`` around a bounded sleep so the
  steady-state serving traffic of the next N seconds lands in a TensorBoard
  -loadable artifact under the configured dir. **Single-flight**: the XLA
  profiler is a process-wide singleton, so a second concurrent capture is
  refused (the API maps that to 409) instead of corrupting the first.
* :func:`device_memory_summary` (``GET /api/admin/profile/memory``) — a
  ``jax.profiler.device_memory_profile`` snapshot parsed down to per-device
  live bytes/allocation counts, also exported as
  ``tpuhive_device_hbm_live_bytes{device}`` so HBM growth is scrapeable and
  correlatable with the KV-pages gauges (docs/OBSERVABILITY.md).

The pprof parsing is a minimal varint walk over the two message levels we
need (sample values + labels + string table) — the full protobuf toolchain
is deliberately not a dependency. Everything importing jax does so lazily:
this module is imported by the controllers package on every boot, including
processes that never touch a device.
"""
from __future__ import annotations

import logging
import sys
import time
from pathlib import Path
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from .metrics import MetricsRegistry

from ..utils import lockwitness

log = logging.getLogger(__name__)

#: hard ceiling no config can raise — a "profile for an hour" typo must not
#: leave the process-wide profiler wedged for an hour
ABSOLUTE_MAX_DURATION_S = 60.0


class ProfileInFlightError(Exception):
    """A trace capture is already running (the profiler is process-wide);
    the API layer answers 409 so the first capture finishes uncorrupted."""


class ProfileUnavailableError(Exception):
    """Profiling is disabled by config (or jax cannot start the profiler);
    the API layer answers 404 with the reason."""


# -- trace capture (single-flight) -------------------------------------------

_capture_lock = lockwitness.Lock(
    "tensorhive_tpu.observability.profiling._capture_lock")


def capture_trace(artifact_dir: str, duration_s: float,
                  max_duration_s: float = ABSOLUTE_MAX_DURATION_S,
                  sleep: Callable[[float], None] = time.sleep,
                  tracer=None) -> Dict:
    """Capture one bounded ``jax.profiler`` trace into ``artifact_dir``.

    Blocks the calling thread for ``duration_s`` (validated against both
    the configured and the absolute ceiling) while every thread's device
    activity streams into the artifact — the caller IS the admin request,
    and a bounded synchronous capture beats a background job the operator
    then has to poll. Returns artifact metadata (dir, files, bytes).
    """
    if not duration_s > 0:
        raise ValueError(f"durationS must be > 0, got {duration_s}")
    ceiling = min(float(max_duration_s), ABSOLUTE_MAX_DURATION_S)
    if duration_s > ceiling:
        raise ValueError(
            f"durationS {duration_s} exceeds the capture ceiling {ceiling}s "
            "([profiling] max_duration_s)")
    if not _capture_lock.acquire(blocking=False):
        raise ProfileInFlightError(
            "a profile capture is already in flight — the device profiler "
            "is process-wide; retry when it finishes")
    try:
        import jax

        target = Path(artifact_dir)
        target.mkdir(parents=True, exist_ok=True)
        started_ts = time.time()
        started = time.perf_counter()
        try:
            jax.profiler.start_trace(str(target))
        except Exception as exc:
            raise ProfileUnavailableError(
                f"cannot start the device profiler: "
                f"{type(exc).__name__}: {exc}") from exc
        try:
            sleep(duration_s)
        finally:
            jax.profiler.stop_trace()
        elapsed_s = time.perf_counter() - started
        files = _artifact_files(target, newer_than=started_ts)
        total_bytes = sum(size for _, size in files)
        result = {
            "artifactDir": str(target),
            "durationS": round(elapsed_s, 3),
            "startedTs": round(started_ts, 3),
            "files": [name for name, _ in files],
            "bytes": total_bytes,
        }
        if tracer is not None:
            tracer.record_span("profile.capture", kind="profile",
                               start_ts=started_ts, duration_s=elapsed_s,
                               artifact_dir=str(target), bytes=total_bytes)
        log.info("profile capture: %.2fs -> %s (%d files, %d bytes)",
                 elapsed_s, target, len(files), total_bytes)
        return result
    finally:
        _capture_lock.release()


def capture_in_flight() -> bool:
    """Whether a trace capture currently holds the single-flight lock."""
    if _capture_lock.acquire(blocking=False):
        _capture_lock.release()
        return False
    return True


def _artifact_files(root: Path,
                    newer_than: float) -> List[Tuple[str, int]]:
    """Profiler output files under ``root`` written by THIS capture
    (mtime-filtered: repeated captures share the dir), relative paths."""
    files = []
    for path in sorted(root.rglob("*")):
        if not path.is_file():
            continue
        stat = path.stat()
        # 1s slack: coarse filesystem mtime granularity must not hide the
        # artifact this capture just wrote
        if stat.st_mtime >= newer_than - 1.0:
            files.append((str(path.relative_to(root)), stat.st_size))
    return files


# -- device memory profile ----------------------------------------------------

def _varints(buf: bytes) -> Iterator[int]:
    value = shift = 0
    for byte in buf:
        value |= (byte & 0x7F) << shift
        if byte & 0x80:
            shift += 7
        else:
            yield value
            value = shift = 0


def _fields(buf: bytes) -> Iterator[Tuple[int, object]]:
    """Walk one protobuf message's (field_number, payload) pairs — varint
    fields yield ints, length-delimited fields yield bytes."""
    i = 0
    length = len(buf)
    while i < length:
        tag = shift = 0
        while True:
            byte = buf[i]
            i += 1
            tag |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
        field_number, wire_type = tag >> 3, tag & 7
        if wire_type == 0:                     # varint
            value = shift = 0
            while True:
                byte = buf[i]
                i += 1
                value |= (byte & 0x7F) << shift
                if not byte & 0x80:
                    break
                shift += 7
            yield field_number, value
        elif wire_type == 2:                   # length-delimited
            size = shift = 0
            while True:
                byte = buf[i]
                i += 1
                size |= (byte & 0x7F) << shift
                if not byte & 0x80:
                    break
                shift += 7
            yield field_number, buf[i:i + size]
            i += size
        elif wire_type == 5:                   # fixed32
            i += 4
        elif wire_type == 1:                   # fixed64
            i += 8
        else:
            raise ValueError(f"unsupported pprof wire type {wire_type}")


def parse_device_memory_profile(profile: bytes) -> Dict[str, Dict[str, int]]:
    """Reduce a ``jax.profiler.device_memory_profile()`` blob (gzipped pprof
    ``Profile`` proto) to ``{device: {"liveBytes": n, "allocations": n}}``.

    Only ``kind=buffer`` samples count — executable allocations carry no
    device label and describe compiled-code host memory, not HBM. Samples
    the runtime leaves unattributed aggregate under ``"unattributed"``.
    """
    import gzip

    raw = gzip.decompress(profile)
    strings: List[str] = []
    samples: List[bytes] = []
    for field_number, payload in _fields(raw):
        if field_number == 6:                              # string_table
            strings.append(payload.decode("utf-8", "replace"))
        elif field_number == 2:                            # sample
            samples.append(payload)
    per_device: Dict[str, Dict[str, int]] = {}
    for sample in samples:
        values: List[int] = []
        labels: Dict[str, str] = {}
        for field_number, payload in _fields(sample):
            if field_number == 2:          # repeated int64 values
                if isinstance(payload, bytes):     # packed encoding
                    values.extend(_varints(payload))
                else:
                    values.append(payload)
            elif field_number == 3:        # Label {key=1, str=2, num=3}
                parts = dict(_fields(payload))
                key = strings[parts.get(1, 0)]
                if 2 in parts:
                    labels[key] = strings[parts[2]]
        if labels.get("kind") != "buffer":
            continue
        device = labels.get("device", "unattributed")
        entry = per_device.setdefault(device,
                                      {"liveBytes": 0, "allocations": 0})
        # sample_type order is fixed by the XLA exporter:
        # [(allocations, count), (space, bytes)]
        entry["allocations"] += values[0] if values else 0
        entry["liveBytes"] += values[1] if len(values) > 1 else 0
    return per_device


def device_memory_summary(
        registry: Optional[MetricsRegistry] = None) -> Dict:
    """One ``device_memory_profile`` snapshot: parsed per-device live bytes
    (gauged as ``tpuhive_device_hbm_live_bytes{device}``) plus the raw blob
    size so callers can fetch the full pprof when the summary is not
    enough."""
    import jax

    profile = jax.profiler.device_memory_profile()
    per_device = parse_device_memory_profile(profile)
    if registry is not None:
        _set_live_bytes_gauges(registry, per_device)
    devices = [
        {"device": device,
         "liveBytes": entry["liveBytes"],
         "allocations": entry["allocations"]}
        for device, entry in sorted(per_device.items())
    ]
    return {
        "capturedTs": round(time.time(), 3),
        "devices": devices,
        "totalLiveBytes": sum(d["liveBytes"] for d in devices),
        "profileBytes": len(profile),
    }


def raw_device_memory_profile() -> bytes:
    """The unparsed gzipped pprof blob (``?format=pprof``) for
    ``pprof``/``go tool pprof`` style offline analysis."""
    import jax

    return jax.profiler.device_memory_profile()


def _set_live_bytes_gauges(registry: MetricsRegistry,
                           per_device: Dict[str, Dict[str, int]]) -> None:
    family = registry.gauge(
        "tpuhive_device_hbm_live_bytes",
        "Live device-memory bytes per device from the XLA memory profiler "
        "(kind=buffer samples) — the scrapeable HBM-growth signal that "
        "correlates with the KV-pages gauges.",
        labels=("device",))
    for device, entry in per_device.items():
        family.labels(device=device).set(entry["liveBytes"])


def hbm_collector(registry: MetricsRegistry) -> None:
    """Registry collector: refresh the live-bytes gauges at scrape time.

    Guarded three ways so a bare ``/api/metrics`` scrape stays cheap and
    jax-free on processes that never serve: profiling must be enabled in
    config, jax must ALREADY be imported (a scrape never pulls in the model
    stack), and a capture in flight is left alone (the memory profiler and
    the trace profiler share runtime plumbing)."""
    if "jax" not in sys.modules:
        return
    try:
        from ..config import get_config

        if not get_config().profiling.enabled:
            return
    except Exception:
        # config not materialized (bare library use): nothing to scrape;
        # debug-level — this runs on every exposition
        log.debug("hbm collector: config unavailable", exc_info=True)
        return
    if capture_in_flight():
        return
    try:
        import jax

        per_device = parse_device_memory_profile(
            jax.profiler.device_memory_profile())
    except Exception:
        log.warning("hbm collector: device_memory_profile failed",
                    exc_info=True)
        return
    _set_live_bytes_gauges(registry, per_device)
