"""Tenant attribution plane: per-tenant resource·time metering.

One :class:`TenantMeter` integrates *resource × time* products per tenant
across both planes:

* **Serving** — the :class:`~tensorhive_tpu.serving.engine.SlotEngine`
  pump thread stamps device-seconds (busy slot-seconds × mesh devices),
  HBM-byte-seconds (resident KV pages × bytes/page, host-tier bytes
  metered separately), queue-seconds and token counters
  (prefill/decode/cached/speculative-accepted), keyed by the request
  ledger's ``userKey``. Pure host bookkeeping: zero traced operands,
  zero new compile fingerprints.
* **Reservations** — ``UsageLoggingService`` feeds reservation
  chip-seconds plus duty-cycle-weighted *effective* chip-seconds per
  reservation owner.

Rollups answer "who consumed which fraction of the chips, HBM and queue
over the last hour": totals are snapshotted on a coarse cadence so
``rollup(window_s)`` returns the delta against the snapshot at the
window's left edge. Export is bounded-cardinality by construction: the
``tpuhive_tenant_*`` counter families carry the top-K tenants by
lifetime device-seconds plus a single ``other`` overflow bucket — at
most K+1 children per family no matter how many distinct users hit the
API (a membership change surfaces as a Prometheus counter reset on the
``other`` child, which ``MetricsHistory.increase()`` already absorbs).

``[accounting] enabled = false`` is a byte-identical rollback:
:func:`get_tenant_meter` returns ``None``, every instrumentation site
takes its meter-less fast path, the collector publishes no children (so
``render()`` emits zero ``tpuhive_tenant_*`` series) and the admin
endpoint 404s.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, fields
from typing import Callable, Deque, Dict, List, Mapping, Optional, Tuple

from ..utils import lockwitness

log = logging.getLogger("tensorhive_tpu.observability.accounting")

#: label value of the overflow bucket that absorbs every tenant outside
#: the top-K by lifetime device-seconds
OVERFLOW_TENANT = "other"

#: tenant key for serving requests submitted without a user key (bare
#: library use / unauthenticated test traffic)
ANONYMOUS_TENANT = "anonymous"

#: ``kind`` label values of ``tpuhive_tenant_tokens_total``
TOKEN_KINDS = ("prefill", "decode", "cached", "spec_accepted")


@dataclass
class TenantUsage:
    """Cumulative resource·time products for one tenant (all monotonic)."""

    device_seconds: float = 0.0         # busy slot-seconds x mesh devices
    kv_byte_seconds: float = 0.0        # HBM-resident KV bytes x seconds
    host_kv_byte_seconds: float = 0.0   # host-tier (parked/demoted) bytes x s
    queue_seconds: float = 0.0          # admission-queue wait
    prefill_tokens: float = 0.0         # prompt tokens actually computed
    decode_tokens: float = 0.0          # emitted decode tokens
    cached_tokens: float = 0.0          # prompt tokens served from the radix cache
    spec_accepted_tokens: float = 0.0   # draft tokens accepted by the verifier
    reserved_chip_seconds: float = 0.0  # reservation wall-clock x chips
    effective_chip_seconds: float = 0.0  # duty-cycle-weighted chip-seconds

    def copy(self) -> "TenantUsage":
        return TenantUsage(**{f.name: getattr(self, f.name)
                              for f in fields(self)})

    def add(self, other: "TenantUsage") -> None:
        for f in fields(self):
            setattr(self, f.name, getattr(self, f.name) + getattr(other, f.name))

    def delta(self, baseline: Optional["TenantUsage"]) -> "TenantUsage":
        """``self - baseline`` clamped at zero per component."""
        if baseline is None:
            return self.copy()
        out = TenantUsage()
        for f in fields(self):
            out_v = getattr(self, f.name) - getattr(baseline, f.name)
            setattr(out, f.name, out_v if out_v > 0.0 else 0.0)
        return out

    def is_zero(self) -> bool:
        return all(getattr(self, f.name) == 0.0 for f in fields(self))

    def to_dict(self) -> Dict[str, float]:
        return {f.name: getattr(self, f.name) for f in fields(self)}


class TenantMeter:
    """Thread-safe per-tenant resource·time accumulator with windowed
    rollups and a bounded-cardinality export view.

    The meter's lock is a **leaf**: callers (the engine pump under the
    engine lock, UsageLoggingService, the metrics collector) only ever
    take it last and never call out while holding it, so no new
    lock-order edges can close a cycle (TH-LOCK).
    """

    def __init__(self, top_k: int = 8, window_s: float = 3600.0,
                 snapshot_interval_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.top_k = int(top_k)
        self.window_s = float(window_s)
        # default cadence: ~120 baselines across the default window; a
        # bounded deque caps memory no matter how long the process lives
        if snapshot_interval_s is None:
            snapshot_interval_s = max(1.0, self.window_s / 120.0)
        self.snapshot_interval_s = float(snapshot_interval_s)
        self.clock = clock
        self._lock = lockwitness.Lock("TenantMeter._lock")
        self._totals: Dict[str, TenantUsage] = {}
        maxlen = int(self.window_s / self.snapshot_interval_s) + 8
        self._snapshots: Deque[Tuple[float, Dict[str, TenantUsage]]] = \
            deque(maxlen=maxlen)
        self._last_snapshot_ts: Optional[float] = None

    # -- internals ------------------------------------------------------------
    def _usage_locked(self, tenant: str) -> TenantUsage:
        usage = self._totals.get(tenant)
        if usage is None:
            usage = TenantUsage()
            self._totals[tenant] = usage
        return usage

    def _maybe_snapshot_locked(self) -> None:
        now = self.clock()
        if (self._last_snapshot_ts is not None
                and now - self._last_snapshot_ts < self.snapshot_interval_s):
            return
        self._last_snapshot_ts = now
        self._snapshots.append(
            (now, {t: u.copy() for t, u in self._totals.items()}))

    # -- serving-plane feeds --------------------------------------------------
    def charge_tick(self, charges: Mapping[str, Tuple[float, float, float]]
                    ) -> None:
        """One engine pump tick: ``{tenant: (device_s, kv_byte_s,
        host_kv_byte_s)}`` computed by the caller from a single dt
        sample, so conservation against the engine's own busy
        slot-second integral is exact."""
        if not charges:
            return
        with self._lock:
            for tenant, (device_s, kv_byte_s, host_kv_byte_s) in \
                    charges.items():
                usage = self._usage_locked(tenant)
                usage.device_seconds += device_s
                usage.kv_byte_seconds += kv_byte_s
                usage.host_kv_byte_seconds += host_kv_byte_s
            self._maybe_snapshot_locked()

    def charge_queue(self, tenant: str, seconds: float) -> None:
        if seconds <= 0:
            return
        with self._lock:
            self._usage_locked(tenant).queue_seconds += seconds
            self._maybe_snapshot_locked()

    def count_tokens(self, tenant: str, kind: str, n: float) -> None:
        if n <= 0:
            return
        if kind not in TOKEN_KINDS:
            raise ValueError(f"unknown token kind {kind!r}; "
                             f"expected one of {TOKEN_KINDS}")
        with self._lock:
            usage = self._usage_locked(tenant)
            setattr(usage, f"{kind}_tokens",
                    getattr(usage, f"{kind}_tokens") + n)
            self._maybe_snapshot_locked()

    # -- reservation-plane feed -----------------------------------------------
    def charge_reservation(self, tenant: str, chip_seconds: float,
                           effective_chip_seconds: Optional[float] = None
                           ) -> None:
        if chip_seconds <= 0:
            return
        with self._lock:
            usage = self._usage_locked(tenant)
            usage.reserved_chip_seconds += chip_seconds
            if effective_chip_seconds is not None \
                    and effective_chip_seconds > 0:
                usage.effective_chip_seconds += effective_chip_seconds
            self._maybe_snapshot_locked()

    # -- reads ----------------------------------------------------------------
    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._totals)

    def totals(self) -> Dict[str, TenantUsage]:
        with self._lock:
            return {t: u.copy() for t, u in self._totals.items()}

    def rollup(self, window_s: Optional[float] = None,
               now: Optional[float] = None) -> Dict[str, TenantUsage]:
        """Per-tenant usage over the trailing window: current totals
        minus the newest snapshot at or before ``now - window_s``
        (missing baseline = process-lifetime totals)."""
        if window_s is None:
            window_s = self.window_s
        with self._lock:
            if now is None:
                now = self.clock()
            cutoff = now - window_s
            baseline: Dict[str, TenantUsage] = {}
            for ts, snap in self._snapshots:
                if ts <= cutoff:
                    baseline = snap
                else:
                    break
            out: Dict[str, TenantUsage] = {}
            for tenant, usage in self._totals.items():
                d = usage.delta(baseline.get(tenant))
                if not d.is_zero():
                    out[tenant] = d
            return out

    def export_totals(self) -> Dict[str, TenantUsage]:
        """Bounded-cardinality view for the metric exporter: the top-K
        tenants by lifetime device-seconds keep their identity, the
        rest collapse into :data:`OVERFLOW_TENANT` — at most K+1 keys.
        ``other`` only exists while there is overflow."""
        with self._lock:
            ranked = sorted(
                self._totals.items(),
                key=lambda item: (-item[1].device_seconds, item[0]))
            out: Dict[str, TenantUsage] = {}
            overflow: Optional[TenantUsage] = None
            for rank, (tenant, usage) in enumerate(ranked):
                if rank < self.top_k:
                    out[tenant] = usage.copy()
                else:
                    if overflow is None:
                        overflow = TenantUsage()
                    overflow.add(usage)
            if overflow is not None:
                out[OVERFLOW_TENANT] = overflow
            return out


# -- process-wide meter + config lifecycle ------------------------------------
_meter: Optional[TenantMeter] = None
_meter_built = False
_meter_lock = lockwitness.Lock(
    "tensorhive_tpu.observability.accounting._meter_lock")


def _accounting_enabled() -> bool:
    try:
        from ..config import get_config

        return bool(get_config().accounting.enabled)
    except Exception:
        log.debug("accounting: config unavailable, defaulting enabled",
                  exc_info=True)
        return True     # bare library use: on, matching AccountingConfig


def get_tenant_meter() -> Optional[TenantMeter]:
    """Process-wide meter, built lazily from ``[accounting]`` — or
    ``None`` while accounting is disabled (every caller's rollback fast
    path)."""
    global _meter, _meter_built
    with _meter_lock:
        if not _meter_built:
            _meter_built = True
            if _accounting_enabled():
                top_k, window_s = 8, 3600.0
                try:
                    from ..config import get_config

                    accounting = get_config().accounting
                    top_k = accounting.top_k_tenants
                    window_s = accounting.window_s
                except Exception:
                    log.debug("accounting: config unavailable, using "
                              "defaults", exc_info=True)
                _meter = TenantMeter(top_k=top_k, window_s=window_s)
            else:
                _meter = None
        return _meter


def set_tenant_meter(meter: Optional[TenantMeter]) -> None:
    """Install a meter (tests), or ``None`` to drop state and rebuild
    lazily from config on the next :func:`get_tenant_meter`."""
    global _meter, _meter_built
    with _meter_lock:
        _meter = meter
        _meter_built = meter is not None


# -- alert source -------------------------------------------------------------

def dominance_signal(now: Optional[float] = None) -> Optional[float]:
    """AlertRule source for ``tenant_dominates_capacity``: the largest
    single-tenant share of attributed device-seconds over the
    accounting window, but only while queue-wait SLO pressure exists
    (p95 admission wait above ``[generation_service] queue_wait_slo_s``)
    — a dominant tenant on an idle box is not a noisy neighbor. Returns
    ``None`` (rule stays quiet) when accounting is off, no engine runs,
    the queue is healthy, or the window attributed nothing."""
    meter = get_tenant_meter()
    if meter is None:
        return None
    try:
        from ..serving import get_engine

        engine = get_engine()
    except Exception:
        log.debug("accounting: serving plane unavailable for dominance "
                  "signal", exc_info=True)
        return None
    if engine is None:
        return None
    queue_wait_slo_s = 1.0
    try:
        from ..config import get_config

        queue_wait_slo_s = get_config().generation.queue_wait_slo_s
    except Exception:
        log.debug("accounting: config unavailable for dominance signal",
                  exc_info=True)
    p95 = engine.queue_wait_p95_s()
    if p95 is None or p95 <= queue_wait_slo_s:
        return None
    rollup = meter.rollup(now=now)
    total = sum(u.device_seconds for u in rollup.values())
    if total <= 0:
        return None
    return max(u.device_seconds for u in rollup.values()) / total


# -- metric export ------------------------------------------------------------

def _sync_counter_family(family, desired: Mapping[Tuple[str, ...], float]
                         ) -> None:
    """Drive a counter family to absolute per-child targets and drop
    every child outside ``desired`` (cardinality bound). Safe only
    because the accounting collector is the sole writer of the tenant
    families: a target below the child's current value (top-K
    membership change shrinking ``other``) re-creates the child — a
    plain Prometheus counter reset."""
    current = {key: child.value for key, child in family.children()}
    keep = [key for key, value in current.items()
            if key in desired and desired[key] >= value]
    family.retain_children(keep)
    for key, target in desired.items():
        if target <= 0:
            continue
        child = family.labels(**dict(zip(family.label_names, key)))
        delta = target - child.value
        if delta > 0:
            child.inc(delta)


def _register_exports():
    from . import get_registry

    registry = get_registry()
    device = registry.counter(
        "tpuhive_tenant_device_seconds_total",
        "Busy slot-seconds x mesh devices attributed per tenant "
        "(top-K by device-seconds + an 'other' overflow bucket; "
        "K = [accounting] top_k_tenants).",
        labels=("tenant",))
    kv = registry.counter(
        "tpuhive_tenant_kv_byte_seconds_total",
        "HBM-resident KV-cache byte-seconds per tenant (int8-aware via "
        "kv_bytes_per_token; same top-K + 'other' bound).",
        labels=("tenant",))
    host_kv = registry.counter(
        "tpuhive_tenant_host_kv_byte_seconds_total",
        "Host-RAM-tier KV byte-seconds per tenant (parked slots whose "
        "pages were demoted to the PR 18 host store).",
        labels=("tenant",))
    queue = registry.counter(
        "tpuhive_tenant_queue_seconds_total",
        "Admission-queue wait seconds per tenant.",
        labels=("tenant",))
    tokens = registry.counter(
        "tpuhive_tenant_tokens_total",
        "Tokens per tenant split by kind: prefill | decode | cached | "
        "spec_accepted.",
        labels=("tenant", "kind"))
    reserved = registry.counter(
        "tpuhive_tenant_reserved_chip_seconds_total",
        "Reservation wall-clock chip-seconds per owner "
        "(UsageLoggingService cadence x reserved chips).",
        labels=("tenant",))
    effective = registry.counter(
        "tpuhive_tenant_effective_chip_seconds_total",
        "Duty-cycle-weighted reservation chip-seconds per owner — the "
        "chips actually exercised, not merely held.",
        labels=("tenant",))

    def _collect_tenant_usage(_registry) -> None:
        meter = get_tenant_meter()
        if meter is None:
            # disabled: publish nothing; families with zero children are
            # skipped by render(), so the rollback emits zero series
            for family in (device, kv, host_kv, queue, tokens, reserved,
                           effective):
                family.retain_children(())
            return
        export = meter.export_totals()
        _sync_counter_family(device, {
            (t,): u.device_seconds for t, u in export.items()})
        _sync_counter_family(kv, {
            (t,): u.kv_byte_seconds for t, u in export.items()})
        _sync_counter_family(host_kv, {
            (t,): u.host_kv_byte_seconds for t, u in export.items()})
        _sync_counter_family(queue, {
            (t,): u.queue_seconds for t, u in export.items()})
        _sync_counter_family(tokens, {
            (t, kind): getattr(u, f"{kind}_tokens")
            for t, u in export.items() for kind in TOKEN_KINDS})
        _sync_counter_family(reserved, {
            (t,): u.reserved_chip_seconds for t, u in export.items()})
        _sync_counter_family(effective, {
            (t,): u.effective_chip_seconds for t, u in export.items()})

    registry.register_collector(_collect_tenant_usage)


_register_exports()
