"""Pipeline parallelism: transformer stages across the ``pp`` mesh axis.

GPipe-style SPMD pipeline, formulated the TPU-idiomatic way (one program,
no per-stage processes): ``jax.shard_map`` is manual over ONLY the ``pp``
axis (``axis_names={"pp"}``) — dp/fsdp/tp stay automatic, so the per-stage
compute (flash-attention pallas kernels included) keeps its GSPMD
partitioning. Stage parameters are the per-layer block pytree stacked on a
leading layer dim and sharded ``P("pp", ...)``: each rank holds
``n_layers / pp`` contiguous layers and scans over them.

Schedule: the batch splits into M microbatches; for ``M + pp - 1`` steps
every rank applies its stage to the activation it currently holds and
hands the result to the next rank with ``lax.ppermute``; rank 0 injects
microbatch ``t`` at step ``t``, the last rank emits finished microbatches
into an accumulator that a final ``psum`` replicates (every other rank
contributes zeros). The pipeline bubble is the standard
``(pp - 1) / (M + pp - 1)`` — raise ``num_microbatches`` to shrink it.
Autodiff flows straight through ``scan`` + ``ppermute`` (validated against
the unpipelined model in tests/unit/test_compute.py).

The reference has no counterpart (SURVEY.md §2.6: the reference templates
launch topology only); this is compute-stack capability beyond it.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

#: apply one layer: (one_layer_params, x [mb, L, D], positions [mb, L]) -> x
LayerFn = Callable


def stack_blocks(blocks):
    """Per-layer list of param dicts → one pytree with leading [n_layers]
    dim (what the pipeline shards over ``pp``). In-graph stacking keeps the
    stored checkpoint layout unchanged; XLA lowers it to a reshard onto the
    stage owners. (A natively layer-stacked param store would skip that
    gather — noted for when pp goes to real pods.)"""
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves), *blocks)


def pipeline_microbatches(batch: int, mesh: Mesh,
                          requested: int = 0) -> int:
    """Microbatch count: the requested value, else one per stage; must
    divide the (global) batch."""
    pp = mesh.shape["pp"]
    count = requested or pp
    if batch % count:
        raise ValueError(
            f"batch {batch} not divisible by {count} pipeline microbatches")
    return count


def pipeline_apply(
    stacked_blocks,
    x: jax.Array,                    # [B, L, D]
    positions: jax.Array,            # [B, L] int32
    mesh: Mesh,
    apply_layer: LayerFn,
    num_microbatches: int = 0,
    seq_axis: Optional[str] = None,
) -> jax.Array:
    """Run the stacked transformer blocks as a ``pp``-stage pipeline.

    ``apply_layer`` receives ONE layer's params (a pytree slice) and a
    microbatch; wrap it in ``jax.checkpoint`` on the caller side for remat.
    Activations AND positions travel the ring together so every stage sees
    the microbatch's own positions.

    ``seq_axis``: compose with sequence parallelism — the shard_map goes
    manual over {pp, seq_axis}, activations and positions enter sharded on
    their sequence dim, and ``apply_layer`` (whose attention must then run
    the manual ring body, parallel/ring.py ``ring_attention_local``) sees
    [mb, L/sp, D] shards. The microbatch ppermute ring over pp carries the
    sp-sharded activations as-is — pp hops move microbatches between
    stages, sp hops rotate KV inside a stage; the two never exchange data
    on the same edge.
    """
    pp = mesh.shape["pp"]
    batch = x.shape[0]
    n_layers = jax.tree_util.tree_leaves(stacked_blocks)[0].shape[0]
    if n_layers % pp:
        raise ValueError(f"{n_layers} layers not divisible by pp={pp}")
    num_mb = pipeline_microbatches(batch, mesh, num_microbatches)
    mb = batch // num_mb

    manual = ("pp",) if seq_axis is None else ("pp", seq_axis)
    data_spec = P() if seq_axis is None else P(None, seq_axis, None)
    pos_spec = P() if seq_axis is None else P(None, seq_axis)

    # stage params: leading layer dim sharded over pp — P("pp") splits the
    # stacked dim so each rank's body sees [n_layers/pp, ...] leaves, with
    # the remaining dims left to the automatic axes (fsdp/tp)
    stage_spec = jax.tree_util.tree_map(
        lambda leaf: P(*(("pp",) + (None,) * (leaf.ndim - 1))), stacked_blocks)

    def body(stage_blocks, x, positions):
        # local shapes: the seq dim arrives pre-sharded when seq_axis is set
        _, seq_len, d_model = x.shape
        rank = jax.lax.axis_index("pp")
        x_mb = x.reshape(num_mb, mb, seq_len, d_model)
        pos_mb = positions.reshape(num_mb, mb, seq_len)
        ring = [(i, (i + 1) % pp) for i in range(pp)]

        def apply_stage(x_one, pos_one):
            def one_layer(carry, layer_params):
                return apply_layer(layer_params, carry, pos_one), None
            out, _ = jax.lax.scan(one_layer, x_one, stage_blocks)
            return out

        def step(carry, t):
            recv_x, recv_pos, acc = carry
            index = jnp.minimum(t, num_mb - 1)
            cur_x = jnp.where(rank == 0, x_mb[index], recv_x)
            cur_pos = jnp.where(rank == 0, pos_mb[index], recv_pos)
            out = apply_stage(cur_x, cur_pos)
            send_x = jax.lax.ppermute(out, "pp", ring)
            send_pos = jax.lax.ppermute(cur_pos, "pp", ring)
            emit = t - (pp - 1)
            acc = jnp.where(
                (rank == pp - 1) & (emit >= 0),
                acc.at[jnp.maximum(emit, 0)].set(out), acc)
            return (send_x, send_pos, acc), None

        # zeros_like inherits sp-varyingness from the sharded inputs, so
        # only the pp axis needs the explicit cast
        varying = lambda v: jax.lax.pcast(v, ("pp",), to="varying")  # noqa: E731
        carry = (varying(jnp.zeros_like(x_mb[0])),
                 varying(jnp.zeros_like(pos_mb[0])),
                 varying(jnp.zeros_like(x_mb)))
        (_, _, acc), _ = jax.lax.scan(step, carry,
                                      jnp.arange(num_mb + pp - 1))
        # only the last rank's accumulator is nonzero; psum replicates it
        return jax.lax.psum(acc, "pp").reshape(batch, seq_len, d_model)

    # NOTE this region runs under vma tracking (check_vma defaults True; a
    # partial-manual shard_map with check_vma=False rejects its own
    # out_specs in current JAX). Pallas kernels inside the region work on
    # real TPU — their out_shapes carry the inputs' vma via
    # ops/flash_attention._struct — but interpret-mode pallas does not
    # (JAX: "Primitive dynamic_slice requires varying manual axes to
    # match"), so off-TPU callers must route attention to non-pallas
    # bodies (see models/transformer._apply_trunk_pipelined).
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_spec, data_spec, pos_spec),
        out_specs=data_spec,
        axis_names=set(manual),
    )(stacked_blocks, x, positions)


def pp_enabled(mesh: Optional[Mesh]) -> bool:
    return (mesh is not None and "pp" in getattr(mesh, "axis_names", ())
            and mesh.shape["pp"] > 1)
