"""Ring attention: exact attention over sequence shards.

Long-context strategy (SURVEY.md §5 "long-context: absent in reference;
TPU build provides it"): the sequence is sharded over the ``sp`` mesh axis;
each device holds a Q/K/V block, computes blockwise attention against the
KV block it currently holds, and passes KV around the ring with
``jax.lax.ppermute`` — after ``sp`` steps every Q block has attended to the
full sequence. Online-softmax (flash-style running max/denominator)
accumulation keeps it exact in one pass; communication overlaps compute on
ICI because each ppermute is independent of the running accumulation.

Reference pattern: Ring Attention (Liu et al., 2023) — re-derived here over
``shard_map`` + XLA collectives, the idiomatic TPU formulation.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _block_attend(q, k, v, acc, row_max, row_sum, q_offset, k_offset, causal, scale):
    """One Q-block × KV-block step of streaming-softmax attention.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; acc: [B, Lq, H, D];
    row_max/row_sum: [B, Lq, H]. All f32 accumulation.
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if causal:
        q_pos = q_offset + jax.lax.iota(jnp.int32, q.shape[1])
        k_pos = k_offset + jax.lax.iota(jnp.int32, k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    block_max = jnp.max(scores, axis=-1)                       # [B, H, Lq]
    new_max = jnp.maximum(row_max, block_max.transpose(0, 2, 1))
    correction = jnp.exp(row_max - new_max)                    # [B, Lq, H]
    probs = jnp.exp(scores - new_max.transpose(0, 2, 1)[:, :, :, None])
    if causal:
        # rows with no visible keys yet: exp(NEG_INF - NEG_INF) = 1, kill them
        probs = jnp.where(mask[None, None, :, :], probs, 0.0)
    block_sum = jnp.sum(probs, axis=-1).transpose(0, 2, 1)     # [B, Lq, H]
    block_out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    acc = acc * correction[:, :, :, None] + block_out
    row_sum = row_sum * correction + block_sum
    return acc, new_max, row_sum


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, scale: float):
    """Body run per sp-shard inside shard_map. Shapes: [B, L_local, H, D]."""
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    seq_len = q.shape[1]
    q32 = q.astype(jnp.float32)

    acc = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    row_max = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    row_sum = jnp.zeros(q.shape[:3], jnp.float32)
    q_offset = my_index * seq_len

    def step(carry, _):
        k_cur, v_cur, k_index, acc, row_max, row_sum = carry
        k_offset = k_index * seq_len
        acc, row_max, row_sum = _block_attend(
            q32, k_cur.astype(jnp.float32), v_cur, acc, row_max, row_sum,
            q_offset, k_offset, causal, scale,
        )
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        k_index = (k_index - 1) % axis_size
        return (k_next, v_next, k_index, acc, row_max, row_sum), None

    carry = (k, v, my_index, acc, row_max, row_sum)
    carry, _ = jax.lax.scan(step, carry, None, length=axis_size)
    _, _, _, acc, row_max, row_sum = carry
    # rows with zero visible keys (never happens for causal with self block)
    denom = jnp.where(row_sum == 0.0, 1.0, row_sum)
    return (acc / denom[:, :, :, None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    axis_name: str = "sp",
    causal: bool = True,
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
) -> jax.Array:
    """Exact attention with the sequence sharded over ``axis_name``.

    Inputs are [batch, seq, heads, d_head] global arrays; internally each
    sp-shard sees [batch, seq/sp, heads, d_head]. Works under an outer jit
    with a mesh in context, or standalone given ``mesh``.
    """
    scale = q.shape[-1] ** -0.5
    if mesh is None or axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        # no sequence sharding: delegate to the shared dense oracle rather
        # than keeping a second copy of the same math
        from ..ops.flash_attention import reference_attention

        return reference_attention(q, k, v, causal=causal)

    spec = P(batch_axes, axis_name, head_axis, None)
    body = functools.partial(
        _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
    )
    return shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )(q, k, v)
