"""Ring attention: exact attention over sequence shards.

Long-context strategy (SURVEY.md §5 "long-context: absent in reference;
TPU build provides it"): the sequence is sharded over the ``sp`` mesh axis;
each device holds a Q/K/V block, computes blockwise attention against the
KV block it currently holds, and passes KV around the ring with
``jax.lax.ppermute`` — after ``sp`` steps every Q block has attended to the
full sequence. Online-softmax (flash-style running max/denominator)
accumulation keeps it exact in one pass; communication overlaps compute on
ICI because each ppermute is independent of the running accumulation.

Reference pattern: Ring Attention (Liu et al., 2023) — re-derived here over
``shard_map`` + XLA collectives, the idiomatic TPU formulation.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..ops.flash_attention import (
    _flash_bwd_bhsd,
    _flash_fwd_bhsd,
    _from_bhsd,
    _to_bhsd,
    default_blocks,
    flash_bwd_delta,
)

NEG_INF = -1e30


def _match_vma(x, ref):
    """Give ``x`` the same varying-manual-axes type as ``ref``.

    Inside a NEW-style partial-manual shard_map (the pipeline's, manual
    over {pp, sp}) every scan carry must carry consistent varying axes;
    fresh zero accumulators start invarying and must be pcast to match the
    data they accumulate. Outside such a region (the classic full-manual
    ``shard_map(check_rep=False)`` wrapper) avals carry no vma info and
    this is a no-op."""
    try:
        missing = tuple(a for a in jax.typeof(ref).vma
                        if a not in jax.typeof(x).vma)
    except (AttributeError, TypeError):
        return x
    return jax.lax.pcast(x, missing, to="varying") if missing else x


def _block_attend(q, k, v, acc, row_max, row_sum, q_offset, k_offset, causal, scale):
    """One Q-block × KV-block step of streaming-softmax attention.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; acc: [B, Lq, H, D];
    row_max/row_sum: [B, Lq, H]. Matmuls run in the input dtype (bf16 keeps
    the MXU on its native path — see ops/flash_attention.py) with f32
    accumulation; stats and the accumulator are f32.
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if causal:
        q_pos = q_offset + jax.lax.iota(jnp.int32, q.shape[1])
        k_pos = k_offset + jax.lax.iota(jnp.int32, k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    block_max = jnp.max(scores, axis=-1)                       # [B, H, Lq]
    new_max = jnp.maximum(row_max, block_max.transpose(0, 2, 1))
    correction = jnp.exp(row_max - new_max)                    # [B, Lq, H]
    probs = jnp.exp(scores - new_max.transpose(0, 2, 1)[:, :, :, None])
    if causal:
        # rows with no visible keys yet: exp(NEG_INF - NEG_INF) = 1, kill them
        probs = jnp.where(mask[None, None, :, :], probs, 0.0)
    block_sum = jnp.sum(probs, axis=-1).transpose(0, 2, 1)     # [B, Lq, H]
    block_out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                           preferred_element_type=jnp.float32)
    acc = acc * correction[:, :, :, None] + block_out
    row_sum = row_sum * correction + block_sum
    return acc, new_max, row_sum


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, scale: float):
    """Body run per sp-shard inside shard_map. Shapes: [B, L_local, H, D]."""
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    seq_len = q.shape[1]

    acc = _match_vma(jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32), q)
    row_max = _match_vma(jnp.full(q.shape[:3], NEG_INF, jnp.float32), q)
    row_sum = _match_vma(jnp.zeros(q.shape[:3], jnp.float32), q)
    q_offset = my_index * seq_len

    def step(carry, _):
        k_cur, v_cur, k_index, acc, row_max, row_sum = carry
        k_offset = k_index * seq_len
        acc, row_max, row_sum = _block_attend(
            q, k_cur, v_cur, acc, row_max, row_sum,
            q_offset, k_offset, causal, scale,
        )
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        k_index = (k_index - 1) % axis_size
        return (k_next, v_next, k_index, acc, row_max, row_sum), None

    carry = (k, v, my_index, acc, row_max, row_sum)
    carry, _ = jax.lax.scan(step, carry, None, length=axis_size)
    _, _, _, acc, row_max, row_sum = carry
    # rows with zero visible keys (never happens for causal with self block)
    denom = jnp.where(row_sum == 0.0, 1.0, row_sum)
    return (acc / denom[:, :, :, None]).astype(q.dtype)


# --------------------------------------------------------------------------
# flash-ring: the pallas kernels inside the ring
# --------------------------------------------------------------------------
#
# The dense blockwise path above materializes [B, H, Lq, Lk] score blocks per
# ring step — O(local_seq²) HBM per pair. The flash-ring path instead runs
# the fused pallas kernels per ring step and merges the per-step normalized
# outputs via their LSEs, so per-shard memory stays O(local_seq·d):
#
#   forward  : out = Σ_j softmax-weighted out_j, combined online with
#              new_lse = logaddexp(lse_run, lse_j)  (exact, order-free)
#   backward : the flash backward per (q-shard, kv-shard) pair only needs the
#              MERGED lse and delta = rowsum(dO·O), so each ring step calls
#              the pallas dq/dkv kernels; dk/dv contributions accumulate in
#              f32 buffers that rotate with the kv blocks and arrive back at
#              the owner after a full revolution (Ring Attention backward,
#              Liu et al. 2023).
#
# Mask mode per step relative to my q shard: the kv block currently held is
# the diagonal (local causal), strictly past (full attention) or strictly
# future (contributes nothing). The mode depends on axis_index, so all three
# branches live in a lax.switch — XLA compiles each kernel once.

def _ring_step_fwd(mode, qb, kb, vb, block_q, block_k, interpret, scale):
    bh, lq, d = qb.shape

    def diag(qb, kb, vb):
        return _flash_fwd_bhsd(qb, kb, vb, True, block_q, block_k, interpret,
                               scale=scale)

    def past(qb, kb, vb):
        return _flash_fwd_bhsd(qb, kb, vb, False, block_q, block_k, interpret,
                               scale=scale)

    def future(qb, kb, vb):
        # must match the pallas branches' varying-axes type exactly, or
        # lax.switch rejects the branch set inside a check_vma region
        return (_match_vma(jnp.zeros((bh, lq, d), qb.dtype), qb),
                _match_vma(jnp.full((bh, 1, lq), NEG_INF, jnp.float32), qb))

    return jax.lax.switch(mode, (diag, past, future), qb, kb, vb)


def _ring_step_bwd(mode, qb, kb, vb, outb, lse, dob, delta, block_q, block_k,
                   interpret, scale):
    def diag(qb, kb, vb, outb, dob, delta):
        return _flash_bwd_bhsd(qb, kb, vb, outb, lse, dob, True,
                               block_q, block_k, interpret, scale=scale,
                               delta=delta)

    def past(qb, kb, vb, outb, dob, delta):
        return _flash_bwd_bhsd(qb, kb, vb, outb, lse, dob, False,
                               block_q, block_k, interpret, scale=scale,
                               delta=delta)

    def future(qb, kb, vb, outb, dob, delta):
        return (jnp.zeros_like(qb), jnp.zeros_like(kb), jnp.zeros_like(vb))

    return jax.lax.switch(mode, (diag, past, future), qb, kb, vb, outb, dob,
                          delta)


def _rotate(arrays, axis_name: str, axis_size: int):
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return [jax.lax.ppermute(a, axis_name, perm) for a in arrays]


def _bhsd(x):
    """[B,S,H,D] → [BH,S,D] via the flash module's shared transform."""
    batch, seq, heads, d = x.shape
    return _to_bhsd(x, batch, seq, heads, d)


def _unbhsd(x, batch, heads):
    bh, seq, d = x.shape
    return _from_bhsd(x, batch, seq, heads, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_ring_local(q, k, v, axis_name, axis_size, causal, block_q, block_k,
                      interpret, scale):
    out, _ = _flash_ring_fwd(q, k, v, axis_name, axis_size, causal, block_q,
                             block_k, interpret, scale)
    return out


def _ring_mode(my_index, step, axis_size, causal):
    """0=diagonal(local causal) 1=full 2=masked-out, per ring step."""
    if not causal:
        return jnp.int32(1)
    k_index = (my_index - step) % axis_size
    return jnp.where(k_index == my_index, 0,
                     jnp.where(k_index < my_index, 1, 2))


def _flash_ring_fwd(q, k, v, axis_name, axis_size, causal, block_q, block_k,
                    interpret, scale):
    batch, seq_local, heads, d = q.shape
    my_index = jax.lax.axis_index(axis_name)
    qb = _bhsd(q)
    out_run = _match_vma(jnp.zeros(qb.shape, jnp.float32), qb)
    lse_run = _match_vma(
        jnp.full((qb.shape[0], 1, seq_local), NEG_INF, jnp.float32), qb)
    k_cur, v_cur = k, v
    for s in range(axis_size):                  # static unroll: sp is small
        mode = _ring_mode(my_index, s, axis_size, causal)
        out_i, lse_i = _ring_step_fwd(mode, qb, _bhsd(k_cur), _bhsd(v_cur),
                                      block_q, block_k, interpret, scale)
        new_lse = jnp.logaddexp(lse_run, lse_i)
        w_run = jnp.exp(lse_run - new_lse).transpose(0, 2, 1)   # [BH, L, 1]
        w_i = jnp.exp(lse_i - new_lse).transpose(0, 2, 1)
        out_run = out_run * w_run + out_i.astype(jnp.float32) * w_i
        lse_run = new_lse
        if s < axis_size - 1:
            k_cur, v_cur = _rotate([k_cur, v_cur], axis_name, axis_size)
    out = _unbhsd(out_run, batch, heads).astype(q.dtype)
    return out, (q, k, v, out, lse_run)


def _flash_ring_bwd(axis_name, axis_size, causal, block_q, block_k, interpret,
                    scale, residuals, grad_out):
    q, k, v, out, lse = residuals
    batch, seq_local, heads, d = q.shape
    my_index = jax.lax.axis_index(axis_name)
    qb, outb, dob = _bhsd(q), _bhsd(out), _bhsd(grad_out)
    # delta = rowsum(dO∘O) depends only on the local q shard: compute it
    # ONCE here instead of per ring step (axis_size× redundant reductions)
    delta = flash_bwd_delta(dob, outb)
    dq_acc = _match_vma(jnp.zeros(qb.shape, jnp.float32), qb)
    # dk/dv accumulators rotate WITH the kv blocks; after axis_size rotations
    # (one per step) they land back on the kv owner
    k_cur, v_cur = k, v
    dk_cur = _match_vma(jnp.zeros(_bhsd(k).shape, jnp.float32), qb)
    dv_cur = _match_vma(jnp.zeros(_bhsd(v).shape, jnp.float32), qb)
    for s in range(axis_size):
        mode = _ring_mode(my_index, s, axis_size, causal)
        dq_i, dk_i, dv_i = _ring_step_bwd(
            mode, qb, _bhsd(k_cur), _bhsd(v_cur), outb, lse, dob, delta,
            block_q, block_k, interpret, scale)
        dq_acc = dq_acc + dq_i.astype(jnp.float32)
        dk_cur = dk_cur + dk_i.astype(jnp.float32)
        dv_cur = dv_cur + dv_i.astype(jnp.float32)
        if s < axis_size - 1:
            k_cur, v_cur, dk_cur, dv_cur = _rotate(
                [k_cur, v_cur, dk_cur, dv_cur], axis_name, axis_size)
        else:
            # only the accumulators must finish the revolution home; the
            # rotated kv blocks would be dead weight on ICI
            dk_cur, dv_cur = _rotate([dk_cur, dv_cur], axis_name, axis_size)
    dq = _unbhsd(dq_acc, batch, heads).astype(q.dtype)
    kv_heads = k.shape[2]               # GQA: dk/dv stay at KV width
    dk = _unbhsd(dk_cur, batch, kv_heads).astype(k.dtype)
    dv = _unbhsd(dv_cur, batch, kv_heads).astype(v.dtype)
    return dq, dk, dv


_flash_ring_local.defvjp(_flash_ring_fwd, _flash_ring_bwd)


def _flash_ring_usable(seq_local: int, block_q: int, block_k: int) -> bool:
    return seq_local % block_q == 0 and seq_local % block_k == 0


def _ring_body_plan(q, k, v, seq_local, heads_shardable=True):
    """Shared flash-vs-dense dispatch for both ring entry points.

    Returns (use_flash, k, v, block_q, block_k) with K/V pre-expanded to
    full head width when the chosen body can't take GQA-narrow K/V
    natively: the dense fallback's einsums assume equal head counts, and
    the flash path needs the KV heads to divide the head-sharding axis
    (``heads_shardable``; vacuously true for per-shard callers whose head
    dim stays automatic)."""
    block_q, block_k = default_blocks(seq_local)
    kv_heads = k.shape[2]
    kv_compatible = (
        v.shape == k.shape and k.shape[:2] == q.shape[:2]
        and k.shape[3] == q.shape[3] and q.shape[2] % kv_heads == 0
    )
    use_flash = _flash_ring_usable(seq_local, block_q, block_k) and kv_compatible
    if kv_heads != q.shape[2] and kv_compatible and (
            not use_flash or not heads_shardable):
        group = q.shape[2] // kv_heads
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
        use_flash = _flash_ring_usable(seq_local, block_q, block_k)
    return use_flash, k, v, block_q, block_k


def ring_attention_local(q, k, v, axis_name: str, axis_size: int,
                         causal: bool = True) -> jax.Array:
    """Per-shard ring attention for callers ALREADY inside a manual region
    over ``axis_name`` — the pipeline's shard_map (manual over {pp, sp})
    calls this per stage so pp and sp compose without nesting shard_maps.

    Arrays are LOCAL shards [B, L/axis_size, H, D]; collectives run over
    the enclosing region's ``axis_name``. Body dispatch is shared with
    ``ring_attention`` (``_ring_body_plan``) with one extra gate: off-TPU
    the flash body would need interpret-mode pallas, which JAX's vma
    tracking does not support inside a partial-manual region ("Primitive
    dynamic_slice requires varying manual axes to match"), so CPU/CI runs
    take the dense blockwise body (same math, same ring collectives); the
    real TPU path runs the pallas flash-ring."""
    scale = q.shape[-1] ** -0.5
    use_flash, k, v, block_q, block_k = _ring_body_plan(q, k, v, q.shape[1])
    if use_flash and jax.default_backend() == "tpu":
        return _flash_ring_local(q, k, v, axis_name, axis_size, causal,
                                 block_q, block_k, False, scale)
    if k.shape[2] != q.shape[2]:
        # the dense body's einsums need full-width K/V (the flash plan
        # above may have kept them GQA-narrow)
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    return _ring_attention_local(q, k, v, axis_name=axis_name, causal=causal,
                                 scale=scale)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    axis_name: str = "sp",
    causal: bool = True,
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
) -> jax.Array:
    """Exact attention with the sequence sharded over ``axis_name``.

    Inputs are [batch, seq, heads, d_head] global arrays; internally each
    sp-shard sees [batch, seq/sp, heads, d_head]. Works under an outer jit
    with a mesh in context, or standalone given ``mesh``.
    """
    scale = q.shape[-1] ** -0.5
    if mesh is None or axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        # no sequence sharding: delegate to the shared dense oracle rather
        # than keeping a second copy of the same math
        from ..ops.flash_attention import reference_attention

        return reference_attention(q, k, v, causal=causal)

    axis_size = mesh.shape[axis_name]
    seq_local = q.shape[1] // axis_size
    spec = P(batch_axes, axis_name, head_axis, None)
    # GQA rides the ring natively when the flash-ring body runs (the inner
    # kernels read KV head h // group via their index maps), which also
    # shrinks the rotating K/V blocks — group× less ICI traffic per step.
    # The KV heads must still divide the head-sharding axis (checked here;
    # this wrapper shards heads manually over head_axis).
    heads_shardable = (
        head_axis is None or head_axis not in mesh.axis_names
        or k.shape[2] % mesh.shape[head_axis] == 0
    )
    use_flash, k, v, block_q, block_k = _ring_body_plan(
        q, k, v, seq_local, heads_shardable=heads_shardable)
    if use_flash:
        interpret = jax.default_backend() != "tpu"

        def body(q, k, v):
            # nondiff args passed positionally (custom_vjp nondiff_argnums);
            # the SAME scale feeds both ring bodies so the flash and dense
            # paths cannot diverge on it
            return _flash_ring_local(q, k, v, axis_name, axis_size, causal,
                                     block_q, block_k,
                                     interpret, scale)
    else:
        # short per-shard sequences: the dense blockwise body (still exact)
        body = functools.partial(
            _ring_attention_local, axis_name=axis_name, causal=causal,
            scale=scale,
        )
    return shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )(q, k, v)
