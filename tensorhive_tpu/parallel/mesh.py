"""Device mesh construction + sharding rules.

The canonical 4-axis mesh for transformer training on TPU pods:

* ``dp``   — pure data parallelism (params replicated) across slices/DCN,
* ``fsdp`` — data parallelism with parameter sharding (ZeRO-3 style) —
  the default scaling axis within a slice,
* ``tp``   — tensor (megatron) parallelism over heads/ffn columns; keep
  within a chip's nearest ICI neighbors,
* ``sp``   — sequence/context parallelism (ring attention over shard_map),
* ``pp``   — pipeline parallelism over layer stages (parallel/pipeline.py);
  point-to-point activation handoff per microbatch, so it tolerates the
  slowest links — outermost, like dp.

Axis order is outermost→innermost = slowest→fastest collectives: pp/dp ride
DCN, fsdp/tp/sp ride ICI (the "How to Scale Your Model" recipe: pick a mesh,
annotate shardings, let XLA insert the collectives).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("pp", "dp", "fsdp", "tp", "sp")


def make_mesh(
    dp: int = 1,
    fsdp: int = -1,
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all). One axis may be -1 to
    absorb the remaining device count (like a reshape)."""
    devices = list(devices if devices is not None else jax.devices())
    sizes = {"pp": pp, "dp": dp, "fsdp": fsdp, "tp": tp, "sp": sp}
    unknown = [axis for axis, size in sizes.items() if size == -1]
    known = math.prod(size for size in sizes.values() if size != -1)
    if len(unknown) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if unknown:
        if len(devices) % known:
            raise ValueError(
                f"cannot infer {unknown[0]}: {len(devices)} devices not divisible "
                f"by {known}"
            )
        sizes[unknown[0]] = len(devices) // known
    if math.prod(sizes.values()) != len(devices):
        raise ValueError(
            f"mesh {sizes} needs {math.prod(sizes.values())} devices, "
            f"have {len(devices)}"
        )
    shape = tuple(sizes[a] for a in AXES)
    return Mesh(np.asarray(devices).reshape(shape), AXES)


def best_mesh_shape(n_devices: int, seq_parallel: bool = False,
                    kv_heads: Optional[int] = None) -> Dict[str, int]:
    """Heuristic default mesh for n devices: fsdp-dominant (the within-slice
    scaling axis), with a modest tp factor once the slice is large, and an
    sp factor when long-context is requested. Factors are only taken when
    they divide n, so the product always equals n_devices.

    ``kv_heads`` caps the auto-chosen tp at the model's K/V head count:
    tp > kv_heads buys nothing for attention (the K/V shards would be
    empty) and forces the GQA replication fallback (:func:`serving_rules`),
    so a GQA model must never be handed a head-starved mesh by default —
    the cap halves tp until it divides ``kv_heads``."""
    sizes = {"dp": 1, "fsdp": n_devices, "tp": 1, "sp": 1}
    if seq_parallel:
        sp = 4 if n_devices >= 16 and n_devices % 4 == 0 else \
            2 if n_devices % 2 == 0 else 1
        sizes["sp"] = sp
        sizes["fsdp"] = n_devices // sp
    else:
        tp = 4 if n_devices >= 16 and n_devices % 4 == 0 else \
            2 if n_devices >= 4 and n_devices % 2 == 0 else 1
        if kv_heads is not None:
            while tp > 1 and (tp > kv_heads or kv_heads % tp):
                tp //= 2
        sizes["tp"] = tp
        sizes["fsdp"] = n_devices // tp
    return sizes


@dataclasses.dataclass(frozen=True)
class MeshRules:
    """Logical-axis → mesh-axis mapping (flax-style rules, explicit here).

    Parameters carry logical axis names; these rules translate them into
    PartitionSpecs. ``embed`` (the d_model axis) shards over fsdp so ZeRO-3
    gathers ride ICI; ``heads``/``ffn``/``vocab`` shard over tp (megatron
    splits); sequence activations shard over sp.
    """

    embed: Optional[str] = "fsdp"
    heads: Optional[str] = "tp"
    #: K/V projection head axis (wk/wv) — separate from ``heads`` so GQA
    #: serving can replicate K/V while still sharding the Q-side matmuls
    #: (:func:`serving_rules`); training defaults keep both on tp, so the
    #: split changes nothing for existing meshes
    kv_heads: Optional[str] = "tp"
    ffn: Optional[str] = "tp"
    vocab: Optional[str] = "tp"
    batch: Tuple[str, ...] = ("dp", "fsdp")
    seq: Optional[str] = "sp"

    def spec(self, *logical: Optional[str]) -> P:
        return P(*(getattr(self, name) if name else None for name in logical))


DEFAULT_RULES = MeshRules()

#: logical axes per parameter leaf path-suffix of the transformer LM
#: (models/transformer.py param tree); order matches the weight's shape
_PARAM_LOGICAL: Dict[str, Tuple[Optional[str], ...]] = {
    "tok_embed": ("vocab", "embed"),
    "pos_embed": (None, "embed"),
    "wq": ("embed", "heads"),
    "wk": ("embed", "kv_heads"),
    "wv": ("embed", "kv_heads"),
    "wo": ("heads", "embed"),
    "w_in": ("embed", "ffn"),
    "w_gate": ("embed", "ffn"),
    "w_out": ("ffn", "embed"),
    "w_lm_head": ("embed", "vocab"),
    "scale": ("embed",),
    "bias": ("embed",),
}


def param_sharding(mesh: Mesh, path: str, ndim: int,
                   rules: MeshRules = DEFAULT_RULES) -> NamedSharding:
    """Sharding for one parameter identified by its tree path."""
    leaf = path.rsplit("/", 1)[-1]
    logical = _PARAM_LOGICAL.get(leaf)
    if logical is None or len(logical) != ndim:
        return NamedSharding(mesh, P())  # replicate unknowns
    return NamedSharding(mesh, rules.spec(*logical))


def batch_sharding(mesh: Mesh, rules: MeshRules = DEFAULT_RULES) -> NamedSharding:
    """[batch, seq+1] token arrays: batch over dp+fsdp. The sequence dim
    stays unsharded here — raw token batches are tiny int32 and carry the
    odd +1 target shift; sp-sharding happens on activations inside the model
    (ring attention's shard_map), where lengths are clean."""
    return NamedSharding(mesh, P(rules.batch, None))


def tree_shardings(mesh: Mesh, params, rules: MeshRules = DEFAULT_RULES):
    """Map a param pytree to a matching tree of NamedShardings."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def path_str(key_path) -> str:
        return "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in key_path
        )

    shardings = {path_str(kp): param_sharding(mesh, path_str(kp), leaf.ndim, rules)
                 for kp, leaf in flat}
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(
        treedef, [shardings[path_str(kp)] for kp, _ in flat]
    )


# -- serving mesh (docs/SERVING.md "Multi-chip serving") ----------------------
#
# Inference shards differently from training: there is no gradient, so fsdp
# buys nothing — the serving engine uses only dp (replicate params, shard the
# slot/page pool so capacity scales with chips) and tp (megatron head/ffn/
# vocab splits so per-token latency scales). The helpers below build that
# 2-axis layout out of the SAME 5-axis mesh machinery the training dryruns
# certify (size-1 fsdp/sp/pp axes), so one MeshRules vocabulary covers both.

def serving_mesh(dp: int = 1, tp: int = 1,
                 devices: Optional[Sequence] = None) -> Mesh:
    """The serving engine's mesh: ``dp x tp`` over the first ``dp*tp``
    devices (fsdp/sp/pp pinned to 1). Raises when the product exceeds the
    available device count — a serving config must never silently fall back
    to fewer chips than the operator budgeted HBM for."""
    if dp < 1 or tp < 1:
        raise ValueError(f"mesh axes must be >= 1, got dp={dp} tp={tp}")
    devices = list(devices if devices is not None else jax.devices())
    if dp * tp > len(devices):
        raise ValueError(
            f"serving mesh dp={dp} x tp={tp} needs {dp * tp} devices, "
            f"have {len(devices)}")
    return make_mesh(dp=dp, fsdp=1, tp=tp, devices=devices[:dp * tp])


def serving_rules(config, tp: int) -> MeshRules:
    """Sharding rules for a serving engine at tensor-parallel degree ``tp``.

    Every tp-sharded axis is checked for divisibility and demoted to
    replication when it cannot split evenly — most importantly the **GQA
    guard**: when ``tp > kv_heads`` (or tp does not divide kv_heads), the
    K/V projections and the KV cache replicate across tp and only the
    Q-side matmuls (wq/wo, and ffn/vocab when they divide) stay sharded.
    Crashing instead would make every GQA preset unservable at high tp;
    replicated K/V merely costs cache HBM (kv_heads/tp of it), never
    correctness — documented in docs/SERVING.md "Multi-chip serving".
    ``embed`` maps to the size-1 fsdp axis (a no-op kept for rule symmetry
    with training)."""
    def axis_or_none(size: int) -> Optional[str]:
        return "tp" if tp > 1 and size % tp == 0 else None

    return MeshRules(
        heads=axis_or_none(config.n_heads),
        kv_heads=axis_or_none(config.kv_heads),
        ffn=axis_or_none(config.d_ff),
        vocab=axis_or_none(config.vocab_size),
    )


def normalized_spec(*entries: Optional[str]) -> P:
    """PartitionSpec with trailing Nones trimmed. jax normalizes specs this
    way on executable OUTPUTS, so a donated buffer device_put with the
    untrimmed spelling would compare unequal to its own round-trip through
    the jit and recompile once per executable — exactly the class of leak
    the serving zero-recompile tests exist to catch."""
    trimmed = list(entries)
    while trimmed and trimmed[-1] is None:
        trimmed.pop()
    return P(*trimmed)


def serving_cache_spec(rules: MeshRules) -> P:
    """PartitionSpec for the serving KV cache, either layout:
    ``[layers, slots | pages, positions, kv_heads, d_head]`` — the pool
    axis (slots or physical pages) shards over dp so capacity scales with
    chips, the kv_heads axis follows the same GQA-guarded rule as wk/wv,
    and layers/positions/d_head stay unsharded."""
    return normalized_spec(None, "dp", None, rules.kv_heads, None)


def serving_scale_spec(rules: MeshRules) -> P:
    """PartitionSpec for the int8 KV cache's per-page scale side-arrays
    (``[layers, pages, kv_heads]``, ``kv_quant = on`` —
    docs/SERVING.md "Quantized KV pages"): scales shard exactly like the
    pages they describe — pages over dp, kv_heads GQA-guarded over tp —
    so a shard always holds the scales for the pages it holds."""
    return normalized_spec(None, "dp", rules.kv_heads)
