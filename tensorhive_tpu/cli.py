"""Command-line interface.

Reference: tensorhive/cli.py (268 LoC) — click group where the bare command
boots everything (DB ensure → TensorHiveManager → webapp Process → API
blocking, cli.py:111-148), plus ``test`` (SSH connectivity :157-166),
``init`` (interactive config+DB+first account :170-214), ``key`` (print
pubkey :218-243), ``create user`` (:247-257).
"""
from __future__ import annotations

import logging
import secrets
import sys

import click

log = logging.getLogger(__name__)


def setup_logging(verbose: bool = False) -> None:
    logging.basicConfig(
        level=logging.DEBUG if verbose else logging.INFO,
        # span_id is injected by SpanLogFilter: log lines emitted inside a
        # tick/request span carry its id, joinable against /api/admin/traces
        format="%(asctime)s %(levelname)-7s %(name)s [%(span_id)s]: %(message)s",
        datefmt="%H:%M:%S",
    )
    from .observability import SpanLogFilter

    for handler in logging.getLogger().handlers:
        handler.addFilter(SpanLogFilter())
    logging.getLogger("werkzeug").setLevel(logging.WARNING)


@click.group(invoke_without_command=True)
@click.option("--verbose", "-v", is_flag=True, help="debug logging")
@click.pass_context
def main(ctx: click.Context, verbose: bool) -> None:
    """tpuhive — TPU cluster reservations, monitoring and job execution."""
    setup_logging(verbose)
    if ctx.invoked_subcommand is None:
        run_everything()


def run_everything() -> None:
    """The daemon path (reference cli.main:111-148): DB, manager (services),
    app server process, API server blocking on the main thread."""
    from .api.server import APIServer
    from .app.server import AppServer
    from .config import get_config
    from .core.managers.manager import TpuHiveManager, set_manager
    from .db.engine import get_engine
    from .db.migrations import ensure_schema

    config = get_config()
    if not config.api.secret_key:
        click.echo("api.secret_key is not configured — run `tpuhive init` first",
                   err=True)
        sys.exit(1)
    ensure_schema(get_engine())

    manager = TpuHiveManager(config=config)
    set_manager(manager)
    if config.hosts:
        statuses = manager.test_connectivity()
        for hostname, ok in statuses.items():
            click.echo(f"  {hostname}: {'ok' if ok else 'UNREACHABLE'}")
    else:
        click.echo("no hosts configured yet — edit hosts.toml "
                   f"in {config.config_dir}")
    manager.configure_services_from_config()
    manager.init()

    app_server = AppServer(config)
    app_server.start()

    api_server = APIServer(config)
    click.echo(f"API:    http://{config.api.url_hostname}:{config.api.url_port}"
               f"/{config.api.url_prefix}/ui/")
    click.echo(f"Web UI: http://{config.app_server.host}:{config.app_server.port}/")
    try:
        api_server.run_forever()
    finally:
        app_server.stop()
        manager.shutdown()


@main.command()
def test() -> None:
    """Probe connectivity to every managed host (reference cli.py:157-166)."""
    from .config import get_config
    from .core.managers.manager import TpuHiveManager

    config = get_config()
    if not config.hosts:
        click.echo("no hosts configured")
        return
    statuses = TpuHiveManager(config=config, services=[]).test_connectivity()
    failed = [h for h, ok in statuses.items() if not ok]
    for hostname, ok in statuses.items():
        click.echo(f"{hostname}: {'ok' if ok else 'FAILED'}")
    sys.exit(1 if failed else 0)


@main.command()
@click.option("--username", prompt=True)
@click.option("--email", prompt=True)
@click.option("--password", prompt=True, hide_input=True, confirmation_prompt=True)
def init(username: str, email: str, password: str) -> None:
    """Write default configs, create the database and the first admin
    account (reference cli.py:170-214 + AccountCreator)."""
    from .config import get_config, write_default_configs
    from .core.account_creator import AccountCreator, ensure_default_group_bootstrap
    from .db.engine import get_engine
    from .db.migrations import ensure_schema

    config = get_config()
    write_default_configs(config.config_dir, secret_key=secrets.token_hex(32))
    click.echo(f"configs in {config.config_dir}")
    ensure_schema(get_engine())

    # bootstrap: default group + global everything-allowed restriction
    # (reference AccountCreator._check_restrictions:113-139)
    ensure_default_group_bootstrap(click.echo)
    AccountCreator.create_account(username, email, password, admin=True)
    click.echo(f"admin account {username!r} created")


@main.command()
@click.option("--all", "fleet", is_flag=True,
              help="probe every configured host over its transport")
def chips(fleet: bool) -> None:
    """Live chip telemetry table — the ``tpu-info``/``nvidia-smi`` analog
    (reference operators shell out to nvidia-smi; here the native probe
    reports chips, holders and utilization in one round-trip)."""
    from .config import HostConfig, get_config
    from .core.monitors.probe import parse_probe_output, probe_command
    from .core.transport.base import TransportManager
    from .core.transport.local import LocalTransport
    from .utils.exceptions import TpuHiveError

    command = probe_command()
    if fleet:
        config = get_config()
        if not config.hosts:
            click.echo("no hosts configured")
            return
        results = TransportManager(config).run_on_all(command)
        outputs = {host: (r.stdout if r.ok else None)
                   for host, r in results.items()}
    else:
        result = LocalTransport(HostConfig(name="localhost", backend="local")).run(
            command, timeout=30)
        outputs = {"localhost": result.stdout if result.ok else None}

    header = (f"{'host':<14} {'chip':<5} {'duty%':>6} {'hbm':>14} "
              f"{'holders':<24} sysfs")
    click.echo(header)
    click.echo("-" * len(header))
    exit_code = 0
    for host in sorted(outputs):
        text = outputs[host]
        if text is None:
            click.echo(f"{host:<14} UNREACHABLE")
            exit_code = 1
            continue
        try:
            sample = parse_probe_output(text)
        except TpuHiveError as exc:
            click.echo(f"{host:<14} probe error: {exc}")
            exit_code = 1
            continue
        if not sample.chips:
            click.echo(f"{host:<14} no accelerator devices")
            continue
        for chip in sample.chips:
            duty = ("-" if chip.duty_cycle_pct is None
                    else f"{chip.duty_cycle_pct:.1f}")
            if chip.hbm_used_bytes is not None and chip.hbm_total_bytes:
                hbm = (f"{chip.hbm_used_bytes // 2**20}/"
                       f"{chip.hbm_total_bytes // 2**20} MiB")
            else:
                hbm = "-"
            holders = ",".join(
                f"{pid}({sample.procs.get(pid, {}).get('user', '?')})"
                for pid in chip.pids) or "-"
            click.echo(f"{host:<14} {chip.index:<5} {duty:>6} {hbm:>14} "
                       f"{holders:<24} {sample.sysfs_status}")
    sys.exit(exit_code)


@main.command()
def key() -> None:
    """Print the manager public key users must add to authorized_keys
    (reference cli.py:218-243)."""
    from .config import get_config
    from .core.transport.ssh import generate_keypair
    from .utils.exceptions import TpuHiveError

    try:
        click.echo(generate_keypair(get_config().ssh_key_path))
    except TpuHiveError as exc:
        click.echo(f"error: {exc}", err=True)
        sys.exit(1)


@main.group()
def create() -> None:
    """Create entities."""


@create.command("user")
@click.option("--username", default=None, help="omit to be prompted")
@click.option("--email", default=None)
@click.option("--password", default=None)
@click.option("--admin", is_flag=True)
@click.option("--multiple", is_flag=True,
              help="loop, creating several accounts in one sitting")
def create_user(username, email, password, admin: bool, multiple: bool) -> None:
    """Create account(s) (reference cli.py:247-257 + AccountCreator.run_prompt).

    With all of --username/--email/--password given, creates one account
    non-interactively; otherwise enters the interactive prompt loop, which
    re-asks on invalid fields and (with --multiple) keeps creating accounts
    until you stop."""
    from .core.account_creator import AccountCreator, ensure_default_group_bootstrap
    from .db.engine import get_engine
    from .db.migrations import ensure_schema
    from .utils.exceptions import ValidationError

    ensure_schema(get_engine())
    if username and email and password and not multiple:
        ensure_default_group_bootstrap(click.echo)
        try:
            AccountCreator.create_account(username, email, password, admin)
        except ValidationError as exc:
            click.echo(f"error: {exc}", err=True)
            sys.exit(1)
        click.echo(f"user {username!r} created{' (admin)' if admin else ''}")
        return
    creator = AccountCreator(prompt=click.prompt, confirm=click.confirm, echo=click.echo)
    created = creator.run_prompt(multiple=multiple, username=username, email=email,
                                 password=password, admin=True if admin else None)
    click.echo(f"created {len(created)} account(s)")
    if not created:
        sys.exit(1)


if __name__ == "__main__":
    main()
