"""Declarative ORM-lite over sqlite3.

Provides the capabilities the reference gets from SQLAlchemy + its CRUDModel
mixin (tensorhive/models/CRUDModel.py:11-94): declarative column definitions,
``save``/``destroy``/``get``/``all``/``filter_by`` CRUD, a
``check_assertions`` validation hook invoked before every save (CRUDModel.py
save :21), and camelCase ``as_dict`` serialization driven by per-model
``__public__`` attribute lists (CRUDModel.py:78-94). Datetimes round-trip as
ISO-8601 naive-UTC TEXT; bools as INTEGER.
"""
from __future__ import annotations

from datetime import datetime
from typing import Any, Dict, List, Optional, Sequence, Type, TypeVar

from ..utils.exceptions import NotFoundError, ValidationError
from ..utils.timeutils import isoformat, parse_datetime, to_utc_naive
from .engine import Engine, get_engine

T = TypeVar("T", bound="Model")

_SQL_TYPES = {int: "INTEGER", str: "TEXT", float: "REAL", bool: "INTEGER", datetime: "TEXT", bytes: "BLOB"}


class Column:
    """Declarative column descriptor."""

    def __init__(
        self,
        py_type: type,
        *,
        primary_key: bool = False,
        nullable: bool = True,
        unique: bool = False,
        default: Any = None,
        foreign_key: Optional[str] = None,   # "table(column)" target
        on_delete: str = "CASCADE",
        index: bool = False,
    ) -> None:
        if py_type not in _SQL_TYPES:
            raise TypeError(f"unsupported column type {py_type}")
        self.py_type = py_type
        self.primary_key = primary_key
        self.nullable = nullable and not primary_key
        self.unique = unique
        self.default = default
        self.foreign_key = foreign_key
        self.on_delete = on_delete
        self.index = index
        self.name: str = ""  # set by metaclass

    # -- python <-> sqlite value conversion --------------------------------
    def to_sql(self, value: Any) -> Any:
        if value is None:
            return None
        if self.py_type is datetime:
            if isinstance(value, datetime):
                return to_utc_naive(value).isoformat()
            return str(value)
        if self.py_type is bool:
            return int(bool(value))
        return value

    def from_sql(self, value: Any) -> Any:
        if value is None:
            return None
        if self.py_type is datetime:
            return parse_datetime(value)
        if self.py_type is bool:
            return bool(value)
        return value

    def ddl(self) -> str:
        parts = [self.name, _SQL_TYPES[self.py_type]]
        if self.primary_key:
            parts.append("PRIMARY KEY")
            if self.py_type is int:
                parts.append("AUTOINCREMENT")
        if not self.nullable and not self.primary_key:
            parts.append("NOT NULL")
        if self.unique:
            parts.append("UNIQUE")
        return " ".join(parts)


class ModelMeta(type):
    registry: List[Type["Model"]] = []

    def __new__(mcls, name, bases, namespace):
        cls = super().__new__(mcls, name, bases, namespace)
        columns: Dict[str, Column] = {}
        for base in bases:
            columns.update(getattr(base, "__columns__", {}))
        for key, value in namespace.items():
            if isinstance(value, Column):
                value.name = key
                columns[key] = value
        cls.__columns__ = columns
        if namespace.get("__tablename__"):
            mcls.registry.append(cls)
        return cls


class Model(metaclass=ModelMeta):
    """Base entity. Subclasses set ``__tablename__`` and Column attributes."""

    __tablename__: str = ""
    __columns__: Dict[str, Column] = {}
    # attribute names exposed by as_dict (camelCased); None = all columns
    __public__: Optional[Sequence[str]] = None

    def __init__(self, **kwargs: Any) -> None:
        for name, col in self.__columns__.items():
            setattr(self, name, kwargs.pop(name, col.default))
        if kwargs:
            raise TypeError(f"unknown fields for {type(self).__name__}: {sorted(kwargs)}")

    # -- schema ------------------------------------------------------------
    @classmethod
    def pk_column(cls) -> Column:
        for col in cls.__columns__.values():
            if col.primary_key:
                return col
        raise TypeError(f"{cls.__name__} has no primary key")

    @classmethod
    def create_table_sql(cls) -> str:
        defs = [col.ddl() for col in cls.__columns__.values()]
        for col in cls.__columns__.values():
            if col.foreign_key:
                defs.append(
                    f"FOREIGN KEY({col.name}) REFERENCES {col.foreign_key} "
                    f"ON DELETE {col.on_delete}"
                )
        uniques = getattr(cls, "__table_constraints__", ())
        defs.extend(uniques)
        return f"CREATE TABLE IF NOT EXISTS {cls.__tablename__} ({', '.join(defs)})"

    @classmethod
    def index_sql(cls) -> List[str]:
        return [
            f"CREATE INDEX IF NOT EXISTS idx_{cls.__tablename__}_{col.name} "
            f"ON {cls.__tablename__}({col.name})"
            for col in cls.__columns__.values()
            if col.index
        ]

    # -- hydration ---------------------------------------------------------
    @classmethod
    def _from_row(cls: Type[T], row) -> T:
        obj = cls.__new__(cls)
        for name, col in cls.__columns__.items():
            obj.__dict__[name] = col.from_sql(row[name])
        return obj

    # -- validation hook ---------------------------------------------------
    def check_assertions(self) -> None:
        """Override to validate invariants; raise ValidationError on failure
        (reference: CRUDModel save-time assertion hook, CRUDModel.py:21)."""

    # -- CRUD --------------------------------------------------------------
    def save(self: T) -> T:
        # always the process-wide engine: check_assertions runs arbitrary
        # model queries which resolve via get_engine(), so accepting a
        # different engine here would validate against the wrong database
        engine = get_engine()
        # run validation and the write under one engine lock so
        # check-then-insert invariants (e.g. reservation overlap,
        # Reservation.would_interfere) are atomic across threads
        with engine.transaction():
            self.check_assertions()
            return self._write(engine)

    def _write(self: T, engine: Engine) -> T:
        pk = self.pk_column()
        cols = self.__columns__
        pk_value = getattr(self, pk.name)
        if pk_value is None:
            names = [c.name for c in cols.values() if c.name != pk.name]
            values = [cols[n].to_sql(getattr(self, n)) for n in names]
            sql = (
                f"INSERT INTO {self.__tablename__} ({', '.join(names)}) "
                f"VALUES ({', '.join('?' * len(names))})"
            )
            cursor = engine.execute(sql, values)
            setattr(self, pk.name, cursor.lastrowid)
        else:
            names = [c.name for c in cols.values() if c.name != pk.name]
            assignments = ", ".join(f"{n} = ?" for n in names)
            values = [cols[n].to_sql(getattr(self, n)) for n in names]
            exists = engine.scalar(
                f"SELECT COUNT(*) FROM {self.__tablename__} WHERE {pk.name} = ?",
                [pk.to_sql(pk_value)],
            )
            if exists:
                engine.execute(
                    f"UPDATE {self.__tablename__} SET {assignments} WHERE {pk.name} = ?",
                    values + [pk.to_sql(pk_value)],
                )
            else:
                all_names = [pk.name] + names
                engine.execute(
                    f"INSERT INTO {self.__tablename__} ({', '.join(all_names)}) "
                    f"VALUES ({', '.join('?' * len(all_names))})",
                    [pk.to_sql(pk_value)] + values,
                )
        return self

    def destroy(self) -> None:
        engine = get_engine()
        pk = self.pk_column()
        engine.execute(
            f"DELETE FROM {self.__tablename__} WHERE {pk.name} = ?",
            [pk.to_sql(getattr(self, pk.name))],
        )

    @classmethod
    def get(cls: Type[T], pk_value: Any, engine: Optional[Engine] = None) -> T:
        engine = engine or get_engine()
        pk = cls.pk_column()
        rows = engine.query(
            f"SELECT * FROM {cls.__tablename__} WHERE {pk.name} = ?",
            [pk.to_sql(pk_value)],
        )
        if not rows:
            raise NotFoundError(f"{cls.__name__} id={pk_value!r} not found")
        return cls._from_row(rows[0])

    @classmethod
    def get_or_none(cls: Type[T], pk_value: Any, engine: Optional[Engine] = None) -> Optional[T]:
        try:
            return cls.get(pk_value, engine)
        except NotFoundError:
            return None

    @classmethod
    def all(cls: Type[T], engine: Optional[Engine] = None) -> List[T]:
        engine = engine or get_engine()
        return [cls._from_row(r) for r in engine.query(f"SELECT * FROM {cls.__tablename__}")]

    @classmethod
    def _eq_clause(cls, eq: Dict[str, Any]):
        clauses, params = [], []
        for key, value in eq.items():
            col = cls.__columns__[key]
            if value is None:
                clauses.append(f"{key} IS NULL")
            else:
                clauses.append(f"{key} = ?")
                params.append(col.to_sql(value))
        return " AND ".join(clauses), params

    @classmethod
    def filter_by(cls: Type[T], engine: Optional[Engine] = None, **eq: Any) -> List[T]:
        engine = engine or get_engine()
        if not eq:
            return cls.all(engine)
        clause, params = cls._eq_clause(eq)
        rows = engine.query(f"SELECT * FROM {cls.__tablename__} WHERE {clause}", params)
        return [cls._from_row(r) for r in rows]

    @classmethod
    def first_by(cls: Type[T], engine: Optional[Engine] = None, **eq: Any) -> Optional[T]:
        results = cls.filter_by(engine, **eq)
        return results[0] if results else None

    @classmethod
    def where(cls: Type[T], sql: str, params: Sequence[Any] = (), engine: Optional[Engine] = None) -> List[T]:
        """Raw-WHERE escape hatch for range/overlap queries."""
        engine = engine or get_engine()
        rows = engine.query(f"SELECT * FROM {cls.__tablename__} WHERE {sql}", params)
        return [cls._from_row(r) for r in rows]

    @classmethod
    def get_many(cls: Type[T], pk_values: Sequence[Any], engine: Optional[Engine] = None) -> List[T]:
        """Batched ``get`` preserving input order — one ``IN ()`` query
        instead of N point lookups (link-table traversal helper)."""
        pk_values = list(pk_values)
        if not pk_values:
            return []
        pk = cls.pk_column()
        unique = list(dict.fromkeys(pk_values))
        placeholders = ", ".join("?" * len(unique))
        rows = cls.where(
            f"{pk.name} IN ({placeholders})",
            [pk.to_sql(v) for v in unique],
            engine=engine,
        )
        by_pk = {getattr(obj, pk.name): obj for obj in rows}
        missing = [v for v in unique if v not in by_pk]
        if missing:
            raise NotFoundError(f"{cls.__name__} ids not found: {missing}")
        return [by_pk[v] for v in pk_values]

    @classmethod
    def atomically(cls):
        """Engine-lock context for caller-level check-then-write sequences
        (e.g. link-table 'insert if absent' helpers)."""
        return get_engine().transaction()

    @classmethod
    def count(cls, engine: Optional[Engine] = None, **eq: Any) -> int:
        engine = engine or get_engine()
        if not eq:
            return int(engine.scalar(f"SELECT COUNT(*) FROM {cls.__tablename__}"))
        clause, params = cls._eq_clause(eq)
        return int(
            engine.scalar(f"SELECT COUNT(*) FROM {cls.__tablename__} WHERE {clause}", params)
        )

    # -- serialization -----------------------------------------------------
    def as_dict(self, include_private: bool = False) -> Dict[str, Any]:
        """camelCase dict of public attributes (reference CRUDModel.py:78-94).

        Attribute names may be column names or zero-arg
        properties/methods declared in ``__public__``.
        """
        names = list(self.__public__) if self.__public__ is not None else list(self.__columns__)
        if include_private:
            names += list(getattr(self, "__private__", ()))
        out: Dict[str, Any] = {}
        for name in names:
            value = getattr(self, name)
            if callable(value):
                value = value()
            if isinstance(value, datetime):
                value = isoformat(value)
            out[_camel(name)] = value
        return out

    def __repr__(self) -> str:  # pragma: no cover
        pk = self.pk_column().name
        return f"<{type(self).__name__} {pk}={getattr(self, pk)!r}>"


def _camel(name: str) -> str:
    head, *rest = name.lstrip("_").split("_")
    return head + "".join(part.title() for part in rest)


def create_all(engine: Engine) -> None:
    for model in ModelMeta.registry:
        engine.execute(model.create_table_sql())
        for sql in model.index_sql():
            engine.execute(sql)
