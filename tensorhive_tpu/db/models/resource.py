"""Physical accelerator chips (reference: tensorhive/models/Resource.py:8-61).

A Resource row is one TPU chip, keyed by a stable chip UID
(``<hostname>:tpu:<index>`` as emitted by the telemetry layer — the analog of
the reference's 40-char GPU UUID). TPU-specific additions: slice metadata so
the scheduler can reason about whole-slice reservations (SURVEY.md §7 risk
"chip vs slice granularity": a v5e-16 slice = 4 VMs x 4 chips; the reference
only ever matched single UUIDs).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ...utils.exceptions import ValidationError
from ..orm import Column, Model


#: physical chip-grid shapes of the Cloud TPU accelerator types the host
#: inventory knows (config.py HostConfig.topology documents the format);
#: used to default Resource.topology/num_chips when config omits them
ACCELERATOR_TOPOLOGIES = {
    "v5litepod-1": "1x1",
    "v5litepod-4": "2x2",
    "v5litepod-8": "2x4",
    "v5litepod-16": "4x4",
    "v5litepod-32": "4x8",
    "v5litepod-64": "8x8",
    "v5litepod-128": "8x16",
    "v5litepod-256": "16x16",
    "v4-8": "2x2x1",
    "v5p-8": "2x2x1",
    "v5p-16": "2x2x2",
    "v5p-32": "2x2x4",
    "v5p-64": "2x4x4",
    "v5p-128": "4x4x4",
}


def topology_chip_count(topology: str) -> int:
    """Chips in a topology string ("4x4" → 16, "2x2x4" → 16); 0 if unknown
    or malformed."""
    try:
        dims = [int(part) for part in topology.split("x")]
    except ValueError:
        return 0
    if not dims or any(dim < 1 for dim in dims):
        return 0
    count = 1
    for dim in dims:
        count *= dim
    return count


class Resource(Model):
    __tablename__ = "resources"
    __public__ = ("id", "uid", "name", "hostname", "accelerator_type",
                  "slice_name", "chip_index", "topology", "num_chips")

    id = Column(int, primary_key=True)
    uid = Column(str, nullable=False, unique=True)
    name = Column(str)            # display name, e.g. "TPU v5e chip 0"
    hostname = Column(str, index=True)
    accelerator_type = Column(str, default="")   # "v5litepod-16", "" for CPU hosts
    slice_name = Column(str, default="", index=True)
    chip_index = Column(int, default=0)
    #: chip-grid shape of the slice this chip belongs to ("4x4"; schema v3 —
    #: the scheduler's whole-slice reasoning needs the grid, not just a count)
    topology = Column(str, default="")
    #: total chips in the slice (denormalized from topology for SQL-side
    #: eligibility filters; schema v3 backfills it)
    num_chips = Column(int, default=0)

    MAX_UID_LEN = 64

    def check_assertions(self) -> None:
        if not self.uid or len(self.uid) > self.MAX_UID_LEN:
            raise ValidationError(
                f"resource uid must be 1..{self.MAX_UID_LEN} chars, got {self.uid!r}"
            )

    # -- lookups (reference Resource.py:56-61) -----------------------------
    @classmethod
    def get_by_uid(cls, uid: str) -> Optional["Resource"]:
        return cls.first_by(uid=uid)

    @classmethod
    def get_by_name(cls, name: str) -> List["Resource"]:
        return cls.filter_by(name=name)

    @classmethod
    def get_by_hostname(cls, hostname: str) -> List["Resource"]:
        return cls.filter_by(hostname=hostname)

    @classmethod
    def get_by_slice(cls, slice_name: str) -> List["Resource"]:
        members = cls.filter_by(slice_name=slice_name)
        members.sort(key=lambda r: (r.hostname, r.chip_index))
        return members

    # -- restrictions (reference Resource.py:29-41, incl. global) ----------
    def get_restrictions(self, include_global: bool = True):
        from .restriction import Restriction

        restrictions = Restriction.for_resource(self.id)
        if include_global:
            seen = {r.id for r in restrictions}
            restrictions += [
                r for r in Restriction.get_global_restrictions() if r.id not in seen
            ]
        return restrictions

    def as_dict(self, include_private: bool = False) -> Dict[str, Any]:
        return super().as_dict(include_private)
