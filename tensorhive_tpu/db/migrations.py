"""Sequential schema migrations via ``PRAGMA user_version``.

Reference: tensorhive/database.py:72-87 creates the schema then
Alembic-stamps/upgrades on boot (18 revisions under tensorhive/migrations/).
Here each migration is a ``(version, fn)`` pair applied in order; a fresh DB
gets ``create_all`` and is stamped at the latest version directly.
"""
from __future__ import annotations

import logging
from typing import Callable, List, Tuple

from .engine import Engine
from .orm import create_all

log = logging.getLogger(__name__)

# append (version, fn) pairs as the schema evolves; fn(engine) must be
# idempotent enough to re-run after a crash mid-upgrade.
MIGRATIONS: List[Tuple[int, Callable[[Engine], None]]] = []

SCHEMA_VERSION = 1


def ensure_schema(engine: Engine) -> None:
    from . import models  # noqa: F401  (register all tables)

    current = engine.user_version
    if current == 0:
        create_all(engine)
        engine.user_version = SCHEMA_VERSION
        log.info("database schema created at version %d", SCHEMA_VERSION)
        return
    for version, migrate in MIGRATIONS:
        if version > current:
            log.info("applying migration %d", version)
            migrate(engine)
            engine.user_version = version
    # create any tables added since the stamped version (additive changes)
    create_all(engine)
    if engine.user_version < SCHEMA_VERSION:
        engine.user_version = SCHEMA_VERSION
