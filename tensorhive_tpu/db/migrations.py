"""Sequential schema migrations via ``PRAGMA user_version``.

Reference: tensorhive/database.py:72-87 creates the schema then
Alembic-stamps/upgrades on boot (18 revisions under tensorhive/migrations/).
Here each migration is a ``(version, fn)`` pair applied in order; a fresh DB
gets ``create_all`` and is stamped at the latest version directly.
"""
from __future__ import annotations

import logging
from typing import Callable, List, Tuple

from .engine import Engine
from .orm import create_all

log = logging.getLogger(__name__)


def _column_names(engine: Engine, table: str) -> List[str]:
    return [row[1] for row in engine.execute(f"PRAGMA table_info({table})")]


def _add_column(engine: Engine, table: str, column: str, ddl_type: str) -> None:
    """Idempotent ADD COLUMN: safe to re-run after a crash mid-upgrade."""
    if column not in _column_names(engine, table):
        engine.execute(f"ALTER TABLE {table} ADD COLUMN {column} {ddl_type}")


def _migration_2_user_last_login(engine: Engine) -> None:
    """v1 → v2: ``users.last_login_at`` (ISO-8601 TEXT, set by the login
    controller; shown in the users admin view)."""
    _add_column(engine, "users", "last_login_at", "TEXT")


#: frozen copy of the accelerator→topology map as of schema v3. Migrations
#: must not import live code (db/models/resource.py's map will keep
#: evolving; replaying this migration years later must produce the v3
#: backfill, not whatever the map says then) — the Alembic lesson the
#: reference's 18 revisions encode by inlining everything
#: (/root/reference/tensorhive/migrations/versions/).
_V3_TOPOLOGIES = {
    "v5litepod-1": "1x1", "v5litepod-4": "2x2", "v5litepod-8": "2x4",
    "v5litepod-16": "4x4", "v5litepod-32": "4x8", "v5litepod-64": "8x8",
    "v5litepod-128": "8x16", "v5litepod-256": "16x16",
    "v4-8": "2x2x1", "v5p-8": "2x2x1", "v5p-16": "2x2x2",
    "v5p-32": "2x2x4", "v5p-64": "2x4x4", "v5p-128": "4x4x4",
}


def _migration_3_slice_topology(engine: Engine) -> None:
    """v2 → v3: ``resources.topology`` + ``resources.num_chips``, backfilled.

    Schema change plus DATA migration: topology comes from the accelerator
    type (frozen map above); num_chips from the topology where known, else
    from counting the slice's registered chips — rows that predate slice
    grouping degrade to a per-row count of 1, never NULL."""
    if not _column_names(engine, "resources"):
        # a DB stamped v1/v2 before ever registering a chip: the table does
        # not exist; ensure_schema's trailing create_all builds it with the
        # v3 columns already in place
        return
    _add_column(engine, "resources", "topology", "TEXT DEFAULT ''")
    _add_column(engine, "resources", "num_chips", "INTEGER DEFAULT 0")
    rows = engine.execute(
        "SELECT id, accelerator_type, slice_name FROM resources").fetchall()
    slice_counts: dict = {}
    for _, _, slice_name in rows:
        if slice_name:
            slice_counts[slice_name] = slice_counts.get(slice_name, 0) + 1
    for row_id, accel_type, slice_name in rows:
        topology = _V3_TOPOLOGIES.get(accel_type or "", "")
        num_chips = 1
        if topology:
            num_chips = 1
            for dim in topology.split("x"):
                num_chips *= int(dim)
        elif slice_name:
            num_chips = slice_counts[slice_name]
        engine.execute(
            "UPDATE resources SET topology = ?, num_chips = ? WHERE id = ?",
            (topology, num_chips, row_id))


# append (version, fn) pairs as the schema evolves; fn(engine) must be
# idempotent enough to re-run after a crash mid-upgrade.
MIGRATIONS: List[Tuple[int, Callable[[Engine], None]]] = [
    (2, _migration_2_user_last_login),
    (3, _migration_3_slice_topology),
]

SCHEMA_VERSION = 3


def ensure_schema(engine: Engine) -> None:
    from . import models  # noqa: F401  (register all tables)

    current = engine.user_version
    if current == 0:
        create_all(engine)
        engine.user_version = SCHEMA_VERSION
        log.info("database schema created at version %d", SCHEMA_VERSION)
        return
    for version, migrate in MIGRATIONS:
        if version > current:
            log.info("applying migration %d", version)
            migrate(engine)
            engine.user_version = version
    # create any tables added since the stamped version (additive changes)
    create_all(engine)
    if engine.user_version < SCHEMA_VERSION:
        engine.user_version = SCHEMA_VERSION
