"""Nodes controller — the monitoring read path.

Reference: tensorhive/controllers/nodes.py (164 LoC): ``get_infrastructure``
snapshots the live infra dict, persists newly-seen accelerators as Resource
rows, and prunes the view to the requester's restrictions (nodes.py:13-50);
plus endpoints for hostnames, metrics, per-chip info, processes and CPU
metrics (:53-160).
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

from ..api import schemas as S
from ..api.app import RequestContext, route
from ..api.schema import arr, obj, s
from ..core.managers.manager import get_manager
from ..db.models.resource import Resource
from ..utils.exceptions import NotFoundError

log = logging.getLogger(__name__)


def sync_resources_from_infrastructure(snapshot: Optional[Dict] = None) -> None:
    """Persist chips seen in live telemetry as Resource rows (reference
    nodes.py:17-40 auto-registration)."""
    if snapshot is None:
        snapshot = get_manager().infrastructure_manager.infrastructure
    for hostname, node in snapshot.items():
        for uid, chip in node.get("TPU", {}).items():
            from ..db.models.resource import (
                ACCELERATOR_TOPOLOGIES,
                topology_chip_count,
            )

            accel_type = chip.get("accelerator_type", "")
            topology = (chip.get("topology")
                        or ACCELERATOR_TOPOLOGIES.get(accel_type, ""))
            # single-chip floor matches the v3 migration backfill ("never
            # 0/NULL"): a chip with unknown topology is still one chip
            num_chips = max(1, topology_chip_count(topology))
            slice_name = chip.get("slice_name", "")
            existing = Resource.get_by_uid(uid)
            if existing is None:
                Resource(
                    uid=uid,
                    name=chip.get("name", uid),
                    hostname=hostname,
                    chip_index=chip.get("index", 0),
                    accelerator_type=accel_type,
                    slice_name=slice_name,
                    topology=topology,
                    num_chips=num_chips,
                ).save()
            elif (existing.slice_name, existing.topology,
                  existing.num_chips, existing.accelerator_type) != (
                      slice_name, topology, num_chips, accel_type):
                # refresh slice metadata on known chips: rows registered
                # before the host inventory carried topology/slice labels
                # (or before schema v3) would otherwise stay stale forever
                existing.slice_name = slice_name
                existing.topology = topology
                existing.num_chips = num_chips
                existing.accelerator_type = accel_type
                existing.save()


def get_infrastructure(context: RequestContext) -> Dict:
    """Snapshot + auto-register + restriction filtering (reference
    nodes.py:13-50). Admins see everything."""
    snapshot = get_manager().infrastructure_manager.infrastructure
    sync_resources_from_infrastructure(snapshot)
    if context.is_admin:
        return snapshot
    return context.current_user().filter_infrastructure_by_user_restrictions(snapshot)


@route("/nodes/metrics", ["GET"], summary="Full telemetry snapshot", tag="nodes",
       responses={200: S.INFRASTRUCTURE})
def get_all_data(context: RequestContext):
    return get_infrastructure(context)


@route("/nodes/hostnames", ["GET"], summary="Managed hostnames", tag="nodes",
       responses={200: arr(s("string"))})
def get_hostnames(context: RequestContext):
    return get_manager().infrastructure_manager.hostnames


@route("/nodes/<hostname>/metrics", ["GET"], summary="One node's telemetry",
       tag="nodes", responses={200: S.NODE})
def get_node_metrics(context: RequestContext, hostname: str):
    infrastructure = get_infrastructure(context)
    if hostname not in infrastructure:
        raise NotFoundError(f"unknown node {hostname!r}")
    return infrastructure[hostname]


@route("/nodes/<hostname>/tpu/info", ["GET"], summary="Chip inventory on a node",
       tag="nodes", responses={200: arr(S.CHIP_METRICS)})
def get_tpu_info(context: RequestContext, hostname: str):
    node = get_node_metrics(context, hostname)
    return [
        {key: value for key, value in chip.items() if key != "processes"}
        for chip in node.get("TPU", {}).values()
    ]


@route("/nodes/<hostname>/tpu/processes", ["GET"],
       summary="Per-chip processes on a node", tag="nodes",
       responses={200: {"type": "object",
                        "additionalProperties": {"type": "array",
                                                 "items": {"type": "object",
                                                           "additionalProperties": True}}}})
def get_tpu_processes(context: RequestContext, hostname: str):
    node = get_node_metrics(context, hostname)
    return {
        uid: chip.get("processes", []) for uid, chip in node.get("TPU", {}).items()
    }


@route("/nodes/<hostname>/cpu/metrics", ["GET"], summary="CPU/RAM metrics",
       tag="nodes",
       responses={200: {"type": "object", "additionalProperties": True}})
def get_cpu_metrics(context: RequestContext, hostname: str):
    node = get_node_metrics(context, hostname)
    return node.get("CPU", {})


_LEASE_RESPONSE = obj(
    required=["host", "lease"],
    host=s("string"),
    lease={"type": "object", "additionalProperties": True})


@route("/admin/hosts/<hostname>/drain", ["POST"], auth="admin",
       summary="Drain a host: no new work, running jobs stopped gracefully",
       tag="nodes", responses={200: _LEASE_RESPONSE})
def drain_host(context: RequestContext, hostname: str):
    """Admin drain (docs/ROBUSTNESS.md "Host membership & leases"): the
    host leaves `_eligible_hosts_resolver`, the scheduler spawns nothing
    new there and stops its running jobs via stop_with_grace; reservations
    stay intact so resume puts the host straight back to work."""
    try:
        lease = get_manager().infrastructure_manager.drain_host(hostname)
    except KeyError:
        raise NotFoundError(f"unknown host {hostname!r}")
    log.info("host %s draining (admin request)", hostname)
    return {"host": hostname, "lease": lease}


@route("/admin/hosts/<hostname>/resume", ["POST"], auth="admin",
       summary="Resume a drained host", tag="nodes",
       responses={200: _LEASE_RESPONSE})
def resume_host(context: RequestContext, hostname: str):
    try:
        lease = get_manager().infrastructure_manager.resume_host(hostname)
    except KeyError:
        raise NotFoundError(f"unknown host {hostname!r}")
    log.info("host %s resumed (admin request)", hostname)
    return {"host": hostname, "lease": lease}


@route("/admin/services", ["GET"], auth="admin",
       summary="Daemon service health (tick latency, liveness)", tag="nodes",
       responses={200: arr(obj(
           required=["name", "alive", "intervalS", "ticksCompleted"],
           name=s("string"),
           alive=s("boolean"),
           intervalS=s("number"),
           ticksCompleted=s("integer"),
           tickOverruns=s("integer"),
           tickP50Ms=s("number", nullable=True),
           tickP95Ms=s("number", nullable=True),
           tickMaxMs=s("number", nullable=True)))})
def get_service_health(context: RequestContext):
    """Per-service tick stats — the loop-timing observability the reference
    only wrote to debug logs (MonitoringService.py:38-54; SURVEY.md §5
    tracing), surfaced as API so the UI can show daemon health. Latency is
    p50/p95/max from the registry-backed tick histogram."""
    def ms(seconds):
        return round(seconds * 1000, 2) if seconds is not None else None

    service_manager = get_manager().service_manager
    health = []
    for service in (service_manager.services if service_manager else []):
        stats = service.tick_latency_stats()
        health.append({
            "name": service.name,
            "alive": service.is_alive(),
            "intervalS": service.interval_s,
            "ticksCompleted": service.ticks_completed,
            "tickOverruns": service.tick_overruns,
            "tickP50Ms": ms(stats["p50"]),
            "tickP95Ms": ms(stats["p95"]),
            "tickMaxMs": ms(stats["max"]),
        })
    return health
