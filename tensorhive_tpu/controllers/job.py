"""Job controller: CRUD + execute/stop + queue management.

Reference: tensorhive/controllers/job.py (421 LoC) — ``business_execute`` /
``business_stop`` (:267-310, :374-417) spawn/terminate every task of a job
and are reused verbatim by the scheduler service; enqueue/dequeue
(:313-350) feed the queue the GreedyScheduler drains.
"""
from __future__ import annotations

import logging
from typing import List, Optional

from ..api import schemas as S
from ..api.app import RequestContext, int_arg, route
from ..api.schema import arr, obj, s
from ..core.templates import Placement, render_template, template_names
from ..db.models.job import Job, JobStatus
from ..db.models.task import SegmentType, Task, TaskStatus
from ..db.models.user import User
from ..utils.exceptions import ConflictError, ForbiddenError, TransportError, ValidationError
from ..utils.timeutils import parse_datetime
from . import task as task_controller

log = logging.getLogger(__name__)

_get_or_404 = Job.get  # raises NotFoundError (→ 404) itself


def _assert_owner_or_admin(context: RequestContext, job: Job) -> None:
    if not context.is_admin and job.user_id != context.user_id:
        raise ForbiddenError("only the job owner or an admin may do this")


# -- business operations (shared with JobSchedulingService) ------------------

def business_execute(job_id: int) -> Job:
    """Spawn all tasks; tasks that fail to spawn are reported but don't
    roll back the ones already started (reference job.py:267-310)."""
    job = Job.get(job_id)
    if not job.tasks:
        raise ConflictError(f"job {job_id} has no tasks")
    errors: List[str] = []
    for task in job.tasks:
        try:
            task_controller.business_spawn(task.id)
        except (TransportError, ConflictError) as exc:
            # TransportError covers SpawnError AND unreachable-host failures:
            # one bad host must not abort the remaining tasks
            errors.append(f"task {task.id}: {exc}")
    job = Job.get(job_id)
    job.synchronize_status()
    if errors:
        log.warning("job %d partially spawned: %s", job_id, "; ".join(errors))
    return job


def business_stop(job_id: int, gracefully: Optional[bool] = True) -> Job:
    """Terminate all running tasks (reference job.py:374-417)."""
    job = Job.get(job_id)
    for task in job.tasks:
        if task.status is TaskStatus.running:
            try:
                task_controller.business_terminate(task.id, gracefully)
            except (ConflictError, TransportError) as exc:
                log.warning("job %d: stopping task %d failed: %s", job_id, task.id, exc)
    job = Job.get(job_id)
    job.synchronize_status()
    return job


# -- HTTP endpoints ----------------------------------------------------------

@route("/jobs", ["GET"], summary="List jobs (optionally ?user_id=)", tag="jobs",
       responses={200: arr(S.JOB)}, query={"user_id": s("integer")})
def list_jobs(context: RequestContext):
    # Listing everyone's jobs is admin-only; non-admins may only list their
    # own (fullCommand embeds env segments, which commonly hold secrets).
    # Reference gates this the same way (reference job.py:48-60).
    user_id = int_arg(context, "user_id")
    if not context.is_admin:
        if user_id is not None and user_id != context.user_id:
            raise ForbiddenError("only admins may list other users' jobs")
        user_id = context.user_id
    jobs = Job.filter_by(user_id=user_id) if user_id is not None else Job.all()
    return [job.as_dict() for job in jobs]


@route("/jobs/<int:job_id>", ["GET"], summary="Get one job with tasks", tag="jobs",
       responses={200: S.JOB})
def get_job(context: RequestContext, job_id: int):
    job = _get_or_404(job_id)
    _assert_owner_or_admin(context, job)
    return job.as_dict()  # as_dict embeds task list


@route("/jobs", ["POST"], summary="Create a job", tag="jobs",
       body=obj(required=["name"],
                name=s("string", minLength=1),
                description=s("string"),
                userId=s("integer", description="admin-only: create for another user"),
                startAt=s("string", format="date-time", nullable=True),
                stopAt=s("string", format="date-time", nullable=True)),
       responses={201: S.JOB})
def create_job(context: RequestContext):
    data = context.json()  # required fields enforced by the route schema
    user_id = context.user_id
    if context.is_admin and "userId" in data:
        user_id = User.get(int(data["userId"])).id
    job = Job(
        name=data["name"],
        description=data.get("description", ""),
        user_id=user_id,
        start_at=parse_datetime(data["startAt"]) if data.get("startAt") else None,
        stop_at=parse_datetime(data["stopAt"]) if data.get("stopAt") else None,
    ).save()
    return job.as_dict(), 201


@route("/jobs/<int:job_id>", ["PUT"], summary="Update a job", tag="jobs",
       body=obj(name=s("string", minLength=1), description=s("string"),
                startAt=s("string", format="date-time", nullable=True),
                stopAt=s("string", format="date-time", nullable=True)),
       responses={200: S.JOB})
def update_job(context: RequestContext, job_id: int):
    job = _get_or_404(job_id)
    _assert_owner_or_admin(context, job)
    data = context.json()
    if "name" in data:
        job.name = data["name"]
    if "description" in data:
        job.description = data["description"]
    if "startAt" in data:
        job.start_at = parse_datetime(data["startAt"]) if data["startAt"] else None
    if "stopAt" in data:
        job.stop_at = parse_datetime(data["stopAt"]) if data["stopAt"] else None
    job.save()
    return job.as_dict()


@route("/jobs/<int:job_id>", ["DELETE"], summary="Delete a job", tag="jobs",
       responses={200: S.MSG})
def delete_job(context: RequestContext, job_id: int):
    job = _get_or_404(job_id)
    _assert_owner_or_admin(context, job)
    job.synchronize_status()
    job = Job.get(job_id)
    if job.status is JobStatus.running:
        raise ConflictError("stop the job before deleting it")
    job.destroy()
    return {"msg": "job deleted"}


@route("/jobs/<int:job_id>/execute", ["POST"], summary="Spawn all tasks of a job",
       tag="jobs", responses={200: S.JOB})
def execute(context: RequestContext, job_id: int):
    job = _get_or_404(job_id)
    _assert_owner_or_admin(context, job)
    return business_execute(job_id).as_dict()


@route("/jobs/<int:job_id>/stop", ["POST"], summary="Stop all tasks of a job",
       tag="jobs", body=S.GRACEFULLY_BODY, responses={200: S.JOB})
def stop(context: RequestContext, job_id: int):
    job = _get_or_404(job_id)
    _assert_owner_or_admin(context, job)
    gracefully = context.json().get("gracefully", True)
    if gracefully not in (True, False, None):
        raise ValidationError("gracefully must be true, false or null")
    return business_stop(job_id, gracefully).as_dict()


@route("/templates", ["GET"], summary="Available launch-topology templates",
       tag="jobs", responses={200: arr(s("string"))})
def list_templates(context: RequestContext):
    return template_names()


_TEMPLATE_BODY = obj(required=["template", "command", "placements"],
                     template=s("string"),
                     command=s("string", minLength=1),
                     placements=arr(obj(required=["hostname"],
                                        hostname=s("string"),
                                        address=s("string"),
                                        chips=arr(s("integer")))),
                     options=obj(extra=True))


def _render_from_request(data):
    """Shared placement parsing + render for the generate/preview routes."""
    if not isinstance(data["placements"], list):
        raise ValidationError("placements must be a list of objects")
    placements = []
    for i, p in enumerate(data["placements"]):
        if not isinstance(p, dict) or not p.get("hostname"):
            raise ValidationError(f"placements[{i}] needs a 'hostname'")
        placements.append(Placement(
            hostname=p["hostname"],
            address=p.get("address", ""),
            chips=p.get("chips"),
        ))
    return render_template(
        data["template"], data["command"], placements, data.get("options"))


@route("/templates/preview", ["POST"],
       summary="Render a template without creating tasks", tag="jobs",
       body=_TEMPLATE_BODY,
       responses={200: arr(obj(hostname=s("string"), command=s("string"),
                               env=obj(extra=True), params=obj(extra=True)))})
def preview_template(context: RequestContext):
    """The interactive-editing step the reference's TaskCreate.vue offers
    client-side (TaskCreate.vue:202-424): render the per-process specs so
    the UI can show every generated env var/parameter as editable rows
    before any task exists; the edited lines are then created through the
    plain POST /tasks path."""
    specs = _render_from_request(context.json())
    return [{"hostname": spec.hostname, "command": spec.command,
             "env": spec.env, "params": spec.params} for spec in specs]


@route("/jobs/<int:job_id>/tasks_from_template", ["POST"],
       summary="Generate the job's tasks from a distributed-launch template",
       tag="jobs",
       body=_TEMPLATE_BODY,
       responses={201: arr(S.TASK)})
def tasks_from_template(context: RequestContext, job_id: int):
    """Body: ``{template, command, placements: [{hostname, address?, chips?}],
    options?}`` — renders one task per process with auto-filled distributed
    wiring (the server-side TaskCreate.vue engine, core/templates.py)."""
    job = _get_or_404(job_id)
    _assert_owner_or_admin(context, job)
    specs = _render_from_request(context.json())
    tasks = []
    for spec in specs:
        task = Task(job_id=job.id, hostname=spec.hostname, command=spec.command).save()
        for name, value in spec.env.items():
            task.add_cmd_segment(name, value, SegmentType.env_variable)
        for name, value in spec.params.items():
            task.add_cmd_segment(name, value, SegmentType.parameter)
        tasks.append(task)
    return [task.as_dict() for task in tasks], 201


@route("/jobs/<int:job_id>/enqueue", ["PUT"], summary="Place job in the scheduler queue",
       tag="jobs", responses={200: S.JOB})
def enqueue(context: RequestContext, job_id: int):
    job = _get_or_404(job_id)
    _assert_owner_or_admin(context, job)
    job.enqueue()
    return job.as_dict()


@route("/jobs/<int:job_id>/dequeue", ["PUT"], summary="Remove job from the queue",
       tag="jobs", responses={200: S.JOB})
def dequeue(context: RequestContext, job_id: int):
    job = _get_or_404(job_id)
    _assert_owner_or_admin(context, job)
    job.dequeue()
    return job.as_dict()
