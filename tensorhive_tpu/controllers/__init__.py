"""API controllers (reference: tensorhive/controllers/).

Each module declares its routes with :func:`tensorhive_tpu.api.app.route`;
importing this package registers everything (the rebuild's analog of the
reference's RestyResolver scan, api/APIServer.py:31).
"""
from . import (
    agent,
    generate,
    group,
    job,
    nodes,
    observability,
    reservation,
    resource,
    restriction,
    schedule,
    task,
    user,
)

ALL_MODULES = (user, group, resource, nodes, reservation, restriction, schedule,
               job, task, observability, generate, agent)
