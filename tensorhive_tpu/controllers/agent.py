"""Agent controller — the push half of the hybrid monitoring plane.

``POST /agent/report`` receives one heartbeat + telemetry report from a
``tpuhive-agent`` (core/agent.py) and applies it to the membership lease
state machine (docs/ROBUSTNESS.md "Host membership & leases"). Unlike every
other write endpoint this one is authenticated by the shared agent bearer
token from ``[agent] token``, not a user JWT: agents are machines, not
users, and the token compare is constant-time. While the plane is disabled
(``[agent] enabled = false`` or an empty token) the endpoint answers 404 —
same knob-naming pattern as the profiling endpoints.

Idempotence lives in the manager (sequence numbers per incarnation);
telemetry subtrees are applied only for ``accepted`` reports, so a
duplicated or replayed report can refresh a lease but never rewrite
telemetry out of order.
"""
from __future__ import annotations

import hmac
import json
import logging
from typing import Any, Dict, Tuple

from ..api.app import RequestContext, route
from ..api.schema import obj, s
from ..config import HostConfig, get_config
from ..core.managers.infrastructure import AGENT_REPORTS
from ..core.managers.manager import get_manager
from ..core.monitors.cpu import cpu_subtree
from ..core.monitors.probe import parse_probe_output
from ..core.monitors.tpu import chip_subtree, host_warnings
from ..api.jwt import AuthError
from ..utils.exceptions import NotFoundError, ValidationError

log = logging.getLogger(__name__)

#: host fields an agent may self-describe on dynamic join (everything else —
#: notably ``backend``/``user``/``port`` — stays operator-controlled)
_JOINABLE_HOST_FIELDS = ("address", "accelerator_type", "topology", "chips",
                         "slice_name", "worker_index")

#: hostname -> (total, idle) jiffies from the previous accepted report; the
#: push-path analog of CpuMonitor._prev (util is a cross-report delta)
_prev_cpu: Dict[str, Tuple[int, int]] = {}

AGENT_REPORT_BODY = obj(
    required=["v", "hostname", "incarnation", "seq", "probe"],
    v=s("integer"),
    hostname=s("string"),
    incarnation=s("string"),
    seq=s("integer"),
    sentTs=s("number"),
    sent_ts=s("number"),
    probe={"type": "object", "additionalProperties": True},
    host={"type": "object", "additionalProperties": True},
)


def _agent_config():
    """404 while the membership plane is off — the response names the knob,
    like the profiling endpoints do."""
    config = get_config().agent
    if not config.enabled or not config.token:
        raise NotFoundError(
            "agent membership plane disabled — set [agent] enabled = true "
            "and a shared token in config.toml")
    return config


def _check_token(context: RequestContext, config) -> None:
    header = context.request.headers.get("Authorization", "")
    presented = header[len("Bearer "):] if header.startswith("Bearer ") else ""
    if not presented or not hmac.compare_digest(presented, config.token):
        # bounded cardinality: unauthenticated reports may carry arbitrary
        # hostnames, so the bad_token outcome is counted against "unknown"
        AGENT_REPORTS.labels(host="unknown", outcome="bad_token").inc()
        raise AuthError("invalid agent token")


def _register_dynamic_host(hostname: str, host_info: Dict[str, Any]) -> None:
    """First report from an unconfigured host = dynamic join: materialize a
    HostConfig (agent-enabled, so the SSH fan-out never targets it) from the
    agent's self-description."""
    manager = get_manager()
    if hostname in manager.config.hosts:
        return
    fields = {key: host_info[key] for key in _JOINABLE_HOST_FIELDS
              if key in host_info}
    host = HostConfig(name=hostname, agent=True, **fields)
    manager.transport_manager.add_host(host)
    log.info("host %s joined dynamically via agent report (%s)",
             hostname, host.accelerator_type or "no accelerator metadata")


@route("/agent/report", ["POST"], auth=None,
       summary="Agent heartbeat + telemetry report (agent-token auth)",
       tag="agent", body=AGENT_REPORT_BODY,
       responses={200: obj(required=["outcome", "lease"],
                           outcome=s("string"),
                           lease={"type": "object",
                                  "additionalProperties": True})})
def post_agent_report(context: RequestContext):
    config = _agent_config()
    _check_token(context, config)
    body = context.json()
    if body["v"] != 1:
        raise ValidationError(f"unsupported agent wire version {body['v']!r}")
    hostname = body["hostname"]
    if not hostname:
        raise ValidationError("hostname must be non-empty")
    manager = get_manager()
    infra = manager.infrastructure_manager

    # lease first: even a report whose telemetry fails to parse is a
    # heartbeat (the agent process is alive on that host)
    try:
        sample = parse_probe_output(json.dumps(body["probe"]))
    except Exception as exc:
        raise ValidationError(f"unparseable probe document: {exc}")

    outcome = infra.agent_report(hostname, body["incarnation"],
                                 int(body["seq"]))
    if outcome == "accepted":
        _register_dynamic_host(hostname, body.get("host") or {})
        host_cfg = manager.config.hosts.get(hostname)
        infra.update_subtree(hostname, "TPU",
                             chip_subtree(hostname, sample, host_cfg))
        infra.update_subtree(hostname, "WARNINGS",
                             host_warnings(hostname, sample))
        prev = _prev_cpu.get(hostname)
        if sample.cpu_total is not None and sample.cpu_idle is not None:
            _prev_cpu[hostname] = (sample.cpu_total, sample.cpu_idle)
        infra.update_subtree(hostname, "CPU",
                             cpu_subtree(hostname, sample, prev))
    return {"outcome": outcome, "lease": infra.host_lease(hostname)}
