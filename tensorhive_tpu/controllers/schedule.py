"""Schedule controller (reference: tensorhive/controllers/schedule.py, 135
LoC): RestrictionSchedule CRUD."""
from __future__ import annotations

from ..api.app import RequestContext, json_body, route
from ..db.models.schedule import RestrictionSchedule


_get_or_404 = RestrictionSchedule.get  # raises NotFoundError (→ 404) itself


@route("/schedules", ["GET"], summary="List schedules", tag="schedules")
def list_schedules(context: RequestContext):
    return [s.as_dict() for s in RestrictionSchedule.all()]


@route("/schedules/<int:schedule_id>", ["GET"], summary="Get one schedule", tag="schedules")
def get_schedule(context: RequestContext, schedule_id: int):
    return _get_or_404(schedule_id).as_dict()


@route("/schedules", ["POST"], auth="admin", summary="Create a schedule", tag="schedules")
def create_schedule(context: RequestContext):
    data = json_body(context, "scheduleDays", "hourStart", "hourEnd")
    schedule = RestrictionSchedule(
        schedule_days=data["scheduleDays"],
        hour_start=data["hourStart"],
        hour_end=data["hourEnd"],
    ).save()
    return schedule.as_dict(), 201


@route("/schedules/<int:schedule_id>", ["PUT"], auth="admin", summary="Update a schedule",
       tag="schedules")
def update_schedule(context: RequestContext, schedule_id: int):
    schedule = _get_or_404(schedule_id)
    data = context.json()
    if "scheduleDays" in data:
        schedule.schedule_days = data["scheduleDays"]
    if "hourStart" in data:
        schedule.hour_start = data["hourStart"]
    if "hourEnd" in data:
        schedule.hour_end = data["hourEnd"]
    schedule.save()
    return schedule.as_dict()


@route("/schedules/<int:schedule_id>", ["DELETE"], auth="admin",
       summary="Delete a schedule", tag="schedules")
def delete_schedule(context: RequestContext, schedule_id: int):
    _get_or_404(schedule_id).destroy()
    return {"msg": "schedule deleted"}
