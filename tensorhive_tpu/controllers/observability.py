"""Observability controller: metrics exposition, trace dump, alert state,
request ledger, profiler captures and health probes.

The read surfaces of tensorhive_tpu/observability:

* ``GET /metrics`` — Prometheus text format (version 0.0.4), unauthenticated
  like a conventional scrape target (it carries latency/count aggregates,
  never user data; JIRIAF-style virtual-kubelet integrations assume exactly
  this per-resource endpoint).
* ``GET /admin/traces`` — recent spans from the ring-buffer tracer,
  admin-auth (span attrs include hostnames and job ids).
* ``GET /admin/requests`` — the per-request serving ledger: phase timings
  (queue/prefill/decode), slot/page placement, compile hit/miss and outcome
  for recent generate requests, admin-auth (docs/OBSERVABILITY.md "Request
  tracing & profiling").
* ``POST /api/admin/profile`` / ``GET /api/admin/profile/memory`` —
  on-demand ``jax.profiler`` trace captures and live-HBM snapshots,
  admin-auth, 404 while ``[profiling]`` is disabled.
* ``GET /healthz`` / ``GET /readyz`` — liveness and readiness, both
  unauthenticated (an orchestrator's kubelet-style prober has no JWT);
  readiness returns 503 with a JSON reason list when any component fails.
* ``GET /admin/alerts`` — full rule/state dump of the alert engine plus
  the transition history ring, admin-auth.
"""
from __future__ import annotations

from typing import Dict, Tuple

from werkzeug.wrappers import Response

from ..api.app import RequestContext, int_arg, json_body, route
from ..api.schema import arr, obj, s
from ..observability import get_registry, get_request_ledger, get_tracer
from ..observability.alerts import get_alert_engine
from ..observability.health import liveness, readiness
from ..utils.exceptions import ConflictError, NotFoundError, ValidationError

#: content type Prometheus scrapers negotiate for the text format
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

SPAN_SCHEMA = obj(
    required=["spanId", "name", "kind", "startTs", "status", "seq"],
    spanId=s("string"),
    parentId=s("string", nullable=True),
    name=s("string"),
    kind=s("string"),
    startTs=s("number"),
    durationMs=s("number", nullable=True),
    status=s("string"),
    attrs={"type": "object", "additionalProperties": True},
    seq=s("integer"),
)


@route("/metrics", ["GET"], auth=None,
       summary="Prometheus metrics exposition (text format)",
       tag="observability", responses={200: s("string")})
def get_metrics(context: RequestContext) -> Response:
    return Response(get_registry().render(),
                    content_type=PROMETHEUS_CONTENT_TYPE)


@route("/admin/traces", ["GET"], auth="admin",
       summary="Recent spans from the ring-buffer tracer",
       tag="observability",
       query={"limit": s("integer"), "kind": s("string")},
       responses={200: obj(required=["capacity", "recorded", "spans"],
                           capacity=s("integer"),
                           recorded=s("integer"),
                           spans=arr(SPAN_SCHEMA))})
def get_traces(context: RequestContext) -> Dict:
    """Completed spans oldest-first (monotone ``seq``); ``?limit=`` caps the
    dump, ``?kind=`` filters (api, tick, monitor, transport, probe, job)."""
    tracer = get_tracer()
    limit = int_arg(context, "limit")
    kind = context.request.args.get("kind")
    return {
        "capacity": tracer.capacity,
        "recorded": len(tracer),
        "spans": tracer.recent(limit=limit, kind=kind),
    }


HEALTH_COMPONENT_SCHEMA = obj(
    required=["component", "ok"],
    component=s("string"),
    ok=s("boolean"),
    reason=s("string"),
)

ALERT_RULE_SCHEMA = obj(
    required=["name", "severity", "kind", "status"],
    name=s("string"),
    severity=s("string"),
    kind=s("string"),
    metric=s("string", nullable=True),
    labels={"type": "object", "additionalProperties": True},
    op=s("string"),
    threshold=s("number"),
    windowS=s("number"),
    forS=s("number"),
    description=s("string"),
    status=s("string"),
    since=s("number", nullable=True),
    lastValue=s("number", nullable=True),
    firedCount=s("integer"),
)


@route("/healthz", ["GET"], auth=None,
       summary="Liveness probe (process is serving requests)",
       tag="observability",
       responses={200: obj(required=["status", "version", "uptimeS"],
                           status=s("string"),
                           version=s("string"),
                           uptimeS=s("number"))})
def get_healthz(context: RequestContext) -> Dict:
    """Unauthenticated by design: a kubelet-style prober carries no JWT,
    and the payload holds nothing but uptime + build version."""
    return liveness()


@route("/readyz", ["GET"], auth=None,
       summary="Readiness probe (503 + reasons when any component fails)",
       tag="observability",
       responses={200: obj(required=["ready", "components"],
                           ready=s("boolean"),
                           components=arr(HEALTH_COMPONENT_SCHEMA),
                           reasons=arr(s("string"))),
                  503: obj(required=["ready", "components", "reasons"],
                           ready=s("boolean"),
                           components=arr(HEALTH_COMPONENT_SCHEMA),
                           reasons=arr(s("string")))})
def get_readyz(context: RequestContext) -> Tuple[Dict, int]:
    """DB answers a query, every registered service is alive and ticking
    within 3x its interval, the probe round is fresh when hosts are
    managed — any failure 503s with the component named."""
    ready, components = readiness()
    reasons = [f"{c['component']}: {c.get('reason', 'not ok')}"
               for c in components if not c["ok"]]
    return ({"ready": ready, "components": components, "reasons": reasons},
            200 if ready else 503)


REQUEST_RECORD_SCHEMA = obj(
    required=["requestId", "submittedTs", "promptTokens", "maxNewTokens",
              "tokens"],
    requestId=s("string"),
    outcome=s("string", nullable=True),
    submittedTs=s("number"),
    finishedTs=s("number", nullable=True),
    promptTokens=s("integer"),
    maxNewTokens=s("integer"),
    temperature=s("number"),
    userKey=s("string", nullable=True),
    slot=s("integer", nullable=True),
    kvPages=s("integer", nullable=True),
    queueMs=s("number", nullable=True),
    #: prompt tokens the prefix cache let prefill skip (docs/SERVING.md
    #: "Prefix cache & chunked prefill"; null = prefix cache off)
    cachedTokens=s("integer", nullable=True),
    #: prefill chunks dispatched (0 = full-prefix hit; null = legacy path)
    prefillChunks=s("integer", nullable=True),
    prefillBucket=s("integer", nullable=True),
    prefillCompile=s("string", nullable=True),
    prefillMs=s("number", nullable=True),
    #: KV-page tiering (docs/SERVING.md "KV-page tiering"): pages promoted
    #: from the host store instead of recomputed, and the promotion DMA's
    #: wall share of TTFT (null = host_kv_bytes=0 rollback)
    hostHitPages=s("integer", nullable=True),
    promoteMs=s("number", nullable=True),
    ttftMs=s("number", nullable=True),
    decodeMs=s("number", nullable=True),
    totalMs=s("number", nullable=True),
    #: tenant accounting (docs/OBSERVABILITY.md "Tenant accounting"): the
    #: TenantMeter's per-request resource-time integrals, finalized at
    #: request end (null = [accounting] off or the row predates it)
    deviceSeconds=s("number", nullable=True),
    kvByteSeconds=s("number", nullable=True),
    tokens=s("integer"),
    intertokenP50Ms=s("number", nullable=True),
)


@route("/admin/requests", ["GET"], auth="admin",
       summary="Per-request serving traces (phase timings + outcomes)",
       tag="observability",
       query={"limit": s("integer"), "outcome": s("string"),
              "user": s("string")},
       responses={200: obj(required=["capacity", "recorded", "requests",
                                     "inFlight"],
                           capacity=s("integer"),
                           recorded=s("integer"),
                           requests=arr(REQUEST_RECORD_SCHEMA),
                           inFlight=arr(REQUEST_RECORD_SCHEMA))})
def get_requests(context: RequestContext) -> Dict:
    """Finished generate requests newest-first with their
    queue/prefill/decode phase breakdown, slot/page placement, prefill
    compile hit/miss and outcome (rejections included), plus the requests
    currently queued or running; ``?limit=`` caps the finished dump,
    ``?outcome=`` and ``?user=`` (exact ``userKey`` match) filter it.
    Every row's ``requestId`` matches the ``X-Request-Id`` response
    header and the ``request_id`` attr on the ``generate.*`` spans in
    ``GET /api/admin/traces``."""
    ledger = get_request_ledger()
    limit = int_arg(context, "limit")
    outcome = context.request.args.get("outcome")
    user = context.request.args.get("user")
    return {
        "capacity": ledger.capacity,
        "recorded": len(ledger),
        "requests": ledger.recent(limit=limit, outcome=outcome, user=user),
        "inFlight": ledger.in_flight(),
    }


def _profiling_config():
    """The [profiling] config, or a 404 while the subsystem is disabled —
    surfacing capture endpoints on a process whose operator never opted in
    would expose disk writes + a process-wide profiler to any admin JWT."""
    from ..config import get_config

    config = get_config()
    if not config.profiling.enabled:
        raise NotFoundError(
            "profiling is disabled on this manager ([profiling] enabled "
            "in config.toml; docs/OBSERVABILITY.md)")
    return config


@route("/admin/profile", ["POST"], auth="admin",
       summary="Capture a bounded jax.profiler trace to the artifact dir",
       tag="observability",
       body=obj(durationS=s("number"), ),
       responses={200: obj(required=["artifactDir", "durationS", "files",
                                     "bytes"],
                           artifactDir=s("string"),
                           durationS=s("number"),
                           startedTs=s("number"),
                           files=arr(s("string")),
                           bytes=s("integer")),
                  404: obj(required=["msg"], msg=s("string")),
                  409: obj(required=["msg"], msg=s("string"))})
def post_profile(context: RequestContext) -> Dict:
    """Run ``jax.profiler.start_trace``/``stop_trace`` around a bounded
    window (body ``durationS``, default/ceiling from ``[profiling]``) so
    steady-state serving traffic lands in a TensorBoard-loadable artifact.
    Single-flight: a concurrent capture answers 409 — the XLA profiler is
    process-wide and two captures would corrupt each other."""
    from ..observability import get_tracer as _get_tracer
    from ..observability.profiling import (
        ProfileInFlightError,
        ProfileUnavailableError,
        capture_trace,
    )

    config = _profiling_config()
    body = json_body(context)
    duration_raw = body.get("durationS")
    duration_s = (config.profiling.default_duration_s
                  if duration_raw is None else float(duration_raw))
    try:
        return capture_trace(
            str(config.profile_artifact_dir), duration_s,
            max_duration_s=config.profiling.max_duration_s,
            tracer=_get_tracer())
    except ValueError as exc:
        raise ValidationError(str(exc))
    except ProfileInFlightError as exc:
        raise ConflictError(str(exc))
    except ProfileUnavailableError as exc:
        raise NotFoundError(str(exc))


@route("/admin/profile/memory", ["GET"], auth="admin",
       summary="Live device-memory snapshot (per-device HBM bytes)",
       tag="observability",
       query={"format": s("string")},
       responses={200: obj(required=["capturedTs", "devices",
                                     "totalLiveBytes"],
                           capturedTs=s("number"),
                           devices=arr(obj(
                               required=["device", "liveBytes",
                                         "allocations"],
                               device=s("string"),
                               liveBytes=s("integer"),
                               allocations=s("integer"))),
                           totalLiveBytes=s("integer"),
                           profileBytes=s("integer")),
                  404: obj(required=["msg"], msg=s("string"))})
def get_profile_memory(context: RequestContext):
    """One ``jax.profiler.device_memory_profile`` snapshot parsed to
    per-device live bytes (also exported as
    ``tpuhive_device_hbm_live_bytes{device}`` so HBM growth is scrapeable
    alongside the KV-pages gauges); ``?format=pprof`` returns the raw
    gzipped pprof blob for offline analysis."""
    from ..observability.profiling import (
        device_memory_summary,
        raw_device_memory_profile,
    )

    _profiling_config()
    if context.request.args.get("format") == "pprof":
        return Response(raw_device_memory_profile(),
                        content_type="application/octet-stream")
    return device_memory_summary(registry=get_registry())


@route("/admin/alerts", ["GET"], auth="admin",
       summary="Alert rule/state dump with transition history",
       tag="observability",
       responses={200: obj(required=["rules", "firing", "transitions"],
                           rules=arr(ALERT_RULE_SCHEMA),
                           firing=arr(s("string")),
                           transitions=arr({"type": "object",
                                            "additionalProperties": True}))})
def get_alerts(context: RequestContext) -> Dict:
    """Current engine truth: every rule with its lifecycle status and last
    value, the firing subset, and the bounded transition history ring —
    the same state the `tpuhive_alerts_firing` gauges export."""
    return get_alert_engine().dump()


def _history_config():
    """The [history] config, or a 404 while the subsystem is disabled —
    same contract as the profiling endpoints: a surface the operator
    turned off does not exist."""
    from ..config import get_config

    config = get_config()
    if not config.history.enabled:
        raise NotFoundError(
            "metrics history is disabled on this manager ([history] "
            "enabled in config.toml; docs/OBSERVABILITY.md)")
    return config


def _float_arg(context: RequestContext, name: str):
    raw = context.request.args.get(name)
    if raw is None or raw == "":
        return None
    try:
        return float(raw)
    except ValueError:
        raise ValidationError(f"query param {name!r} must be a number, "
                              f"got {raw!r}")


HISTORY_POINT_SCHEMA = obj(
    required=["ts", "min", "mean", "max", "last", "count"],
    ts=s("number"),
    min=s("number"),
    mean=s("number"),
    max=s("number"),
    last=s("number"),
    count=s("integer"),
)


@route("/admin/history", ["GET"], auth="admin",
       summary="Downsampled metrics history (ring TSDB over the registry)",
       tag="observability",
       query={"series": s("string"), "since": s("number"),
              "step": s("number")},
       responses={200: obj(required=["retentionS", "windowS", "series"],
                           retentionS=s("number"),
                           windowS=s("number"),
                           sampleIntervalS=s("number"),
                           series={"type": "object",
                                   "additionalProperties": True}),
                  404: obj(required=["msg"], msg=s("string"))})
def get_history(context: RequestContext) -> Dict:
    """Per-series min/mean/max/last windows oldest-first from the
    in-process history store (docs/OBSERVABILITY.md "History, SLOs &
    flight recorder"). ``?series=`` is a comma-separated allowlist-spec
    filter (default: everything sampled), ``?since=`` a unix-seconds
    floor, ``?step=`` re-buckets into coarser windows. 404 while
    ``[history]`` is disabled."""
    from ..observability.history import get_metrics_history

    config = _history_config()
    raw = context.request.args.get("series")
    series = None
    if raw:
        series = [part.strip() for part in raw.split(",") if part.strip()]
    history = get_metrics_history()
    try:
        data = history.query(series=series,
                             since=_float_arg(context, "since"),
                             step=_float_arg(context, "step"))
    except ValueError as exc:
        raise ValidationError(str(exc))
    return {
        "retentionS": history.retention_s,
        "windowS": history.window_s,
        "sampleIntervalS": config.history.sample_interval_s,
        "series": data,
    }


def _accounting_config():
    """The [accounting] config, or a 404 while tenant accounting is
    disabled — same contract as the profiling/history endpoints: a
    surface the operator turned off does not exist."""
    from ..config import get_config

    config = get_config()
    if not config.accounting.enabled:
        raise NotFoundError(
            "tenant accounting is disabled on this manager ([accounting] "
            "enabled in config.toml; docs/OBSERVABILITY.md)")
    return config


USAGE_TENANT_SCHEMA = obj(
    required=["tenant", "deviceSeconds", "kvByteSeconds", "queueSeconds",
              "share"],
    tenant=s("string"),
    deviceSeconds=s("number"),
    kvByteSeconds=s("number"),
    hostKvByteSeconds=s("number"),
    queueSeconds=s("number"),
    prefillTokens=s("integer"),
    decodeTokens=s("integer"),
    cachedTokens=s("integer"),
    specAcceptedTokens=s("integer"),
    reservedChipSeconds=s("number"),
    effectiveChipSeconds=s("number"),
    #: fraction of the window's ATTRIBUTED device-seconds (all tenants'
    #: shares sum to 1 while anything was attributed)
    share=s("number"),
    #: fraction of the window's theoretical capacity (numDevices x
    #: window); null while no serving engine is published
    capacityShare=s("number", nullable=True),
)


@route("/admin/usage", ["GET"], auth="admin",
       summary="Per-tenant resource rollups (chip-seconds, HBM, queue)",
       tag="observability",
       query={"window": s("number"), "user": s("string")},
       responses={200: obj(required=["windowS", "tenants", "totals"],
                           windowS=s("number"),
                           topKTenants=s("integer"),
                           numDevices=s("integer", nullable=True),
                           busySlotSeconds=s("number", nullable=True),
                           tenants=arr(USAGE_TENANT_SCHEMA),
                           totals={"type": "object",
                                   "additionalProperties": True}),
                  404: obj(required=["msg"], msg=s("string"))})
def get_usage(context: RequestContext) -> Dict:
    """Per-tenant rollups over the trailing window (docs/OBSERVABILITY.md
    "Tenant accounting"): device-seconds, HBM/host KV byte-seconds,
    queue-seconds and token splits from the serving plane plus
    reservation chip-seconds, with share-of-attributed and
    share-of-capacity fractions. ``?window=`` overrides the
    ``[accounting] window_s`` lookback, ``?user=`` keeps one tenant's
    row. 404 while ``[accounting]`` is disabled."""
    from ..observability.accounting import get_tenant_meter
    from ..serving import get_engine

    config = _accounting_config()
    meter = get_tenant_meter()
    if meter is None:       # disabled between config load and this call
        raise NotFoundError(
            "tenant accounting is disabled on this manager ([accounting] "
            "enabled in config.toml; docs/OBSERVABILITY.md)")
    window_s = _float_arg(context, "window")
    if window_s is None:
        window_s = config.accounting.window_s
    if window_s <= 0:
        raise ValidationError(
            f"query param 'window' must be > 0 seconds, got {window_s}")
    user = context.request.args.get("user")
    rollup = meter.rollup(window_s=window_s)
    total_device = sum(u.device_seconds for u in rollup.values())
    engine = get_engine()
    capacity_s = (engine.num_devices * window_s
                  if engine is not None else None)
    tenants = []
    for tenant, usage in sorted(rollup.items(),
                                key=lambda kv: (-kv[1].device_seconds,
                                                kv[0])):
        if user is not None and tenant != user:
            continue
        tenants.append({
            "tenant": tenant,
            "deviceSeconds": round(usage.device_seconds, 6),
            "kvByteSeconds": round(usage.kv_byte_seconds, 3),
            "hostKvByteSeconds": round(usage.host_kv_byte_seconds, 3),
            "queueSeconds": round(usage.queue_seconds, 6),
            "prefillTokens": int(usage.prefill_tokens),
            "decodeTokens": int(usage.decode_tokens),
            "cachedTokens": int(usage.cached_tokens),
            "specAcceptedTokens": int(usage.spec_accepted_tokens),
            "reservedChipSeconds": round(usage.reserved_chip_seconds, 6),
            "effectiveChipSeconds": round(usage.effective_chip_seconds, 6),
            "share": (round(usage.device_seconds / total_device, 6)
                      if total_device > 0 else 0.0),
            "capacityShare": (round(usage.device_seconds / capacity_s, 6)
                              if capacity_s else None),
        })
    return {
        "windowS": window_s,
        "topKTenants": meter.top_k,
        "numDevices": engine.num_devices if engine is not None else None,
        "busySlotSeconds": (round(engine.busy_slot_seconds, 6)
                            if engine is not None else None),
        "tenants": tenants,
        "totals": {
            "deviceSeconds": round(total_device, 6),
            "kvByteSeconds": round(sum(u.kv_byte_seconds
                                       for u in rollup.values()), 3),
            "queueSeconds": round(sum(u.queue_seconds
                                      for u in rollup.values()), 6),
            "reservedChipSeconds": round(
                sum(u.reserved_chip_seconds for u in rollup.values()), 6),
            "tenantsAttributed": len(rollup),
        },
    }


FLIGHTREC_TICK_SCHEMA = obj(
    required=["tick", "ts", "durationS"],
    tick=s("integer"),
    ts=s("number"),
    durationS=s("number"),
    admitted=s("integer"),
    prefillChunks=s("integer"),
    decodeSlots=s("integer"),
    slotsBusy=s("integer"),
    queueDepth=s("integer"),
    pagesFree=s("integer"),
    compiles=s("integer"),
    faults=s("integer"),
)


def _flightrec_enabled():
    """404 while the flight recorder is configured off — the live-ring and
    dump endpoints describe a subsystem that does not exist then."""
    from ..config import get_config

    config = get_config()
    if not config.generation.flight_recorder:
        raise NotFoundError(
            "the serving flight recorder is disabled on this manager "
            "([generation_service] flight_recorder in config.toml; "
            "docs/OBSERVABILITY.md)")
    return config


@route("/admin/flightrec", ["GET"], auth="admin",
       summary="Live per-tick flight-recorder ring of the serving engine",
       tag="observability",
       query={"limit": s("integer")},
       responses={200: obj(required=["engineUp", "capacity", "recorded",
                                     "ticks"],
                           engineUp=s("boolean"),
                           capacity=s("integer"),
                           recorded=s("integer"),
                           ticks=arr(FLIGHTREC_TICK_SCHEMA)),
                  404: obj(required=["msg"], msg=s("string"))})
def get_flightrec(context: RequestContext) -> Dict:
    """The engine's in-memory tick ring oldest-first (``?limit=`` keeps
    the newest N); ``engineUp=false`` with an empty ring while no engine
    is published (crashed or serving disabled) — the post-mortem for that
    case is ``GET /api/admin/flightrec/dumps``. 404 while
    ``flight_recorder`` is configured off."""
    from ..serving import get_engine

    _flightrec_enabled()
    engine = get_engine()
    recorder = getattr(engine, "flight_recorder", None)
    if recorder is None:
        return {"engineUp": False, "capacity": 0, "recorded": 0,
                "ticks": []}
    return {
        "engineUp": True,
        "capacity": recorder.capacity,
        "recorded": recorder.recorded,
        "ticks": recorder.snapshot(int_arg(context, "limit")),
    }


@route("/admin/flightrec/dumps", ["GET"], auth="admin",
       summary="Flight-recorder crash dumps written on fatal engine faults",
       tag="observability",
       query={"file": s("string")},
       responses={200: obj(dumps=arr(obj(
                               required=["file"],
                               file=s("string"),
                               writtenTs=s("number"),
                               reason=s("string"),
                               ticks=s("integer"),
                               inFlight=s("integer"),
                               firingAlerts=s("integer"))),
                           schemaVersion=s("integer"),
                           writtenTs=s("number"),
                           reason=s("string"),
                           ticks=arr(FLIGHTREC_TICK_SCHEMA),
                           inFlight=arr({"type": "object",
                                         "additionalProperties": True}),
                           firingAlerts=arr(s("string"))),
                  404: obj(required=["msg"], msg=s("string"))})
def get_flightrec_dumps(context: RequestContext) -> Dict:
    """Without ``?file=``: newest-first summaries of the crash dumps under
    ``{config_dir}/flightrec`` (the supervisor writes one per fatal
    classification, pruned past ``flightrec_dumps``). With ``?file=``: the
    full dump — last-N-tick timeline, the in-flight ledger rows at the
    moment of death, and the alerts firing then."""
    from ..serving.flight_recorder import list_crash_dumps, load_crash_dump

    config = _flightrec_enabled()
    directory = str(config.flightrec_dir)
    name = context.request.args.get("file")
    if name:
        dump = load_crash_dump(directory, name)
        if dump is None:
            raise NotFoundError(f"no crash dump named {name!r}")
        return dump
    return {"dumps": list_crash_dumps(directory)}
