"""Observability controller: metrics exposition + trace dump.

The two read surfaces of tensorhive_tpu/observability:

* ``GET /metrics`` — Prometheus text format (version 0.0.4), unauthenticated
  like a conventional scrape target (it carries latency/count aggregates,
  never user data; JIRIAF-style virtual-kubelet integrations assume exactly
  this per-resource endpoint).
* ``GET /admin/traces`` — recent spans from the ring-buffer tracer,
  admin-auth (span attrs include hostnames and job ids).
"""
from __future__ import annotations

from typing import Dict

from werkzeug.wrappers import Response

from ..api.app import RequestContext, int_arg, route
from ..api.schema import arr, obj, s
from ..observability import get_registry, get_tracer

#: content type Prometheus scrapers negotiate for the text format
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

SPAN_SCHEMA = obj(
    required=["spanId", "name", "kind", "startTs", "status", "seq"],
    spanId=s("string"),
    parentId=s("string", nullable=True),
    name=s("string"),
    kind=s("string"),
    startTs=s("number"),
    durationMs=s("number", nullable=True),
    status=s("string"),
    attrs={"type": "object", "additionalProperties": True},
    seq=s("integer"),
)


@route("/metrics", ["GET"], auth=None,
       summary="Prometheus metrics exposition (text format)",
       tag="observability", responses={200: s("string")})
def get_metrics(context: RequestContext) -> Response:
    return Response(get_registry().render(),
                    content_type=PROMETHEUS_CONTENT_TYPE)


@route("/admin/traces", ["GET"], auth="admin",
       summary="Recent spans from the ring-buffer tracer",
       tag="observability",
       query={"limit": s("integer"), "kind": s("string")},
       responses={200: obj(required=["capacity", "recorded", "spans"],
                           capacity=s("integer"),
                           recorded=s("integer"),
                           spans=arr(SPAN_SCHEMA))})
def get_traces(context: RequestContext) -> Dict:
    """Completed spans oldest-first (monotone ``seq``); ``?limit=`` caps the
    dump, ``?kind=`` filters (api, tick, monitor, transport, probe, job)."""
    tracer = get_tracer()
    limit = int_arg(context, "limit")
    kind = context.request.args.get("kind")
    return {
        "capacity": tracer.capacity,
        "recorded": len(tracer),
        "spans": tracer.recent(limit=limit, kind=kind),
    }
