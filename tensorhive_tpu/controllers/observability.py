"""Observability controller: metrics exposition, trace dump, alert state
and health probes.

The read surfaces of tensorhive_tpu/observability:

* ``GET /metrics`` — Prometheus text format (version 0.0.4), unauthenticated
  like a conventional scrape target (it carries latency/count aggregates,
  never user data; JIRIAF-style virtual-kubelet integrations assume exactly
  this per-resource endpoint).
* ``GET /admin/traces`` — recent spans from the ring-buffer tracer,
  admin-auth (span attrs include hostnames and job ids).
* ``GET /healthz`` / ``GET /readyz`` — liveness and readiness, both
  unauthenticated (an orchestrator's kubelet-style prober has no JWT);
  readiness returns 503 with a JSON reason list when any component fails.
* ``GET /admin/alerts`` — full rule/state dump of the alert engine plus
  the transition history ring, admin-auth.
"""
from __future__ import annotations

from typing import Dict, Tuple

from werkzeug.wrappers import Response

from ..api.app import RequestContext, int_arg, route
from ..api.schema import arr, obj, s
from ..observability import get_registry, get_tracer
from ..observability.alerts import get_alert_engine
from ..observability.health import liveness, readiness

#: content type Prometheus scrapers negotiate for the text format
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

SPAN_SCHEMA = obj(
    required=["spanId", "name", "kind", "startTs", "status", "seq"],
    spanId=s("string"),
    parentId=s("string", nullable=True),
    name=s("string"),
    kind=s("string"),
    startTs=s("number"),
    durationMs=s("number", nullable=True),
    status=s("string"),
    attrs={"type": "object", "additionalProperties": True},
    seq=s("integer"),
)


@route("/metrics", ["GET"], auth=None,
       summary="Prometheus metrics exposition (text format)",
       tag="observability", responses={200: s("string")})
def get_metrics(context: RequestContext) -> Response:
    return Response(get_registry().render(),
                    content_type=PROMETHEUS_CONTENT_TYPE)


@route("/admin/traces", ["GET"], auth="admin",
       summary="Recent spans from the ring-buffer tracer",
       tag="observability",
       query={"limit": s("integer"), "kind": s("string")},
       responses={200: obj(required=["capacity", "recorded", "spans"],
                           capacity=s("integer"),
                           recorded=s("integer"),
                           spans=arr(SPAN_SCHEMA))})
def get_traces(context: RequestContext) -> Dict:
    """Completed spans oldest-first (monotone ``seq``); ``?limit=`` caps the
    dump, ``?kind=`` filters (api, tick, monitor, transport, probe, job)."""
    tracer = get_tracer()
    limit = int_arg(context, "limit")
    kind = context.request.args.get("kind")
    return {
        "capacity": tracer.capacity,
        "recorded": len(tracer),
        "spans": tracer.recent(limit=limit, kind=kind),
    }


HEALTH_COMPONENT_SCHEMA = obj(
    required=["component", "ok"],
    component=s("string"),
    ok=s("boolean"),
    reason=s("string"),
)

ALERT_RULE_SCHEMA = obj(
    required=["name", "severity", "kind", "status"],
    name=s("string"),
    severity=s("string"),
    kind=s("string"),
    metric=s("string", nullable=True),
    labels={"type": "object", "additionalProperties": True},
    op=s("string"),
    threshold=s("number"),
    windowS=s("number"),
    forS=s("number"),
    description=s("string"),
    status=s("string"),
    since=s("number", nullable=True),
    lastValue=s("number", nullable=True),
    firedCount=s("integer"),
)


@route("/healthz", ["GET"], auth=None,
       summary="Liveness probe (process is serving requests)",
       tag="observability",
       responses={200: obj(required=["status", "version", "uptimeS"],
                           status=s("string"),
                           version=s("string"),
                           uptimeS=s("number"))})
def get_healthz(context: RequestContext) -> Dict:
    """Unauthenticated by design: a kubelet-style prober carries no JWT,
    and the payload holds nothing but uptime + build version."""
    return liveness()


@route("/readyz", ["GET"], auth=None,
       summary="Readiness probe (503 + reasons when any component fails)",
       tag="observability",
       responses={200: obj(required=["ready", "components"],
                           ready=s("boolean"),
                           components=arr(HEALTH_COMPONENT_SCHEMA),
                           reasons=arr(s("string"))),
                  503: obj(required=["ready", "components", "reasons"],
                           ready=s("boolean"),
                           components=arr(HEALTH_COMPONENT_SCHEMA),
                           reasons=arr(s("string")))})
def get_readyz(context: RequestContext) -> Tuple[Dict, int]:
    """DB answers a query, every registered service is alive and ticking
    within 3x its interval, the probe round is fresh when hosts are
    managed — any failure 503s with the component named."""
    ready, components = readiness()
    reasons = [f"{c['component']}: {c.get('reason', 'not ok')}"
               for c in components if not c["ok"]]
    return ({"ready": ready, "components": components, "reasons": reasons},
            200 if ready else 503)


@route("/admin/alerts", ["GET"], auth="admin",
       summary="Alert rule/state dump with transition history",
       tag="observability",
       responses={200: obj(required=["rules", "firing", "transitions"],
                           rules=arr(ALERT_RULE_SCHEMA),
                           firing=arr(s("string")),
                           transitions=arr({"type": "object",
                                            "additionalProperties": True}))})
def get_alerts(context: RequestContext) -> Dict:
    """Current engine truth: every rule with its lifecycle status and last
    value, the firing subset, and the bounded transition history ring —
    the same state the `tpuhive_alerts_firing` gauges export."""
    return get_alert_engine().dump()
