"""Generation gateway controller: streaming inference + SLO stats.

The user-facing edge of the continuous-batching engine (docs/SERVING.md):

* ``POST /generate`` — submit a prompt, stream tokens back as NDJSON chunks
  (one JSON object per line) over a chunked response. Admission control is
  explicit: a full queue or a per-user concurrency cap answers **429 with a
  Retry-After header** (load is shed at the edge, never absorbed as
  latency), and a user without an active Restriction covering any resource
  is **403** — the same permission model that gates reservations gates
  inference capacity (Tally-style: fairness enforced outside the model).
* ``GET /generate/stats`` — queue/slot occupancy + TTFT/inter-token
  percentiles for the dashboard serving strip.

Serving disabled (no engine installed) answers 503 on both, so probes and
the SPA can distinguish "off" from "broken".
"""
from __future__ import annotations

import json
import time
from typing import Dict, Optional

from werkzeug.wrappers import Response

from ..api.app import RequestContext, json_body, route
from ..api.schema import arr, obj, s
from ..serving import AdmissionError, EngineDrainingError, get_engine
from ..utils.exceptions import ForbiddenError

#: streaming media type: one JSON object per line, flushed per token
NDJSON_CONTENT_TYPE = "application/x-ndjson"

GENERATE_BODY = obj(
    required=["promptTokens"],
    promptTokens=arr(s("integer")),
    maxNewTokens=s("integer"),
    temperature=s("number"),
    #: per-request deadline override (seconds, capped by
    #: [generation_service] max_deadline_s; omitted = default_deadline_s).
    #: Expiry ends the stream with an honest outcome=timeout done chunk —
    #: in queue, mid-prefill or mid-decode (docs/ROBUSTNESS.md)
    deadlineS=s("number"),
)

STATS_SCHEMA = obj(
    required=["enabled"],
    enabled=s("boolean"),
    slots=s("integer"),
    slotsBusy=s("integer"),
    queueDepth=s("integer"),
    queueCapacity=s("integer"),
    #: drain mode (docs/ROBUSTNESS.md "Serving data plane"): admission is
    #: closed (503 + Retry-After) while in-flight requests finish — the
    #: serving-strip draining badge renders this
    draining=s("boolean"),
    maxSeqLen=s("integer"),
    #: serving mesh layout "dp x tp" (docs/SERVING.md "Multi-chip
    #: serving"); "1x1" = single-chip engine
    meshShape=s("string"),
    numDevices=s("integer"),
    paged=s("boolean"),
    pageSize=s("integer", nullable=True),
    #: which paged decode attention dispatch compiled: "pallas" (the fused
    #: page-table kernel), "xla" (the gather reference) or null (contiguous)
    pagedKernel=s("string", nullable=True),
    kvPagesTotal=s("integer", nullable=True),
    kvPagesFree=s("integer", nullable=True),
    #: int8 KV pages (docs/SERVING.md "Quantized KV pages"): "on"/"off",
    #: and the per-token KV HBM cost across layers (payload + amortized
    #: scale side-arrays; null for the contiguous layout) — the
    #: serving-strip quant badge renders these
    kvQuant=s("string"),
    kvBytesPerToken=s("number", nullable=True),
    #: radix prefix cache (docs/SERVING.md "Prefix cache & chunked
    #: prefill"): "on"/"off", lifetime hit rate and retained page count —
    #: the serving-strip prefix badge renders these
    prefixCache=s("string"),
    prefixHits=s("integer"),
    prefixMisses=s("integer"),
    prefixHitRate=s("number", nullable=True),
    cachedPages=s("integer", nullable=True),
    prefillChunkTokens=s("integer", nullable=True),
    #: KV-page tiering (docs/SERVING.md "KV-page tiering"): host-RAM store
    #: budget, residency and lifetime host hit rate — all null with
    #: host_kv_bytes=0 (the rollback hides the serving-strip tier badge)
    hostKvBytes=s("integer", nullable=True),
    hostPagesResident=s("integer", nullable=True),
    hostBytesUsed=s("integer", nullable=True),
    hostHitRate=s("number", nullable=True),
    #: speculative decoding lane (docs/SERVING.md "Speculative decoding"):
    #: "on"/"off", the per-tick proposal depth, and the lifetime draft
    #: acceptance counters/rate the serving-strip spec badge renders
    speculative=s("string"),
    specTokens=s("integer", nullable=True),
    specProposed=s("integer"),
    specAccepted=s("integer"),
    specAcceptanceRate=s("number", nullable=True),
    requestsCompleted=s("integer"),
    tokensEmitted=s("integer"),
    steps=s("integer"),
    #: tenant accounting (docs/OBSERVABILITY.md "Tenant accounting"):
    #: busy slot-second integral the TenantMeter conserves against —
    #: null while [accounting] is disabled
    busySlotSeconds=s("number", nullable=True),
    ttftP50Ms=s("number", nullable=True),
    ttftP95Ms=s("number", nullable=True),
    intertokenP50Ms=s("number", nullable=True),
    intertokenP95Ms=s("number", nullable=True),
)


def _unavailable_msg() -> str:
    """503 body: a recorded boot failure (e.g. checkpoint shape mismatch —
    docs/SERVING.md "Loading checkpoints") beats the generic disabled
    message, so operators see WHY the plane is down, not just that it is."""
    from ..serving import get_unavailable_reason

    return (get_unavailable_reason()
            or "generation serving is not enabled on this manager "
               "([generation_service] in config.toml)")


#: Retry-After on 503s when the supervisor gave no sharper hint: long
#: enough for an operator restart, short enough that clients re-probe
DEFAULT_UNAVAILABLE_RETRY_AFTER_S = 30


def _service_unavailable(msg: Optional[str] = None,
                         retry_after_s: Optional[float] = None) -> Response:
    """503 with the stored unavailability reason AND an honest Retry-After:
    a restart in progress advertises the supervisor's hint (seconds until
    the rebuild or the crash-loop cooldown expires), anything else the
    conservative default — clients should re-probe, not give up
    (docs/ROBUSTNESS.md 'Serving data plane')."""
    from ..serving import get_serving_state

    if retry_after_s is None:
        retry_after_s = (get_serving_state()["retry_after_s"]
                         or DEFAULT_UNAVAILABLE_RETRY_AFTER_S)
    response = Response(
        json.dumps({"msg": msg or _unavailable_msg(),
                    "retryAfterS": round(float(retry_after_s), 1)}),
        status=503, content_type="application/json")
    response.headers["Retry-After"] = str(max(1, int(retry_after_s)))
    return response


def _rejection(exc: AdmissionError) -> Response:
    """429 with an honest Retry-After (seconds, integral per RFC 9110) and
    the rejection's ledger id — a shed request is still quotable against
    ``GET /api/admin/requests``."""
    response = Response(
        json.dumps({"msg": str(exc),
                    "retryAfterS": round(exc.retry_after_s, 1)}),
        status=429, content_type="application/json")
    response.headers["Retry-After"] = str(max(1, int(exc.retry_after_s)))
    if exc.request_id:
        response.headers["X-Request-Id"] = exc.request_id
    return response


def _check_restriction_gate(context: RequestContext) -> None:
    """Inference capacity rides the reservation permission model: a user
    with no active Restriction (direct, via group, or global) may not pull
    tokens from the shared slot pool. Admins bypass, as everywhere."""
    from ..config import get_config

    if not get_config().generation.require_restriction:
        return
    user = context.current_user()
    if user.has_role("admin"):
        return
    if not any(r.is_active() for r in user.get_restrictions()):
        raise ForbiddenError(
            "no active restriction grants you generation capacity — ask an "
            "admin to attach one (docs/SERVING.md)")


@route("/generate", ["POST"], auth="jwt", tag="generate",
       summary="Stream a model generation (NDJSON chunked response)",
       body=GENERATE_BODY,
       responses={200: s("string"),
                  403: obj(required=["msg"], msg=s("string")),
                  429: obj(required=["msg"], msg=s("string"),
                           retryAfterS=s("number")),
                  503: obj(required=["msg"], msg=s("string"),
                           retryAfterS=s("number"))})
def post_generate(context: RequestContext) -> Response:
    """Submit one prompt to the continuous-batching engine and stream its
    tokens. Response lines: ``{"token": n}`` per generated token, then one
    ``{"done": true, "tokens": [...], "outcome": ..., "ttftMs": ...}``; a
    mid-stream failure emits ``{"error": msg}`` as the final line."""
    engine = get_engine()
    if engine is None:
        return _service_unavailable()
    _check_restriction_gate(context)
    body = json_body(context, "promptTokens")
    prompt = body["promptTokens"]
    max_new = int(body.get("maxNewTokens") or 16)
    temperature = float(body.get("temperature") or 0.0)
    deadline_raw = body.get("deadlineS")
    deadline_s = None if deadline_raw is None else float(deadline_raw)
    from ..config import get_config

    timeout_s = get_config().generation.stream_timeout_s
    try:
        # submit() validates prompt/length/temperature/deadline
        # (ValueError -> 422 via the standard mapping is NOT available
        # here since ValueError isn't typed; map explicitly)
        handle = engine.submit(prompt, max_new_tokens=max_new,
                               temperature=temperature,
                               user_key=str(context.user_id),
                               deadline_s=deadline_s)
    except EngineDrainingError as exc:
        # a drain is not load shedding: the plane is deliberately going
        # quiet, so the answer is 503 (with the drain ETA), not 429
        return _service_unavailable(msg=str(exc),
                                    retry_after_s=exc.retry_after_s)
    except AdmissionError as exc:
        return _rejection(exc)
    except ValueError as exc:
        return Response(json.dumps({"msg": str(exc)}), status=422,
                        content_type="application/json")

    def stream():
        from ..observability import get_tracer

        stream_started = time.time()
        status = "ok"
        try:
            for token in handle.tokens(timeout_s=timeout_s):
                yield json.dumps({"token": token}) + "\n"
            summary = handle.result(timeout_s=timeout_s)
            yield json.dumps({
                "done": True,
                "requestId": summary["requestId"],
                "outcome": summary["outcome"],
                "tokens": summary["tokens"],
                "ttftMs": (round(summary["ttftS"] * 1e3, 3)
                           if summary.get("ttftS") is not None else None),
                "durationMs": round(summary["durationS"] * 1e3, 3),
            }) + "\n"
        except (TimeoutError, RuntimeError) as exc:
            status = "error"
            yield json.dumps({"error": str(exc)}) + "\n"
        finally:
            # a client that disconnects mid-stream must not leak its slot:
            # generator close cancels the request (no-op when finished)
            handle.cancel()
            # the streaming phase outlives the api dispatch span (werkzeug
            # iterates this generator after dispatch returns), so it gets
            # its own request_id-labelled span — the fourth phase of the
            # ledger's queue/prefill/decode story
            get_tracer().record_span(
                "generate.stream", kind="generate",
                start_ts=stream_started,
                duration_s=time.time() - stream_started,
                status=status, request_id=handle.request_id)

    return Response(stream(), content_type=NDJSON_CONTENT_TYPE,
                    headers={"X-Accel-Buffering": "no",
                             "Cache-Control": "no-cache",
                             # quotable against /api/admin/requests and the
                             # request_id-labelled spans in /api/admin/traces
                             "X-Request-Id": handle.request_id})


@route("/generate/stats", ["GET"], auth="jwt", tag="generate",
       summary="Serving SLO snapshot (queue, slots, latency percentiles)",
       responses={200: STATS_SCHEMA,
                  503: obj(required=["enabled", "msg"],
                           enabled=s("boolean"), msg=s("string"))})
def get_generate_stats(context: RequestContext):
    """Queue depth, slot occupancy and TTFT/inter-token p50/p95 — the same
    numbers the ``generate_*`` alert rules and the dashboard strip read."""
    engine = get_engine()
    if engine is None:
        return ({"enabled": False, "msg": _unavailable_msg()}, 503)
    stats: Dict[str, Optional[float]] = {"enabled": True}
    stats.update(engine.stats())
    return stats


DRAIN_SCHEMA = obj(
    required=["draining", "inFlight"],
    draining=s("boolean"),
    #: requests still queued or running (what the drain is waiting on)
    inFlight=s("integer"),
    #: the Retry-After estimate new requests are being answered with
    retryAfterS=s("number"),
)


@route("/admin/generate/drain", ["POST"], auth="admin", tag="generate",
       summary="Drain the serving plane (stop admission, finish in-flight)",
       responses={200: DRAIN_SCHEMA,
                  503: obj(required=["msg"], msg=s("string"),
                           retryAfterS=s("number"))})
def post_generate_drain(context: RequestContext):
    """Graceful drain (docs/ROBUSTNESS.md "Serving data plane"): admission
    closes — new ``POST /api/generate`` requests answer 503 with an honest
    Retry-After — while everything queued or running keeps finishing
    through the live pump. ``draining`` surfaces in ``/api/generate/stats``
    and flips ``/api/readyz`` so orchestrators stop routing here.
    Idempotent; ``POST /api/admin/generate/resume`` reopens admission."""
    engine = get_engine()
    if engine is None:
        return _service_unavailable()
    engine.drain()
    stats = engine.stats()
    return {"draining": True,
            "inFlight": stats["slotsBusy"] + stats["queueDepth"],
            "retryAfterS": engine.drain_retry_after()}


@route("/admin/generate/resume", ["POST"], auth="admin", tag="generate",
       summary="Reopen admission after a drain",
       responses={200: DRAIN_SCHEMA,
                  503: obj(required=["msg"], msg=s("string"),
                           retryAfterS=s("number"))})
def post_generate_resume(context: RequestContext):
    """Undo a drain: admission reopens immediately. Idempotent."""
    engine = get_engine()
    if engine is None:
        return _service_unavailable()
    engine.resume()
    stats = engine.stats()
    return {"draining": False,
            "inFlight": stats["slotsBusy"] + stats["queueDepth"],
            "retryAfterS": 0.0}
