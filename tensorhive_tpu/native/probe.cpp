// tpuhive-probe: native node-telemetry probe (schema v1).
//
// Emits exactly one JSON line on stdout describing this host:
//   {"v":1,"chips":[...],"procs":{...},"cpu":{...},"mem":{...},"metrics":{...}}
// The schema is defined in tensorhive_tpu/core/monitors/probe.py, which also
// carries an equivalent inline-Python fallback — change both together.
//
// This binary is the TPU-native analog of the reference's nvidia-smi
// dependency (tensorhive/core/monitors/GPUMonitor.py builds nvidia-smi
// query/pmon command lines; tensorhive/core/utils/NvidiaSmiParser.py parses
// them): accelerator inventory comes from /dev/accel* (TPU VM kernel driver)
// or /dev/vfio/*, per-chip holder PIDs from a /proc/*/fd scan (the libtpu
// device lock means the holder IS the workload — SURVEY.md §7 "process
// adoption & exclusive enforcement"), process owners from /proc/<pid> uid,
// CPU/memory from /proc/stat + /proc/meminfo, and HBM/duty-cycle runtime
// counters from ~/.tpuhive/metrics/*.json drop-files published by the
// workload-side telemetry emitter.
//
// No third-party dependencies; C++17 + POSIX only. Typical runtime is a few
// milliseconds, vs ~2 s for a cold python3 interpreter — the difference is
// the monitoring tick's p50 latency (BASELINE.md north-star metric).

#include <dirent.h>
#include <errno.h>
#include <pwd.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <map>
#include <set>
#include <sstream>
#include <string>
#include <vector>

namespace {

std::string json_escape(const std::string& in) {
  std::string out;
  out.reserve(in.size() + 8);
  for (unsigned char c : in) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += static_cast<char>(c);
        }
    }
  }
  return out;
}

std::vector<std::string> list_dir(const std::string& path) {
  std::vector<std::string> names;
  DIR* dir = ::opendir(path.c_str());
  if (!dir) return names;
  while (dirent* ent = ::readdir(dir)) {
    if (std::strcmp(ent->d_name, ".") != 0 && std::strcmp(ent->d_name, "..") != 0)
      names.emplace_back(ent->d_name);
  }
  ::closedir(dir);
  std::sort(names.begin(), names.end());
  return names;
}

bool all_digits(const std::string& s) {
  return !s.empty() &&
         std::all_of(s.begin(), s.end(), [](unsigned char c) { return std::isdigit(c); });
}

std::string read_link(const std::string& path) {
  char buf[4096];
  ssize_t n = ::readlink(path.c_str(), buf, sizeof buf - 1);
  if (n < 0) return {};
  buf[n] = '\0';
  return buf;
}

std::string real_path(const std::string& path) {
  char buf[4096];
  if (::realpath(path.c_str(), buf) == nullptr) return path;
  return buf;
}

// Accelerator device nodes: /dev/accel<N> (TPU v4+/v5 "accel" driver) or
// /dev/vfio/<N> (older vfio-based stacks). Order defines chip index.
std::vector<std::string> accelerator_devices() {
  std::vector<std::string> devs;
  for (const auto& name : list_dir("/dev")) {
    if (name.rfind("accel", 0) == 0 && all_digits(name.substr(5)))
      devs.push_back("/dev/" + name);
  }
  for (const auto& name : list_dir("/dev/vfio")) {
    if (all_digits(name)) devs.push_back("/dev/vfio/" + name);
  }
  return devs;
}

// pid -> set of chip indexes, found by resolving every /proc/*/fd symlink
// against the device-node real paths (analog of `nvidia-smi pmon`).
// /proc/<pid>/fd is only readable for same-uid processes unless the probe
// runs privileged (root / CAP_SYS_PTRACE); unreadable candidates are counted
// into *restricted so the monitor can surface that ownership data is
// incomplete — probe_command() therefore attempts `sudo -n` first.
std::map<int, std::set<int>> device_holders(const std::vector<std::string>& devs,
                                            int* restricted) {
  std::map<std::string, int> dev_index;
  for (size_t i = 0; i < devs.size(); ++i) dev_index[real_path(devs[i])] = static_cast<int>(i);
  std::map<int, std::set<int>> holders;
  if (dev_index.empty()) return holders;
  for (const auto& pid_name : list_dir("/proc")) {
    if (!all_digits(pid_name)) continue;
    const std::string fd_dir = "/proc/" + pid_name + "/fd";
    DIR* dir = ::opendir(fd_dir.c_str());
    if (!dir) {
      if (errno == EACCES) ++*restricted;
      continue;
    }
    while (dirent* ent = ::readdir(dir)) {
      if (ent->d_name[0] == '.') continue;
      const std::string target = read_link(fd_dir + "/" + ent->d_name);
      auto it = dev_index.find(target);
      if (it != dev_index.end()) holders[std::stoi(pid_name)].insert(it->second);
    }
    ::closedir(dir);
  }
  return holders;
}

struct ProcInfo {
  std::string user;
  std::string cmd;
};

bool proc_info(int pid, ProcInfo* out) {
  const std::string base = "/proc/" + std::to_string(pid);
  std::ifstream cmdline(base + "/cmdline", std::ios::binary);
  if (!cmdline) return false;
  std::string raw((std::istreambuf_iterator<char>(cmdline)),
                  std::istreambuf_iterator<char>());
  std::replace(raw.begin(), raw.end(), '\0', ' ');
  while (!raw.empty() && raw.back() == ' ') raw.pop_back();
  out->cmd = raw;
  struct stat st {};
  if (::stat(base.c_str(), &st) != 0) return false;
  if (passwd* pw = ::getpwuid(st.st_uid)) {
    out->user = pw->pw_name;
  } else {
    out->user = std::to_string(st.st_uid);
  }
  return true;
}

struct CpuSample {
  long long total = -1, idle = -1;
  int ncpu = 1;
};

CpuSample cpu_sample() {
  CpuSample s;
  std::ifstream stat("/proc/stat");
  std::string label;
  if (stat >> label && label == "cpu") {
    long long v, total = 0, idle = 0;
    int field = 0;
    while (stat.peek() != '\n' && stat >> v) {
      total += v;
      if (field == 3 || field == 4) idle += v;  // idle + iowait
      ++field;
    }
    if (field >= 4) {
      s.total = total;
      s.idle = idle;
    }
  }
  long n = ::sysconf(_SC_NPROCESSORS_ONLN);
  s.ncpu = n > 0 ? static_cast<int>(n) : 1;
  return s;
}

struct MemSample {
  long long total_kb = 0, avail_kb = 0;
};

MemSample mem_sample() {
  MemSample m;
  std::ifstream info("/proc/meminfo");
  std::string key;
  long long value;
  std::string unit;
  long long mem_free = 0;
  bool has_avail = false;
  while (info >> key >> value) {
    std::getline(info, unit);
    if (key == "MemTotal:") m.total_kb = value;
    else if (key == "MemAvailable:") { m.avail_kb = value; has_avail = true; }
    else if (key == "MemFree:") mem_free = value;
  }
  if (!has_avail) m.avail_kb = mem_free;
  return m;
}

// --- runtime-metric drop-files ---------------------------------------------
// Each ~/.tpuhive/metrics/*.json holds {"<chip_index>": {<metrics>}, ...}.
// We split the top level without a full JSON parser (depth/str tracking),
// inject "age_s" into each per-chip object, and merge across files in
// lexicographic order (later files win), matching the Python fallback.

size_t skip_string(const std::string& s, size_t i) {  // i at opening quote
  for (++i; i < s.size(); ++i) {
    if (s[i] == '\\') ++i;
    else if (s[i] == '"') return i + 1;
  }
  return s.size();
}

// Minimal recursive-descent JSON validator. Drop-file content is spliced
// verbatim into this probe's own output, so anything unparseable must be
// rejected here — one corrupt metrics file must not invalidate the whole
// telemetry line (the Python fallback gets this for free from json.load).
bool skip_ws(const std::string& s, size_t& i) {
  while (i < s.size() && std::isspace(static_cast<unsigned char>(s[i]))) ++i;
  return i < s.size();
}

bool valid_value(const std::string& s, size_t& i, int depth);

bool valid_literal(const std::string& s, size_t& i, const char* word) {
  size_t n = std::strlen(word);
  if (s.compare(i, n, word) != 0) return false;
  i += n;
  return true;
}

bool valid_number(const std::string& s, size_t& i) {
  size_t start = i;
  if (i < s.size() && s[i] == '-') ++i;
  while (i < s.size() && (std::isdigit(static_cast<unsigned char>(s[i])) ||
                          s[i] == '.' || s[i] == 'e' || s[i] == 'E' ||
                          s[i] == '+' || s[i] == '-'))
    ++i;
  return i > start;
}

bool valid_string(const std::string& s, size_t& i) {
  if (i >= s.size() || s[i] != '"') return false;
  for (++i; i < s.size(); ++i) {
    if (s[i] == '\\') { ++i; continue; }
    if (s[i] == '"') { ++i; return true; }
  }
  return false;  // unterminated
}

bool valid_container(const std::string& s, size_t& i, int depth, char open, char close) {
  if (depth > 64 || i >= s.size() || s[i] != open) return false;
  ++i;
  if (!skip_ws(s, i)) return false;
  if (s[i] == close) { ++i; return true; }
  while (true) {
    if (open == '{') {
      if (!skip_ws(s, i) || !valid_string(s, i)) return false;
      if (!skip_ws(s, i) || s[i] != ':') return false;
      ++i;
    }
    if (!valid_value(s, i, depth + 1)) return false;
    if (!skip_ws(s, i)) return false;
    if (s[i] == ',') { ++i; continue; }
    if (s[i] == close) { ++i; return true; }
    return false;
  }
}

bool valid_value(const std::string& s, size_t& i, int depth) {
  if (!skip_ws(s, i)) return false;
  switch (s[i]) {
    case '{': return valid_container(s, i, depth, '{', '}');
    case '[': return valid_container(s, i, depth, '[', ']');
    case '"': return valid_string(s, i);
    case 't': return valid_literal(s, i, "true");
    case 'f': return valid_literal(s, i, "false");
    case 'n': return valid_literal(s, i, "null");
    default: return valid_number(s, i);
  }
}

bool valid_json_document(const std::string& s) {
  size_t i = 0;
  if (!valid_value(s, i, 0)) return false;
  skip_ws(s, i);
  return i == s.size();
}

bool split_top_level(const std::string& text,
                     std::vector<std::pair<std::string, std::string>>* out) {
  size_t i = text.find('{');
  if (i == std::string::npos) return false;
  ++i;
  while (i < text.size()) {
    while (i < text.size() && (std::isspace(static_cast<unsigned char>(text[i])) || text[i] == ','))
      ++i;
    if (i >= text.size() || text[i] == '}') return true;
    if (text[i] != '"') return false;
    size_t key_end = skip_string(text, i);
    std::string key = text.substr(i + 1, key_end - i - 2);
    i = key_end;
    while (i < text.size() && (std::isspace(static_cast<unsigned char>(text[i])) || text[i] == ':'))
      ++i;
    size_t value_start = i;
    int depth = 0;
    while (i < text.size()) {
      char c = text[i];
      if (c == '"') { i = skip_string(text, i); continue; }
      if (c == '{' || c == '[') ++depth;
      else if (c == '}' || c == ']') {
        if (depth == 0) break;
        --depth;
        if (depth == 0) { ++i; break; }
      } else if (c == ',' && depth == 0) {
        break;
      }
      ++i;
    }
    out->emplace_back(key, text.substr(value_start, i - value_start));
  }
  return true;
}

// --metrics-dir <path> lets `sudo -n` invocations keep reading the
// monitoring user's drop-files ($HOME flips to /root under sudo). An argv
// flag instead of an env assignment because default sudoers (no SETENV
// tag) rejects `sudo VAR=... cmd` outright.
std::string g_metrics_dir_override;

std::map<std::string, std::string> runtime_metrics() {
  std::map<std::string, std::string> merged;
  std::string dir;
  if (!g_metrics_dir_override.empty()) {
    dir = g_metrics_dir_override;
  } else if (const char* override_dir = std::getenv("TPUHIVE_METRICS_DIR")) {
    dir = override_dir;
  } else if (const char* home = std::getenv("HOME")) {
    dir = std::string(home) + "/.tpuhive/metrics";
  } else {
    return merged;
  }
  const time_t now = ::time(nullptr);
  for (const auto& name : list_dir(dir)) {
    if (name.size() < 5 || name.substr(name.size() - 5) != ".json") continue;
    const std::string path = dir + "/" + name;
    struct stat st {};
    if (::stat(path.c_str(), &st) != 0) continue;
    std::ifstream fh(path);
    if (!fh) continue;
    std::string text((std::istreambuf_iterator<char>(fh)),
                     std::istreambuf_iterator<char>());
    if (!valid_json_document(text)) continue;  // corrupt/half-written file
    std::vector<std::pair<std::string, std::string>> entries;
    if (!split_top_level(text, &entries)) continue;
    const double age = ::difftime(now, st.st_mtime);
    char age_buf[48];
    std::snprintf(age_buf, sizeof age_buf, "\"age_s\":%.1f", age < 0 ? 0.0 : age);
    for (auto& [key, value] : entries) {
      if (value.empty() || value.front() != '{') continue;  // chip metrics must be objects
      std::string injected = value;
      size_t brace = injected.find('{');
      bool empty_obj = injected.find_first_not_of(" \t\r\n", brace + 1) != std::string::npos &&
                       injected[injected.find_first_not_of(" \t\r\n", brace + 1)] == '}';
      injected.insert(brace + 1, std::string(age_buf) + (empty_obj ? "" : ","));
      merged[key] = injected;
    }
  }
  return merged;
}

// --- kernel/runtime utilization counters (sysfs) ---------------------------
// Per-chip utilization for workloads that never import the framework's
// telemetry emitter (the reference reads ANY process's utilization from the
// driver via nvidia-smi, GPUMonitor.py:20-48): when the platform's TPU
// kernel driver / runtime exports per-accel counters under
// /sys/class/accel/accel<N>/device/ (tpu-info-style runtime metrics), read
// them directly. These are authoritative over drop-files — a chip-level
// counter sees intruders and external jobs that self-reporting never will.
// Absence is LOUD, not silent: the top-level "sysfs_status" key reports
// "ok" when at least one per-chip counter was read and "absent" otherwise —
// on a fleet, a misconfigured driver yielding blind any-workload
// utilization must be distinguishable from an idle chip (VERDICT r3 weak #7).
std::string g_sysfs_dir_override;
std::string g_sysfs_status = "absent";

double read_numeric_file(const std::string& path, bool* ok) {
  std::ifstream fh(path);
  double value = 0.0;
  *ok = static_cast<bool>(fh >> value);
  return value;
}

std::map<std::string, std::string> sysfs_metrics() {
  std::map<std::string, std::string> per_chip;
  std::string dir;
  if (!g_sysfs_dir_override.empty()) {
    dir = g_sysfs_dir_override;
  } else if (const char* override_dir = std::getenv("TPUHIVE_SYSFS_DIR")) {
    dir = override_dir;
  } else {
    dir = "/sys/class/accel";
  }
  static const char* kFields[] = {"duty_cycle_pct", "hbm_used_bytes",
                                  "hbm_total_bytes"};
  for (const auto& name : list_dir(dir)) {
    if (name.rfind("accel", 0) != 0) continue;
    const std::string index = name.substr(5);
    if (index.empty() ||
        !std::all_of(index.begin(), index.end(),
                     [](unsigned char c) { return std::isdigit(c); }))
      continue;
    std::ostringstream obj;
    bool any = false;
    for (const char* field : kFields) {
      bool ok = false;
      const double value =
          read_numeric_file(dir + "/" + name + "/device/" + field, &ok);
      if (!ok) continue;
      if (any) obj << ',';
      char buf[64];
      // byte counters must round-trip exactly (%.6g would truncate 2^34)
      if (value == static_cast<long long>(value))
        std::snprintf(buf, sizeof buf, "\"%s\":%lld", field,
                      static_cast<long long>(value));
      else
        std::snprintf(buf, sizeof buf, "\"%s\":%.10g", field, value);
      obj << buf;
      any = true;
    }
    if (any) per_chip[index] = "{" + obj.str() + "}";
  }
  if (!per_chip.empty()) g_sysfs_status = "ok";
  return per_chip;
}

}  // namespace

int main(int argc, char** argv) {
  for (int i = 1; i < argc - 1; ++i) {
    if (std::string(argv[i]) == "--metrics-dir") g_metrics_dir_override = argv[i + 1];
    if (std::string(argv[i]) == "--sysfs-dir") g_sysfs_dir_override = argv[i + 1];
  }
  const auto devs = accelerator_devices();
  int restricted = 0;
  const auto holders = device_holders(devs, &restricted);

  // invert: chip index -> pids
  std::map<int, std::vector<int>> chip_pids;
  std::set<int> all_pids;
  for (const auto& [pid, chips] : holders) {
    for (int chip : chips) chip_pids[chip].push_back(pid);
    all_pids.insert(pid);
  }

  std::ostringstream out;
  out << "{\"v\":1,\"chips\":[";
  for (size_t i = 0; i < devs.size(); ++i) {
    if (i) out << ',';
    out << "{\"index\":" << i << ",\"dev\":\"" << json_escape(devs[i]) << "\",\"pids\":[";
    auto it = chip_pids.find(static_cast<int>(i));
    if (it != chip_pids.end()) {
      for (size_t j = 0; j < it->second.size(); ++j) {
        if (j) out << ',';
        out << it->second[j];
      }
    }
    out << "]}";
  }
  out << "],\"procs\":{";
  bool first = true;
  for (int pid : all_pids) {
    ProcInfo info;
    if (!proc_info(pid, &info)) continue;
    if (!first) out << ',';
    first = false;
    out << "\"" << pid << "\":{\"user\":\"" << json_escape(info.user)
        << "\",\"cmd\":\"" << json_escape(info.cmd) << "\"}";
  }
  out << "},\"cpu\":";
  const CpuSample cpu = cpu_sample();
  if (cpu.total >= 0) {
    out << "{\"total\":" << cpu.total << ",\"idle\":" << cpu.idle
        << ",\"ncpu\":" << cpu.ncpu << "}";
  } else {
    out << "{}";
  }
  const MemSample mem = mem_sample();
  out << ",\"mem\":{\"total_kb\":" << mem.total_kb << ",\"avail_kb\":" << mem.avail_kb << "}";
  out << ",\"metrics\":{";
  first = true;
  for (const auto& [key, value] : runtime_metrics()) {
    if (!first) out << ',';
    first = false;
    out << "\"" << json_escape(key) << "\":" << value;
  }
  out << "},\"sysfs_metrics\":{";
  first = true;
  for (const auto& [key, value] : sysfs_metrics()) {
    if (!first) out << ',';
    first = false;
    out << "\"" << json_escape(key) << "\":" << value;
  }
  out << "},\"sysfs_status\":\"" << g_sysfs_status << "\"";
  out << ",\"restricted\":" << restricted << "}";
  std::puts(out.str().c_str());
  return 0;
}
