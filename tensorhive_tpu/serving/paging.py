"""Block-paged KV cache bookkeeping: the host side of paged attention.

PR 6's slot engine allocates one contiguous ``[layers, slots, max_len, ...]``
cache, so every slot pays ``max_len`` HBM whether its request uses 16 tokens
or 2000 — pool size is welded to context length. This module decouples them
(ROADMAP item 1): the cache becomes a pool of fixed-size *pages* of
``page_size`` token positions each, and a slot owns only the pages its
request actually needs (``ceil((prompt + max_new) / page_size)``), handed
out from a host-side free list at admission and recycled the moment the
slot leaves.

Everything here is host-side numpy — deliberately jax-free, like the
package root: the allocator is pure bookkeeping that tests exercise without
a device, and the engine ships its ``page_table`` array to the device as a
*traced operand* of the paged step/prefill executables (page assignment
must never be a shape, or every admission would recompile —
docs/SERVING.md "Paged KV cache").

Physical page 0 is the **trash page**: it is never handed out, and a freed
slot's page-table row resets to it, so the parked slot's masked garbage
writes (see engine docstring — parked slots keep stepping) land somewhere
no live sequence ever reads. Without it, a parked slot would keep writing
through page-table entries whose pages may already belong to a *new*
request — the one corruption mode paging introduces over the contiguous
layout.

Pages are all the same size, so the pool cannot fragment: any ``n`` free
pages satisfy any ``n``-page request regardless of allocation history
(pinned by test_paging.py's churn test). Concurrency: the pool is NOT
internally locked — the engine mutates it only under its own lock / from
its single pump thread, the same discipline as the per-slot operand arrays.
"""
from __future__ import annotations

from typing import List

import numpy as np

#: physical index of the write sink for parked slots; never allocated
TRASH_PAGE = 0


class PagePool:
    """Fixed-size page allocator + per-slot page tables.

    ``num_pages`` usable pages (physical indices ``1..num_pages`` — index 0
    is the trash page), each covering ``page_size`` consecutive token
    positions of one sequence. ``page_table`` is the ``[slots,
    max_pages_per_slot]`` int32 array the paged executables consume: row
    ``s``, entry ``j`` is the physical page holding slot ``s``'s logical
    positions ``j*page_size .. (j+1)*page_size-1``; unassigned entries
    point at the trash page (they are masked out of attention by the
    ``<= position`` mask long before they could matter, because a slot's
    position never enters a page that was not assigned first).
    """

    def __init__(self, num_pages: int, page_size: int, slots: int,
                 max_pages_per_slot: int, trash_pages: int = 1) -> None:
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_pages_per_slot < 1:
            raise ValueError(
                f"max_pages_per_slot must be >= 1, got {max_pages_per_slot}")
        if trash_pages < 1:
            raise ValueError(
                f"trash_pages must be >= 1, got {trash_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.slots = int(slots)
        self.max_pages_per_slot = int(max_pages_per_slot)
        #: physical rows reserved ahead of the usable pool. 1 everywhere
        #: except the dp-sharded serving mesh, which reserves ``dp`` rows so
        #: the physical-pages axis (trash + usable) stays divisible by dp —
        #: jax refuses uneven NamedShardings, and padding with extra trash
        #: rows costs dp-1 pages of HBM instead of a layout change. Parked
        #: rows still reset to TRASH_PAGE (= 0); the extra reserved rows are
        #: simply never referenced by any table.
        self.trash_pages = int(trash_pages)
        # LIFO free list: recently-used pages are reissued first (their
        # cache lines are warm, and reuse-after-free is exercised hardest).
        # Usable physical pages are trash_pages .. trash_pages+num_pages-1.
        self._free: List[int] = list(
            range(self.trash_pages + self.num_pages - 1,
                  self.trash_pages - 1, -1))
        self._owned: List[List[int]] = [[] for _ in range(self.slots)]
        self.page_table = np.full((self.slots, self.max_pages_per_slot),
                                  TRASH_PAGE, np.int32)

    @property
    def physical_pages(self) -> int:
        """Rows of the physical cache array: reserved trash + usable."""
        return self.trash_pages + self.num_pages

    # -- sizing ------------------------------------------------------------
    def pages_for(self, tokens: int) -> int:
        """Pages a ``tokens``-position sequence occupies (ceil division)."""
        if tokens < 1:
            raise ValueError(f"tokens must be >= 1, got {tokens}")
        return -(-tokens // self.page_size)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def owned_count(self, slot: int) -> int:
        return len(self._owned[slot])

    def saturation(self) -> float:
        """Fraction of the pool in use — 1.0 is the kv_pages_exhausted
        alert condition."""
        return self.used_pages / self.num_pages

    # -- allocation --------------------------------------------------------
    def assign(self, slot: int, pages: int) -> bool:
        """Move ``pages`` pages from the free list to ``slot`` and fill its
        page-table row. Returns False (taking nothing) when the pool cannot
        satisfy the request — partial grants would deadlock admission.
        Raises on a slot that already holds pages (a free-slot invariant
        violation, never load)."""
        if not 0 < pages <= self.max_pages_per_slot:
            raise ValueError(
                f"pages must be in [1, {self.max_pages_per_slot}], "
                f"got {pages}")
        if self._owned[slot]:
            raise ValueError(
                f"slot {slot} already owns {len(self._owned[slot])} pages; "
                "release before reassigning")
        if pages > len(self._free):
            return False
        granted = [self._free.pop() for _ in range(pages)]
        self._owned[slot] = granted
        self.page_table[slot, :pages] = granted
        return True

    def release(self, slot: int) -> int:
        """Return ``slot``'s pages to the free list and point its whole
        page-table row back at the trash page; idempotent (releasing an
        empty slot is a no-op returning 0)."""
        granted = self._owned[slot]
        self._owned[slot] = []
        self._free.extend(reversed(granted))
        self.page_table[slot, :] = TRASH_PAGE
        return len(granted)
