"""Block-paged KV cache bookkeeping: the host side of paged attention.

PR 6's slot engine allocates one contiguous ``[layers, slots, max_len, ...]``
cache, so every slot pays ``max_len`` HBM whether its request uses 16 tokens
or 2000 — pool size is welded to context length. This module decouples them
(ROADMAP item 1): the cache becomes a pool of fixed-size *pages* of
``page_size`` token positions each, and a slot owns only the pages its
request actually needs (``ceil((prompt + max_new) / page_size)``), handed
out from a host-side free list at admission and recycled the moment the
slot leaves.

Pages are REFCOUNTED (PR 11): the prefix cache
(:mod:`tensorhive_tpu.serving.prefix_cache`) maps shared prompt prefixes to
physical page runs, so one page can back many slots at once — each slot's
grant and each radix-tree node holds one reference, and a page returns to
the free list only when the last reference drops. A slot leaving therefore
frees only its *net-releasable* pages; pages still shared with other slots
(or retained by the tree for future joiners) stay allocated. With no
sharing in play every refcount is 1 and the pool behaves exactly like the
PR 7 allocator — the ``prefix_cache=off`` rollback contract.

Everything here is host-side numpy — deliberately jax-free, like the
package root: the allocator is pure bookkeeping that tests exercise without
a device, and the engine ships its ``page_table`` array to the device as a
*traced operand* of the paged step/prefill executables (page assignment
must never be a shape, or every admission would recompile —
docs/SERVING.md "Paged KV cache").

Physical page 0 is the **trash page**: it is never handed out, and a freed
slot's page-table row resets to it, so the parked slot's masked garbage
writes (see engine docstring — parked slots keep stepping) land somewhere
no live sequence ever reads. Without it, a parked slot would keep writing
through page-table entries whose pages may already belong to a *new*
request — the one corruption mode paging introduces over the contiguous
layout.

Pages are all the same size, so the pool cannot fragment: any ``n`` free
pages satisfy any ``n``-page request regardless of allocation history
(pinned by test_paging.py's churn test). Concurrency: the pool is NOT
internally locked — the engine mutates it only under its own lock / from
its single pump thread, the same discipline as the per-slot operand arrays.

Quantized engines (``kv_quant = on`` — docs/SERVING.md "Quantized KV
pages") pair every physical page with a per-kv-head f32 scale row in the
cache pytree's side-arrays (``ops/kv_quant.py``), indexed by the SAME
physical ids this allocator hands out; the allocator itself is unchanged —
a page is a page whatever its cells are made of, so refcounts, sharing and
the churn invariant carry over verbatim. ``release`` deliberately does NOT
scrub scales (that would cost a device dispatch per leave): the quantizer's
offset-0 rebase rule makes a recycled page behave byte-identically to a
fresh one anyway. Byte-level accounting (the ``tpuhive_generate_kv_bytes_
capacity`` / ``_used`` gauges) lives with the engine, which knows the cell
width; this module keeps counting pages.

KV-page TIERING (docs/SERVING.md "KV-page tiering") adds a third place a
page's *payload* can live: :class:`HostPageStore` is a bounded host-RAM
ring of demoted int8 pages plus their per-(page, kv_head) scales, keyed by
the radix tree's token-tuple content key. A page the pool is about to
recycle (an LRU-evicted cache-only radix leaf, or a drained slot's last
reference) spills its bytes host-side instead of being dropped; the next
radix hit promotes them back through the engine's async copy lane
(:class:`HostCopyLane`) — "recompute the prefill" becomes "DMA the pages
back". The pool itself never changes: a demoted page's PHYSICAL page was
freed normally, and promotion allocates a fresh physical page like any
miss — tier membership is host bookkeeping, so the pool invariant extends
to ``free + live == num_pages`` with ``store.resident_pages`` counted on
both sides (pinned by the tiering churn test). Store reads/writes happen
only on the engine's pump thread (under its lock where bookkeeping
requires), the same single-writer discipline as the allocator.
"""
from __future__ import annotations

import queue as queue_module
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

#: physical index of the write sink for parked slots; never allocated
TRASH_PAGE = 0


class PagePool:
    """Fixed-size page allocator + per-slot page tables + refcounts.

    ``num_pages`` usable pages (physical indices ``1..num_pages`` — index 0
    is the trash page), each covering ``page_size`` consecutive token
    positions of one sequence. ``page_table`` is the ``[slots,
    max_pages_per_slot]`` int32 array the paged executables consume: row
    ``s``, entry ``j`` is the physical page holding slot ``s``'s logical
    positions ``j*page_size .. (j+1)*page_size-1``; unassigned entries
    point at the trash page (they are masked out of attention by the
    ``<= position`` mask long before they could matter, because a slot's
    position never enters a page that was not assigned first).

    A page is either FREE (refcount 0, on the free list) or LIVE (refcount
    = number of slot grants + at most one prefix-cache reference holding
    it). The invariant ``free_pages + live_pages == num_pages`` holds after
    every operation (pinned by the prefix-cache churn property test).
    """

    def __init__(self, num_pages: int, page_size: int, slots: int,
                 max_pages_per_slot: int, trash_pages: int = 1) -> None:
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_pages_per_slot < 1:
            raise ValueError(
                f"max_pages_per_slot must be >= 1, got {max_pages_per_slot}")
        if trash_pages < 1:
            raise ValueError(
                f"trash_pages must be >= 1, got {trash_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.slots = int(slots)
        self.max_pages_per_slot = int(max_pages_per_slot)
        #: physical rows reserved ahead of the usable pool. 1 everywhere
        #: except the dp-sharded serving mesh, which reserves ``dp`` rows so
        #: the physical-pages axis (trash + usable) stays divisible by dp —
        #: jax refuses uneven NamedShardings, and padding with extra trash
        #: rows costs dp-1 pages of HBM instead of a layout change. Parked
        #: rows still reset to TRASH_PAGE (= 0); the extra reserved rows are
        #: simply never referenced by any table.
        self.trash_pages = int(trash_pages)
        # LIFO free list: recently-used pages are reissued first (their
        # cache lines are warm, and reuse-after-free is exercised hardest).
        # Usable physical pages are trash_pages .. trash_pages+num_pages-1.
        self._free: List[int] = list(
            range(self.trash_pages + self.num_pages - 1,
                  self.trash_pages - 1, -1))
        self._owned: List[List[int]] = [[] for _ in range(self.slots)]
        #: per physical page: slot grants + prefix-cache references
        self._refcounts = np.zeros(self.trash_pages + self.num_pages,
                                   np.int32)
        self.page_table = np.full((self.slots, self.max_pages_per_slot),
                                  TRASH_PAGE, np.int32)

    @property
    def physical_pages(self) -> int:
        """Rows of the physical cache array: reserved trash + usable."""
        return self.trash_pages + self.num_pages

    # -- sizing ------------------------------------------------------------
    def pages_for(self, tokens: int) -> int:
        """Pages a ``tokens``-position sequence occupies (ceil division)."""
        if tokens < 1:
            raise ValueError(f"tokens must be >= 1, got {tokens}")
        return -(-tokens // self.page_size)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def live_pages(self) -> int:
        """Pages with at least one reference (slot grant or prefix-cache
        retention) — the complement of the free list."""
        return int((self._refcounts > 0).sum())

    def refcount(self, page: int) -> int:
        return int(self._refcounts[page])

    def owned_count(self, slot: int) -> int:
        return len(self._owned[slot])

    def owned_pages(self, slot: int) -> List[int]:
        """The slot's granted pages in logical order (a copy)."""
        return list(self._owned[slot])

    def slot_ref_counts(self) -> Dict[int, int]:
        """page -> number of slots currently holding a grant on it. A live
        page absent from this map is held only by the prefix cache, i.e.
        evictable the moment admission needs it."""
        counts: Dict[int, int] = {}
        for owned in self._owned:
            for page in owned:
                counts[page] = counts.get(page, 0) + 1
        return counts

    def cached_only_pages(self) -> int:
        """Live pages held ONLY by the prefix cache (no slot grant) — the
        evictable headroom admission can reclaim under pressure."""
        slot_held = set()
        for owned in self._owned:
            slot_held.update(owned)
        return int(sum(1 for page in range(self.trash_pages,
                                           self.physical_pages)
                       if self._refcounts[page] > 0
                       and page not in slot_held))

    def saturation(self) -> float:
        """Fraction of the pool in use — 1.0 is the kv_pages_exhausted
        alert condition."""
        return self.used_pages / self.num_pages

    # -- allocation --------------------------------------------------------
    def assign(self, slot: int, pages: int) -> bool:
        """Move ``pages`` fresh pages from the free list to ``slot`` and
        fill its page-table row. Returns False (taking nothing) when the
        pool cannot satisfy the request — partial grants would deadlock
        admission. Raises on a slot that already holds pages (a free-slot
        invariant violation, never load)."""
        if not 0 < pages <= self.max_pages_per_slot:
            raise ValueError(
                f"pages must be in [1, {self.max_pages_per_slot}], "
                f"got {pages}")
        return self.assign_shared(slot, (), pages)

    def assign_shared(self, slot: int, shared: Sequence[int],
                      fresh: int) -> bool:
        """Grant ``slot`` a run of already-live ``shared`` pages (a prefix-
        cache hit: each gains one reference, its K/V is read-only to this
        slot) followed by ``fresh`` pages popped from the free list (the
        request's private suffix — the first page it will ever WRITE is
        always private, the copy-on-write rule of docs/SERVING.md "Prefix
        cache"). Returns False taking nothing when the free list cannot
        cover ``fresh``; raises on invariant violations (occupied slot,
        oversize grant, sharing a page nobody holds)."""
        total = len(shared) + fresh
        if not 0 < total <= self.max_pages_per_slot:
            raise ValueError(
                f"total pages must be in [1, {self.max_pages_per_slot}], "
                f"got {total}")
        if self._owned[slot]:
            raise ValueError(
                f"slot {slot} already owns {len(self._owned[slot])} pages; "
                "release before reassigning")
        for page in shared:
            if not (self.trash_pages <= page < self.physical_pages):
                raise ValueError(f"shared page {page} is not a usable page")
            if self._refcounts[page] < 1:
                raise ValueError(
                    f"shared page {page} has no live reference — sharing a "
                    "free page would read recycled garbage")
        if fresh > len(self._free):
            return False
        granted = [self._free.pop() for _ in range(fresh)]
        for page in shared:
            self._refcounts[page] += 1
        for page in granted:
            self._refcounts[page] = 1
        row = list(shared) + granted
        self._owned[slot] = row
        self.page_table[slot, :len(row)] = row
        return True

    def release(self, slot: int) -> int:
        """Drop ``slot``'s reference on each granted page, returning pages
        whose refcount hits 0 to the free list, and point the whole
        page-table row back at the trash page; idempotent (releasing an
        empty slot is a no-op returning 0). Returns the NET number of pages
        actually freed — shared pages survive their sharers, so Retry-After
        estimates must use this, not the grant size (docs/SERVING.md)."""
        granted = self._owned[slot]
        self._owned[slot] = []
        freed = 0
        for page in reversed(granted):
            self._refcounts[page] -= 1
            if self._refcounts[page] == 0:
                self._free.append(page)
                freed += 1
        self.page_table[slot, :] = TRASH_PAGE
        return freed

    # -- prefix-cache references -------------------------------------------
    def cache_ref(self, page: int) -> None:
        """Add the prefix cache's retention reference to a LIVE page (the
        tree only ever adopts pages some slot just filled)."""
        if self._refcounts[page] < 1:
            raise ValueError(
                f"page {page} is free — the prefix cache can only retain "
                "pages a slot currently holds")
        self._refcounts[page] += 1

    def cache_unref(self, page: int) -> bool:
        """Drop the prefix cache's reference (eviction); returns True when
        that freed the page back to the pool."""
        if self._refcounts[page] < 1:
            raise ValueError(f"page {page} has no reference to drop")
        self._refcounts[page] -= 1
        if self._refcounts[page] == 0:
            self._free.append(page)
            return True
        return False


# -- host tier (docs/SERVING.md "KV-page tiering") ----------------------------

def page_content_key(prompt: Sequence[int], page_index: int,
                     page_size: int) -> bytes:
    """Content key of logical page ``page_index`` of ``prompt``: the WHOLE
    token prefix through the page's last position, serialized. K/V at a
    position depends on every earlier token (the PR 11 sharing argument),
    so the page's identity is the full prefix, not just its own
    ``page_size``-token run — two prompts sharing a page's tokens but
    diverging earlier must key differently."""
    end = (page_index + 1) * page_size
    return np.asarray(prompt[:end], np.int32).tobytes()


class HostPageEntry:
    """One demoted page: int8 K/V payload ``[layers, page_size, kv_heads,
    d_head]`` plus the per-(page, kv_head) f32 scale rows ``[layers,
    kv_heads]`` that travelled with it (ops/kv_quant.py). Immutable once
    stored — a promotion reads it, never edits it — so the store can hand
    the same entry to concurrent promote jobs without copying."""

    __slots__ = ("k", "v", "k_scale", "v_scale", "nbytes", "last_used")

    def __init__(self, k: np.ndarray, v: np.ndarray, k_scale: np.ndarray,
                 v_scale: np.ndarray, last_used: int) -> None:
        self.k = k
        self.v = v
        self.k_scale = k_scale
        self.v_scale = v_scale
        self.nbytes = int(k.nbytes + v.nbytes + k_scale.nbytes
                          + v_scale.nbytes)
        self.last_used = last_used


class HostPageStore:
    """Bounded host-RAM ring of demoted int8 pages, LRU inside a byte
    budget (``[generation_service] host_kv_bytes``).

    Keys are radix content keys (:func:`page_content_key`). ``put`` admits
    an entry and LRU-evicts past the budget; ``get`` returns the entry and
    touches its LRU stamp. An entry larger than the whole budget is
    refused outright (a zero-budget store therefore stores nothing — the
    rollback configuration never constructs one anyway). NOT internally
    locked: the engine mutates it only from its pump thread, exactly like
    :class:`PagePool`."""

    def __init__(self, capacity_bytes: int) -> None:
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1, got {capacity_bytes}")
        self.capacity_bytes = int(capacity_bytes)
        self._entries: Dict[bytes, HostPageEntry] = {}
        self._tick = 0
        self.bytes_used = 0
        #: lifetime pages the budget pushed back out — the host_kv_thrash
        #: signal's raw material (demoting faster than the budget holds)
        self.evictions = 0

    @property
    def resident_pages(self) -> int:
        return len(self._entries)

    def __contains__(self, key: bytes) -> bool:
        return key in self._entries

    def put(self, key: bytes, k: np.ndarray, v: np.ndarray,
            k_scale: np.ndarray, v_scale: np.ndarray) -> bool:
        """Adopt one demoted page; returns False when it can never fit.
        Re-demoting a resident key refreshes its bytes and LRU stamp (the
        payload is identical by construction — content-keyed)."""
        self._tick += 1
        entry = HostPageEntry(k, v, k_scale, v_scale, self._tick)
        if entry.nbytes > self.capacity_bytes:
            return False
        old = self._entries.pop(key, None)
        if old is not None:
            self.bytes_used -= old.nbytes
        self._entries[key] = entry
        self.bytes_used += entry.nbytes
        while self.bytes_used > self.capacity_bytes:
            victim = min(self._entries,
                         key=lambda k_: self._entries[k_].last_used)
            self.bytes_used -= self._entries.pop(victim).nbytes
            self.evictions += 1
        return True

    def get(self, key: bytes) -> Optional[HostPageEntry]:
        entry = self._entries.get(key)
        if entry is not None:
            self._tick += 1
            entry.last_used = self._tick
        return entry

    def clear(self) -> int:
        dropped = len(self._entries)
        self._entries.clear()
        self.bytes_used = 0
        return dropped


class LaneJob:
    """One unit of copy-lane work. ``done`` flips True (a plain attribute
    write — atomic under the GIL) only AFTER ``result``/``error`` are set,
    so a pump-thread poll that observes ``done`` always sees the full
    outcome."""

    __slots__ = ("fn", "result", "error", "done")

    def __init__(self, fn: Callable[[], object]) -> None:
        self.fn = fn
        self.result: object = None
        self.error: Optional[BaseException] = None
        self.done = False

    def run(self) -> None:
        try:
            self.result = self.fn()
        except BaseException as exc:        # noqa: BLE001 - reported via poll
            self.error = exc
        self.done = True


class HostCopyLane:
    """The async promote/demote copy lane: a single background worker that
    runs staged host<->device copies OFF the pump thread, so a promotion's
    ``device_put`` (or a demotion's device->host materialization) overlaps
    the running decode step instead of blocking it.

    The pump thread ``submit``s a closure and polls ``job.done`` at each
    tick — never joins, never waits (the fake-clock tiering test pins that
    a job which NEVER completes still costs the running batch nothing).
    The worker thread is started lazily on first submit and is a daemon:
    an engine teardown abandons at most one idle queue reader. Tests
    substitute a synchronous or manually-released lane through the same
    two-method surface."""

    def __init__(self) -> None:
        self._jobs: "queue_module.Queue[LaneJob]" = queue_module.Queue()
        self._worker: Optional[threading.Thread] = None

    def submit(self, fn: Callable[[], object]) -> LaneJob:
        job = LaneJob(fn)
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._run, name="host-kv-copy-lane", daemon=True)
            self._worker.start()
        self._jobs.put(job)
        return job

    def _run(self) -> None:
        while True:
            self._jobs.get().run()
