"""Deterministic fault injection for the serving data plane
(docs/ROBUSTNESS.md "Serving data plane").

The control plane earned a chaos harness in PR 5 (``FakeCluster`` +
``FaultPlan``: every transport call consults a seeded plan before running).
This module is the data-plane analog: a :class:`ServingFaultPlan` attaches
to a :class:`~tensorhive_tpu.serving.engine.SlotEngine` and every device
DISPATCH — decode step, prefill (whole-prompt or chunked), speculative
verify — consults it first, so the failure modes preemptible TPU capacity
actually produces (XLA runtime error, HBM OOM, device lost mid-serving)
are reproducible in CI from a seed instead of waiting for real hardware to
die on schedule.

Like ``FaultPlan``, nothing here sleeps or flakes: latency is *modeled*
through an injectable sleeper (the default really sleeps, for smokes over
a real socket; tests inject a recorder), probability faults are seeded,
and ``fail_next`` faults are exact counts consumed in dispatch order.

This module is deliberately jax-free (like the ``serving`` package root):
the supervisor's failure classifier runs in the API/alerting processes
that never import the model stack.

Failure taxonomy (what :func:`classify_failure` answers):

* **transient** — worth retrying the tick against the SAME engine: the
  dispatch never reached the device (the donated cache was not consumed),
  so the engine's state is intact. Only failures that declare themselves
  transient qualify: :class:`TransientDispatchError` (and anything with a
  truthy ``transient`` attribute). Injected pre-dispatch faults are the
  canonical case.
* **fatal** — everything else. A real failure inside a dispatch may have
  consumed the donated KV cache or wedged the runtime; the only honest
  recovery is fail-fast (terminal chunks to every in-flight stream) and a
  full engine rebuild. Fatal-by-default is deliberate: guessing that an
  unknown XLA error is retryable risks serving garbage from a
  half-donated cache.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Dict, Optional, Type

from ..utils import lockwitness

#: dispatch kinds a plan can target (the engine's three device seams)
DISPATCH_KINDS = ("step", "prefill", "verify")

TRANSIENT = "transient"
FATAL = "fatal"


class InjectedFaultError(RuntimeError):
    """Base class for failures a :class:`ServingFaultPlan` raises — fatal
    unless a subclass says otherwise (the same default real errors get)."""

    transient = False


class TransientDispatchError(InjectedFaultError):
    """A dispatch failure that never reached the device: the engine's
    donated buffers are intact and retrying the tick is safe. The
    supervisor retries these with bounded backoff before escalating."""

    transient = True


class DeviceLostError(InjectedFaultError):
    """The accelerator went away mid-serving (TPU-VM preemption, runtime
    crash) — the canonical fatal fault: every in-flight stream must be
    failed fast and the engine rebuilt on whatever device comes back."""

    transient = False


def classify_failure(exc: BaseException) -> str:
    """``"transient"`` (retry the tick, same engine) or ``"fatal"``
    (fail-fast + rebuild). See the module docstring for why unknown
    errors are fatal by default."""
    if getattr(exc, "transient", False):
        return TRANSIENT
    return FATAL


class ServingFaultPlan:
    """Seeded, deterministic fault schedule for one engine's dispatches.

    Attach via ``SlotEngine(fault_plan=...)``; the engine calls
    :meth:`before_dispatch` at the top of every step/prefill/verify
    dispatch (BEFORE any device call, so the donated cache is never
    half-consumed by an injected fault — which is what makes the
    ``transient`` classification honest for injected faults).

    * :meth:`fail_next` — the next N dispatches of a kind raise the given
      exception class (default :class:`DeviceLostError`, the fatal case);
      exact counts, consumed in dispatch order.
    * ``fail_probability`` — seeded coin per dispatch: deterministic given
      ``seed`` and the dispatch order.
    * :meth:`slow_next` — the next N dispatches of a kind invoke
      ``sleeper(seconds)`` first (a stalling device, not a dead one);
      tests inject a recording sleeper so nothing really waits.
    * :meth:`set_device_lost` — every dispatch raises
      :class:`DeviceLostError` until cleared: the persistent-outage shape
      a crash-loop breaker must survive (clearing it is "the platform
      restored the device").

    Counters (:attr:`dispatches`, :attr:`faults_injected`, per kind) let
    harnesses assert exactly how many dispatches consulted the plan — the
    serving chaos smoke pins fault counts the way the control-plane smoke
    pins breaker streak counts.
    """

    def __init__(self, seed: int = 0, error: str = "injected serving fault",
                 fail_probability: float = 0.0,
                 exc_class: Type[BaseException] = DeviceLostError,
                 sleeper: Callable[[float], None] = time.sleep) -> None:
        self.seed = seed
        self.error = error
        self.fail_probability = float(fail_probability)
        self.exc_class = exc_class
        self._sleeper = sleeper
        self._rng = random.Random(seed)
        self._lock = lockwitness.Lock("ServingFaultPlan._lock")
        self._fail_next: Dict[str, list] = {kind: [] for kind in
                                            DISPATCH_KINDS}
        self._slow_next: Dict[str, list] = {kind: [] for kind in
                                            DISPATCH_KINDS}
        self._device_lost = False
        self.dispatches: Dict[str, int] = {kind: 0 for kind in DISPATCH_KINDS}
        self.faults_injected: Dict[str, int] = {kind: 0 for kind in
                                                DISPATCH_KINDS}

    # -- scheduling --------------------------------------------------------
    def fail_next(self, kind: str, count: int = 1,
                  exc_class: Optional[Type[BaseException]] = None) -> None:
        """Fail the next ``count`` dispatches of ``kind`` with
        ``exc_class`` (default: the plan's, default DeviceLostError)."""
        self._check_kind(kind)
        with self._lock:
            self._fail_next[kind].extend(
                [exc_class or self.exc_class] * int(count))

    def slow_next(self, kind: str, count: int = 1,
                  seconds: float = 0.1) -> None:
        """Stall the next ``count`` dispatches of ``kind`` by ``seconds``
        (through the injectable sleeper) before running them."""
        self._check_kind(kind)
        with self._lock:
            self._slow_next[kind].extend([float(seconds)] * int(count))

    def set_device_lost(self, lost: bool = True) -> None:
        """Every dispatch raises DeviceLostError until cleared."""
        with self._lock:
            self._device_lost = lost

    @staticmethod
    def _check_kind(kind: str) -> None:
        if kind not in DISPATCH_KINDS:
            raise ValueError(
                f"unknown dispatch kind {kind!r}; one of {DISPATCH_KINDS}")

    # -- the seam ----------------------------------------------------------
    def before_dispatch(self, kind: str) -> None:
        """Consulted by the engine before every device dispatch; raises the
        planned failure (if any) and applies planned slowness."""
        self._check_kind(kind)
        with self._lock:
            self.dispatches[kind] += 1
            slow_s = (self._slow_next[kind].pop(0)
                      if self._slow_next[kind] else None)
            exc_class: Optional[Type[BaseException]] = None
            reason = None
            if self._device_lost:
                exc_class, reason = DeviceLostError, "device_lost"
            elif self._fail_next[kind]:
                exc_class = self._fail_next[kind].pop(0)
                reason = "fail_next"
            elif (self.fail_probability
                    and self._rng.random() < self.fail_probability):
                exc_class, reason = self.exc_class, "seeded"
            if exc_class is not None:
                self.faults_injected[kind] += 1
        # sleep and raise OUTSIDE the lock: a slow dispatch must not block
        # another thread's counter reads, and exception construction can
        # run arbitrary subclass code
        if slow_s:
            self._sleeper(slow_s)
        if exc_class is not None:
            raise exc_class(f"{self.error} ({kind}: {reason})")


__all__ = [
    "DISPATCH_KINDS",
    "DeviceLostError",
    "FATAL",
    "InjectedFaultError",
    "ServingFaultPlan",
    "TRANSIENT",
    "TransientDispatchError",
    "classify_failure",
]
