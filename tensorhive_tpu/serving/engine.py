"""Slot-based continuous-batching engine over the decode fast path.

PR 3 built single-tenant decode primitives (donated in-place KV cache,
bucketed prefill executables, ``lax.top_k`` sampling); this module turns
them into the first user-facing data plane: many concurrent generation
requests share ONE running decode batch on one chip, FlexNPU-style
(PAPERS.md — dynamic prefill/decode co-location on a single accelerator).

Design, in the order the constraints forced it:

* **Fixed-capacity slot pool, one persistent cache.** The KV cache is a
  single ``[layers, slots, max_len, kv_heads, d_head]`` buffer allocated
  once; a request *joins* by prefilling its prompt into a free slot row and
  *leaves* by having its slot freed on EOS/max-tokens. Batch shape never
  changes, so the decode executable never recompiles.
* **Per-slot state is traced, never static.** The fused step takes per-slot
  token/position/active/temperature arrays as *operands*; joins and leaves
  only flip mask entries host-side. ``tpuhive_decode_compile_total`` counts
  ``serving_step``/``serving_prefill`` compiles so the zero-recompile
  contract is observable (and gated by tools/serving_smoke.py).
* **Prefill co-location.** Each scheduler iteration admits waiting requests
  (bucketed prefill — power-of-two widths reuse PR 3's
  ``_prefill_bucket``) and then advances the whole running batch one token,
  interleaving prefill and decode work on the same chip instead of
  dedicating it to either phase.
* **Admission control at the edge.** The pending queue is bounded; a full
  queue rejects at submit time (the API layer maps that to 429 +
  Retry-After) rather than letting latency collapse for everyone already
  admitted. Per-user concurrency caps ride the same path (Tally-style
  non-intrusive fairness: the model itself is never preempted).
* **Inactive slots are harmless by construction.** A parked slot keeps
  stepping (masked) and writes garbage K/V at its frozen position; that is
  safe because a joining sequence's own prefill/steps rewrite every
  position it will ever attend to *before* attending to it (the attend
  mask is ``<= position`` and each step writes its position first) — this
  is what makes join/leave free of any cache scrubbing pass, and it is
  pinned by test_serving.py::test_slot_reuse_matches_fresh_engine.

SLO instrumentation (TTFT, inter-token latency, queue depth, slot
occupancy, batch efficiency) lands in the PR 1 registry; docs/SERVING.md
is the operator guide.
"""
from __future__ import annotations

import collections
import dataclasses
import functools
import queue as queue_module
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.decode import (
    KVCache,
    _count_compile,
    _decode_attend,
    _prefill_bucket,
)
from ..models.transformer import (
    TransformerConfig,
    TransformerLM,
    _rmsnorm,
)
from ..observability import get_registry, Histogram
from . import QueueFullError, RateLimitError

# -- metrics (registered once at import; one exposition surface) -------------
_REQUESTS = get_registry().counter(
    "tpuhive_generate_requests_total",
    "Generation requests by outcome: completed, rejected_queue, "
    "rejected_ratelimit, cancelled, failed.",
    labels=("outcome",))
_TOKENS = get_registry().counter(
    "tpuhive_generate_tokens_total",
    "Tokens emitted by the serving engine across all requests.")
_QUEUE_DEPTH = get_registry().gauge(
    "tpuhive_generate_queue_depth",
    "Requests waiting for a slot (admission queue occupancy).")
_QUEUE_CAPACITY = get_registry().gauge(
    "tpuhive_generate_queue_capacity",
    "Bound of the admission queue — depth/capacity == 1 is saturation.")
_SLOTS_BUSY = get_registry().gauge(
    "tpuhive_generate_slots_busy",
    "Slots currently occupied by a running sequence.")
_SLOTS_TOTAL = get_registry().gauge(
    "tpuhive_generate_slots_total",
    "Slot-pool capacity (the fixed decode batch size).")
_TTFT_SECONDS = get_registry().histogram(
    "tpuhive_generate_ttft_seconds",
    "Submit-to-first-token latency (queue wait + prefill + first step).")
_INTERTOKEN_SECONDS = get_registry().histogram(
    "tpuhive_generate_intertoken_seconds",
    "Gap between consecutive emitted tokens of one sequence.")
_BATCH_EFFICIENCY = get_registry().histogram(
    "tpuhive_generate_batch_efficiency",
    "Active slots / capacity per decode step (1.0 = perfectly packed).",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))


# -- device functions ---------------------------------------------------------
#
# Both are jitted with EVERYTHING shape-determining static (config, slot
# count, cache length, bucket width, top_k) and all per-slot state traced:
# one step executable for the engine's lifetime, one prefill executable per
# prompt bucket. The cache is donated through both so the multi-hundred-MB
# buffer aliases in place instead of being copied per token.

def _step_body(params, tokens, positions, active, temps, cache, key,
               config: TransformerConfig, top_k: Optional[int]):
    """One fused decode step for the whole slot batch.

    tokens/positions/active/temps are [S] per-slot operands; each active
    slot consumes the token AT its own position and emits the token for
    position+1. Per-slot cache writes are a vmapped dynamic_update_slice
    (batched start indices lower to one scatter) into this layer's
    [S, max_len, Hkv, Dh] page of the 5-D buffer — the attend math itself
    is the SAME ``_decode_attend`` the single-tenant path uses (positions
    broadcast per slot), so serving and ``decode.generate`` cannot drift.
    """
    dtype = config.dtype
    x = params["tok_embed"].astype(dtype)[tokens][:, None, :]     # [S,1,D]
    rope_positions = positions[:, None]                           # [S,1]
    cache_k, cache_v = cache.k, cache.v

    write = jax.vmap(
        lambda row, update, position: jax.lax.dynamic_update_slice(
            row, update, (position, 0, 0)))

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v
        layer_k = write(cache_k[layer], k.astype(cache_k.dtype), positions)
        layer_v = write(cache_v[layer], v.astype(cache_v.dtype), positions)
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        # per-slot causal mask: broadcastable positions [S,1,1,1,1] against
        # the key iota inside _decode_attend
        return _decode_attend(q, cache_k[layer], cache_v[layer],
                              positions[:, None, None, None, None])

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, rope_positions,
                                        attend, layer_index=layer_index)
    x = _rmsnorm(x, params["final_norm"]["scale"])
    logits = jnp.dot(x[:, 0].astype(dtype), params["w_lm_head"].astype(dtype),
                     preferred_element_type=jnp.float32)           # [S,V]

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_temps = jnp.where(temps > 0.0, temps, 1.0)
    scaled = logits / safe_temps[:, None]
    if top_k is not None:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    key, sample_key = jax.random.split(key)
    sampled = jax.random.categorical(sample_key, scaled, axis=-1)
    chosen = jnp.where(temps > 0.0, sampled.astype(jnp.int32), greedy)
    # inactive slots keep their frozen token so their (harmless) writes
    # stay deterministic
    chosen = jnp.where(active, chosen, tokens)
    return chosen, KVCache(k=cache_k, v=cache_v), key


_serving_step = functools.partial(
    jax.jit, static_argnames=("config", "top_k"),
    donate_argnames=("cache",))(_step_body)


def _prefill_body(params, head, cache, slot, real_len,
                  config: TransformerConfig):
    """Prefill one joining sequence's prompt head into its slot row.

    ``head`` is [1, W] with W a power-of-two bucket; ``real_len`` (traced)
    zero-masks the padded K/V writes and ``slot`` (traced) selects the row,
    so every prompt length in a bucket — in any slot — reuses ONE
    executable. Mirrors models/decode.py::_prefill_body, with the write
    offset at (layer, slot, 0, 0, 0) instead of a whole-batch write.
    ``config.use_flash`` picks the attention impl like the training attend
    does (runtimes without the pallas kernels serve via the XLA reference
    path — identical math, tested exact in f32)."""
    from ..models.transformer import flash_attention
    from ..ops.flash_attention import reference_attention

    dtype = config.dtype
    batch, width = head.shape
    x = params["tok_embed"].astype(dtype)[head]
    positions = jnp.broadcast_to(jnp.arange(width, dtype=jnp.int32),
                                 (batch, width))
    valid = (jnp.arange(width, dtype=jnp.int32)
             < real_len)[None, :, None, None]
    cache_k, cache_v = cache.k, cache.v

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v
        write_k = jnp.where(valid, k, 0).astype(cache_k.dtype)
        write_v = jnp.where(valid, v, 0).astype(cache_v.dtype)
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, write_k[None], (layer, slot, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, write_v[None], (layer, slot, 0, 0, 0))
        if config.use_flash:
            return flash_attention(q, k, v, causal=True)
        return reference_attention(q, k, v, causal=True)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, positions, attend,
                                        layer_index=layer_index)
    return KVCache(k=cache_k, v=cache_v)


_serving_prefill = functools.partial(
    jax.jit, static_argnames=("config",),
    donate_argnames=("cache",))(_prefill_body)


# -- request plumbing ---------------------------------------------------------

#: handle event kinds
TOKEN, DONE, ERROR = "token", "done", "error"


class GenerationHandle:
    """Consumer side of one request: a bounded event stream plus final
    summary. ``tokens()`` is what the streaming endpoint iterates."""

    def __init__(self, engine: "SlotEngine", request: "_Request") -> None:
        self._engine = engine
        self._request = request
        self._events: "queue_module.Queue[tuple]" = queue_module.Queue()
        self._summary: Optional[Dict] = None

    # -- engine side ------------------------------------------------------
    def _push(self, kind: str, payload: object) -> None:
        self._events.put((kind, payload))

    # -- consumer side ----------------------------------------------------
    def tokens(self, timeout_s: float = 30.0):
        """Yield generated token ids as they are produced. Raises
        ``TimeoutError`` if the engine produces nothing for ``timeout_s``
        (a wedged pump must cost the client a bounded wait, never a hung
        connection) and ``RuntimeError`` on engine-side failure."""
        while True:
            try:
                kind, payload = self._events.get(timeout=timeout_s)
            except queue_module.Empty:
                self.cancel()
                raise TimeoutError(
                    f"no token within {timeout_s:.0f}s") from None
            if kind == TOKEN:
                yield payload
            elif kind == DONE:
                self._summary = payload
                return
            else:
                raise RuntimeError(str(payload))

    def result(self, timeout_s: float = 30.0) -> Dict:
        """Drain the stream and return the completion summary."""
        if self._summary is None:
            for _ in self.tokens(timeout_s=timeout_s):
                pass
        assert self._summary is not None
        return self._summary

    def cancel(self) -> None:
        """Mark the request cancelled; the engine frees its slot (or drops
        it from the queue) at the next scheduler iteration."""
        self._engine._cancel(self._request)

    @property
    def done(self) -> bool:
        return self._request.finished


@dataclasses.dataclass
class _Request:
    prompt: List[int]
    max_new_tokens: int
    temperature: float
    user_key: Optional[str]
    submitted_ts: float
    handle: Optional[GenerationHandle] = None
    generated: List[int] = dataclasses.field(default_factory=list)
    first_token_ts: Optional[float] = None
    last_token_ts: Optional[float] = None
    cancelled: bool = False
    finished: bool = False


@dataclasses.dataclass
class _Slot:
    request: _Request
    joined_ts: float


class SlotEngine:
    """The continuous-batching scheduler + device state.

    Host-side bookkeeping (queue, slot table, per-user counts, metrics) is
    guarded by one lock; device calls happen OUTSIDE the lock and only ever
    from the single pump thread (GenerationService), so submitters are never
    blocked behind a decode step.
    """

    def __init__(
        self,
        params,
        config: TransformerConfig,
        *,
        slots: int = 8,
        max_len: Optional[int] = None,
        queue_depth: int = 32,
        top_k: Optional[int] = None,
        eos_token: Optional[int] = None,
        max_new_tokens_cap: int = 512,
        max_concurrent_per_user: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if not config.causal:
            raise ValueError("serving needs an autoregressive model; this "
                             "config is a bidirectional encoder")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if top_k is not None and not 0 < top_k <= config.vocab_size:
            raise ValueError(
                f"top_k must be in (0, {config.vocab_size}], got {top_k}")
        self.params = params
        self.config = config
        self.capacity = int(slots)
        self.max_len = int(max_len or config.max_seq_len)
        self.queue_depth = int(queue_depth)
        self.top_k = top_k
        self.eos_token = eos_token
        self.max_new_tokens_cap = int(max_new_tokens_cap)
        self.max_concurrent_per_user = int(max_concurrent_per_user)
        self.clock = clock

        self._lock = threading.Lock()
        self._pending: Deque[_Request] = collections.deque()
        self._slots: List[Optional[_Slot]] = [None] * self.capacity
        self._user_active: Dict[str, int] = {}
        self.completed_requests = 0
        self.emitted_tokens = 0
        self.steps = 0
        #: private latency views backing ``stats()`` p50/p95 (the registry
        #: children are shared across engine instances in tests)
        self._ttft_hist = Histogram()
        self._intertoken_hist = Histogram()

        # device state: one persistent cache + per-slot operand arrays
        # (host numpy masters; tiny, shipped per step)
        shape = (config.n_layers, self.capacity, self.max_len,
                 config.kv_heads, config.d_head)
        self._cache = KVCache(k=jnp.zeros(shape, config.dtype),
                              v=jnp.zeros(shape, config.dtype))
        self._tokens = np.zeros(self.capacity, np.int32)
        self._positions = np.zeros(self.capacity, np.int32)
        self._active = np.zeros(self.capacity, bool)
        self._temps = np.zeros(self.capacity, np.float32)
        self._key = jax.random.PRNGKey(0)

        _QUEUE_CAPACITY.set(self.queue_depth)
        _SLOTS_TOTAL.set(self.capacity)
        _QUEUE_DEPTH.set(0)
        _SLOTS_BUSY.set(0)

    # -- admission --------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0,
               user_key: Optional[str] = None) -> GenerationHandle:
        """Queue one request; raises ``ValueError`` on malformed input,
        ``RateLimitError``/``QueueFullError`` on admission failure."""
        prompt = [int(token) for token in prompt]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        if any(not 0 <= t < self.config.vocab_size for t in prompt):
            raise ValueError(
                f"prompt tokens must be in [0, {self.config.vocab_size})")
        if not 1 <= max_new_tokens <= self.max_new_tokens_cap:
            raise ValueError(
                f"max_new_tokens must be in [1, {self.max_new_tokens_cap}], "
                f"got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+new = {len(prompt) + max_new_tokens} exceeds the "
                f"engine sequence budget {self.max_len}")
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        request = _Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                           temperature=float(temperature),
                           user_key=str(user_key) if user_key else None,
                           submitted_ts=self.clock())
        handle = GenerationHandle(self, request)
        request.handle = handle
        with self._lock:
            if (self.max_concurrent_per_user > 0 and request.user_key
                    and self._user_active.get(request.user_key, 0)
                    >= self.max_concurrent_per_user):
                _REQUESTS.labels(outcome="rejected_ratelimit").inc()
                raise RateLimitError(
                    f"user has {self.max_concurrent_per_user} generation "
                    "requests in flight; retry when one completes",
                    retry_after_s=self._retry_after_locked())
            if len(self._pending) >= self.queue_depth:
                _REQUESTS.labels(outcome="rejected_queue").inc()
                raise QueueFullError(
                    f"admission queue is full ({self.queue_depth} waiting); "
                    "retry shortly",
                    retry_after_s=self._retry_after_locked())
            if request.user_key:
                self._user_active[request.user_key] = (
                    self._user_active.get(request.user_key, 0) + 1)
            self._pending.append(request)
            _QUEUE_DEPTH.set(len(self._pending))
        return handle

    def _retry_after_locked(self) -> float:
        """Honest Retry-After: time for the oldest running sequence to
        finish at the observed inter-token rate (floor 1 s)."""
        per_token = self._intertoken_hist.quantile(0.5) or 0.05
        remaining = [
            slot.request.max_new_tokens - len(slot.request.generated)
            for slot in self._slots if slot is not None]
        if not remaining:
            return 1.0
        return max(1.0, round(min(remaining) * per_token, 1))

    def _cancel(self, request: _Request) -> None:
        with self._lock:
            if not request.finished:
                request.cancelled = True

    # -- scheduler --------------------------------------------------------
    def has_work(self) -> bool:
        with self._lock:
            return bool(self._pending) or any(
                slot is not None for slot in self._slots)

    def step(self) -> int:
        """One scheduler iteration: admit joins, then advance the running
        batch one token. Returns the number of active slots stepped."""
        self._admit()
        return self._decode_step()

    def pump(self, budget_s: Optional[float] = None,
             should_stop: Optional[Callable[[], bool]] = None) -> int:
        """Run scheduler iterations until idle, the wall budget is spent,
        or ``should_stop()`` — the GenerationService tick body."""
        deadline = None if budget_s is None else self.clock() + budget_s
        steps = 0
        while self.has_work():
            if should_stop is not None and should_stop():
                break
            if deadline is not None and self.clock() >= deadline:
                break
            self.step()
            steps += 1
        return steps

    def warmup(self, prompt_lens: Sequence[int] = ()) -> None:
        """Compile the step executable and the prefill executable for each
        bucket the given prompt lengths map to (plus the smallest bucket),
        so steady-state traffic never pays a compile."""
        buckets = {_prefill_bucket(max(1, length - 1), self.max_len - 1)
                   for length in prompt_lens} or {
                       _prefill_bucket(1, self.max_len - 1)}
        for width in sorted(buckets):
            head = jnp.zeros((1, width), jnp.int32)
            self._count_prefill_compile(width)
            self._cache = _serving_prefill(
                self.params, head, self._cache, jnp.int32(0), jnp.int32(0),
                self.config)
        chosen, self._cache, self._key = self._run_step()
        np.asarray(chosen)      # force the compile before traffic arrives

    # -- internals --------------------------------------------------------
    def _count_prefill_compile(self, width: int) -> None:
        _count_compile("serving_prefill",
                       ("serving_prefill", self.config, self.capacity,
                        self.max_len, width))

    def _run_step(self):
        _count_compile("serving_step",
                       ("serving_step", self.config, self.capacity,
                        self.max_len, self.top_k))
        return _serving_step(
            self.params, jnp.asarray(self._tokens),
            jnp.asarray(self._positions), jnp.asarray(self._active),
            jnp.asarray(self._temps), self._cache, self._key,
            config=self.config, top_k=self.top_k)

    def _admit(self) -> int:
        """Move pending requests into free slots (prefill co-located with
        decode: every scheduler iteration does its joins first, then the
        batch step — FlexNPU's dynamic phase mixing on one chip)."""
        joined = 0
        while True:
            with self._lock:
                self._drop_cancelled_pending_locked()
                free = next((index for index, slot
                             in enumerate(self._slots) if slot is None), None)
                if free is None or not self._pending:
                    _QUEUE_DEPTH.set(len(self._pending))
                    return joined
                request = self._pending.popleft()
                self._slots[free] = _Slot(request=request,
                                          joined_ts=self.clock())
                _QUEUE_DEPTH.set(len(self._pending))
                _SLOTS_BUSY.set(self._busy_locked())
            self._join(free, request)
            joined += 1

    def _drop_cancelled_pending_locked(self) -> None:
        kept: Deque[_Request] = collections.deque()
        for request in self._pending:
            if request.cancelled:
                self._finish_locked(request, outcome="cancelled")
            else:
                kept.append(request)
        self._pending = kept  # thive: disable=TH-C — caller holds the lock (_locked suffix)

    def _join(self, slot: int, request: _Request) -> None:
        """Prefill the prompt head into the slot row and arm the per-slot
        operands; the first decode step after this emits the request's
        first token."""
        prompt = request.prompt
        prompt_len = len(prompt)
        if prompt_len > 1:
            width = _prefill_bucket(prompt_len - 1, self.max_len - 1)
            head = np.zeros((1, width), np.int32)
            head[0, :prompt_len - 1] = prompt[:-1]
            self._count_prefill_compile(width)
            self._cache = _serving_prefill(
                self.params, jnp.asarray(head), self._cache,
                jnp.int32(slot), jnp.int32(prompt_len - 1), self.config)
        with self._lock:
            self._tokens[slot] = prompt[-1]
            self._positions[slot] = prompt_len - 1
            self._temps[slot] = request.temperature
            self._active[slot] = True

    def _decode_step(self) -> int:
        with self._lock:
            stepped = [(index, slot.request)
                       for index, slot in enumerate(self._slots)
                       if slot is not None]
        if not stepped:
            return 0
        chosen, self._cache, self._key = self._run_step()
        emitted = np.asarray(chosen)
        now = self.clock()
        with self._lock:
            self.steps += 1
            _BATCH_EFFICIENCY.observe(len(stepped) / self.capacity)
            for index, request in stepped:
                if self._slots[index] is None or (
                        self._slots[index].request is not request):
                    continue        # freed between snapshot and apply
                token = int(emitted[index])
                self._tokens[index] = token
                self._positions[index] += 1
                self._apply_token_locked(index, request, token, now)
            _SLOTS_BUSY.set(self._busy_locked())
        return len(stepped)

    def _apply_token_locked(self, index: int, request: _Request,
                            token: int, now: float) -> None:
        if request.cancelled:
            self._free_slot_locked(index)
            self._finish_locked(request, outcome="cancelled")
            return
        request.generated.append(token)
        self.emitted_tokens += 1
        _TOKENS.inc()
        if request.first_token_ts is None:
            request.first_token_ts = now
            ttft = now - request.submitted_ts
            _TTFT_SECONDS.observe(ttft)
            self._ttft_hist.observe(ttft)
        else:
            gap = now - (request.last_token_ts or now)
            _INTERTOKEN_SECONDS.observe(gap)
            self._intertoken_hist.observe(gap)
        request.last_token_ts = now
        if request.handle is not None:
            request.handle._push(TOKEN, token)
        hit_eos = (self.eos_token is not None and token == self.eos_token)
        if hit_eos or len(request.generated) >= request.max_new_tokens:
            self._free_slot_locked(index)
            self._finish_locked(request, outcome="completed")

    def _free_slot_locked(self, index: int) -> None:
        self._slots[index] = None  # thive: disable=TH-C — caller holds the lock (_locked suffix)
        self._active[index] = False  # thive: disable=TH-C — caller holds the lock (_locked suffix)
        # position stays frozen: the parked slot's masked writes keep
        # landing on one already-consumed coordinate (see module docstring)

    def _finish_locked(self, request: _Request, outcome: str) -> None:
        if request.finished:
            return
        request.finished = True
        _REQUESTS.labels(outcome=outcome).inc()
        if outcome == "completed":
            self.completed_requests += 1
        if request.user_key:
            remaining = self._user_active.get(request.user_key, 1) - 1
            if remaining <= 0:
                self._user_active.pop(request.user_key, None)  # thive: disable=TH-C — caller holds the lock (_locked suffix)
            else:
                self._user_active[request.user_key] = remaining  # thive: disable=TH-C — caller holds the lock (_locked suffix)
        if request.handle is not None:
            now = self.clock()
            request.handle._push(DONE, {
                "tokens": list(request.generated),
                "outcome": outcome,
                "ttftS": (round(request.first_token_ts - request.submitted_ts,
                                6)
                          if request.first_token_ts is not None else None),
                "durationS": round(now - request.submitted_ts, 6),
            })

    # -- introspection ----------------------------------------------------
    def _busy_locked(self) -> int:
        return sum(1 for slot in self._slots if slot is not None)

    def stalled_slots(self, older_than_s: float) -> int:
        """Busy slots that have not emitted a token for ``older_than_s`` —
        the generate_slot_leak alert signal."""
        now = self.clock()
        with self._lock:
            count = 0
            for slot in self._slots:
                if slot is None:
                    continue
                last = (slot.request.last_token_ts
                        or slot.request.first_token_ts or slot.joined_ts)
                if now - last > older_than_s:
                    count += 1
            return count

    def stats(self) -> Dict:
        """SLO snapshot for ``GET /api/generate/stats`` + the dashboard."""
        def ms(value: Optional[float]) -> Optional[float]:
            return None if value is None else round(value * 1e3, 3)

        with self._lock:
            busy = self._busy_locked()
            return {
                "slots": self.capacity,
                "slotsBusy": busy,
                "queueDepth": len(self._pending),
                "queueCapacity": self.queue_depth,
                "maxSeqLen": self.max_len,
                "requestsCompleted": self.completed_requests,
                "tokensEmitted": self.emitted_tokens,
                "steps": self.steps,
                "ttftP50Ms": ms(self._ttft_hist.quantile(0.5)),
                "ttftP95Ms": ms(self._ttft_hist.quantile(0.95)),
                "intertokenP50Ms": ms(self._intertoken_hist.quantile(0.5)),
                "intertokenP95Ms": ms(self._intertoken_hist.quantile(0.95)),
            }

    def ttft_p95_s(self) -> Optional[float]:
        return self._ttft_hist.quantile(0.95)

    def queue_saturation(self) -> float:
        with self._lock:
            return len(self._pending) / self.queue_depth
