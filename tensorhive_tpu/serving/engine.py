"""Slot-based continuous-batching engine over the decode fast path.

PR 3 built single-tenant decode primitives (donated in-place KV cache,
bucketed prefill executables, ``lax.top_k`` sampling); this module turns
them into the first user-facing data plane: many concurrent generation
requests share ONE running decode batch on one chip, FlexNPU-style
(PAPERS.md — dynamic prefill/decode co-location on a single accelerator).

Design, in the order the constraints forced it:

* **Fixed-capacity slot pool, one persistent cache.** The KV cache is a
  single buffer allocated once; a request *joins* by prefilling its prompt
  into a free slot and *leaves* by having its slot freed on EOS/max-tokens.
  Batch shape never changes, so the decode executable never recompiles.
* **Paged by default, contiguous as rollback.** The default cache layout is
  a block-paged pool ``[layers, 1 + num_pages, page_size, kv_heads,
  d_head]`` (physical page 0 is the trash page): a slot owns only the pages
  its request needs — ``ceil((prompt + max_new) / page_size)`` from
  :class:`~tensorhive_tpu.serving.paging.PagePool`'s free list — so serving
  capacity is bound by *tokens in flight*, not ``slots × max_len``; at
  equal HBM the pool admits strictly more concurrent short/mixed sequences
  (docs/SERVING.md "Paged KV cache"). The page table rides into the
  step/prefill executables as a TRACED operand, so page assignment never
  recompiles. ``paged=False`` (``[generation_service] paged``) restores the
  PR 6 contiguous ``[layers, slots, max_len, kv_heads, d_head]`` layout —
  both are pinned f32-exact against ``decode.generate``.
* **Per-slot state is traced, never static.** The fused step takes per-slot
  token/position/active/temperature arrays as *operands*; joins and leaves
  only flip mask entries host-side. ``tpuhive_decode_compile_total`` counts
  ``serving_step``/``serving_prefill`` compiles so the zero-recompile
  contract is observable (and gated by tools/serving_smoke.py).
* **Prefill co-location.** Each scheduler iteration admits waiting requests
  (bucketed prefill — power-of-two widths reuse PR 3's
  ``_prefill_bucket``) and then advances the whole running batch one token,
  interleaving prefill and decode work on the same chip instead of
  dedicating it to either phase.
* **Shared prefixes cost once.** The paged layout defaults to the radix
  prefix cache (``serving/prefix_cache.py`` — ``[generation_service]
  prefix_cache``): admission grants matched prefix pages SHARED
  (refcounted) and charges only the unique suffix, prefill skips to the
  first uncached position through a start-offset chunked executable, and
  long prompts advance ONE ``prefill_chunk_tokens`` chunk per tick so a
  join can never stall the running batch's inter-token latency
  (docs/SERVING.md "Prefix cache & chunked prefill"). ``prefix_cache=off``
  is a byte-identical rollback to the PR 7-10 whole-prompt prefill path.
* **Pages are int8 by default.** The paged cache quantizes K/V to int8
  with one f32 scale per (physical page, kv_head) in side-arrays behind
  the same page tables (``[generation_service] kv_quant``, auto = on for
  paged layouts; ops/kv_quant.py) — the same HBM holds ~2x (bf16) / ~4x
  (f32) the pages, and page-bound admission converts that straight into
  concurrent sequences. Scales are traced operands in the donated cache
  pytree, so scale updates never recompile (``serving_paged_*_q``
  fingerprints); ``kv_quant=off`` rolls back byte-identically to the
  full-precision cache (docs/SERVING.md "Quantized KV pages").
* **Mesh-aware, single-chip by default.** An optional serving mesh
  (``parallel/mesh.py::serving_mesh``; ``[generation_service]
  mesh_dp``/``mesh_tp``) shards params over tp via the SAME
  ``MeshRules``/``tree_shardings`` machinery the training dryruns certify,
  and gives the KV cache a ``NamedSharding`` — kv_heads over tp (GQA guard:
  replicate K/V when tp does not divide kv_heads), the slot/page pool axis
  over dp so capacity scales with chips. Per-slot operands/page tables/
  positions are device_put replicated but stay TRACED, so the
  zero-recompile contract survives sharding (fingerprints gain a
  ``serving_mesh_*`` variant); ``mesh=None`` is byte-identical to the
  single-chip engine (docs/SERVING.md "Multi-chip serving").
* **Admission control at the edge.** The pending queue is bounded; a full
  queue rejects at submit time (the API layer maps that to 429 +
  Retry-After) rather than letting latency collapse for everyone already
  admitted. Per-user concurrency caps ride the same path (Tally-style
  non-intrusive fairness: the model itself is never preempted).
* **Inactive slots are harmless by construction.** A parked slot keeps
  stepping (masked) and writes garbage K/V at its frozen position; that is
  safe because a joining sequence's own prefill/steps rewrite every
  position it will ever attend to *before* attending to it (the attend
  mask is ``<= position`` and each step writes its position first) — this
  is what makes join/leave free of any cache scrubbing pass, and it is
  pinned by test_serving.py::test_slot_reuse_matches_fresh_engine.

SLO instrumentation (TTFT, inter-token latency, queue depth, slot
occupancy, batch efficiency) lands in the PR 1 registry; docs/SERVING.md
is the operator guide.
"""
from __future__ import annotations

import collections
import dataclasses
import functools
import logging
import queue as queue_module
import time
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.decode import (
    KVCache,
    QuantKVCache,
    _compile_seen,
    _count_compile,
    _decode_attend,
    _paged_attend,
    _prefill_bucket,
)
from ..ops import kv_quant as kvq
from ..utils import lockwitness
from ..models.transformer import (
    TransformerConfig,
    TransformerLM,
    _rmsnorm,
)
from ..observability import (
    Histogram,
    get_registry,
    get_request_ledger,
    get_tracer,
)
from ..observability.accounting import ANONYMOUS_TENANT
from ..ops.paged_attention import resolve_paged_kernel
from . import EngineDrainingError, QueueFullError, RateLimitError
from .faults import ServingFaultPlan
from .paging import (
    TRASH_PAGE,
    HostCopyLane,
    HostPageEntry,
    HostPageStore,
    LaneJob,
    PagePool,
    page_content_key,
)
from .prefix_cache import PrefixCache
from .speculative import (
    SpeculativeLane,
    _paged_spec_verify,
    _spec_verify,
    build_draft,
    resolve_speculative,
)

# -- metrics (registered once at import; one exposition surface) -------------
_REQUESTS = get_registry().counter(
    "tpuhive_generate_requests_total",
    "Generation requests by outcome: completed, rejected_queue, "
    "rejected_ratelimit, cancelled, timeout, failed.",
    labels=("outcome",))
_TOKENS = get_registry().counter(
    "tpuhive_generate_tokens_total",
    "Tokens emitted by the serving engine across all requests.")
_QUEUE_DEPTH = get_registry().gauge(
    "tpuhive_generate_queue_depth",
    "Requests waiting for a slot (admission queue occupancy).")
_QUEUE_CAPACITY = get_registry().gauge(
    "tpuhive_generate_queue_capacity",
    "Bound of the admission queue — depth/capacity == 1 is saturation.")
_SLOTS_BUSY = get_registry().gauge(
    "tpuhive_generate_slots_busy",
    "Slots currently occupied by a running sequence.")
_SLOTS_TOTAL = get_registry().gauge(
    "tpuhive_generate_slots_total",
    "Slot-pool capacity (the fixed decode batch size).")
_TTFT_SECONDS = get_registry().histogram(
    "tpuhive_generate_ttft_seconds",
    "Submit-to-first-token latency (queue wait + prefill + first step).")
_QUEUE_WAIT_SECONDS = get_registry().histogram(
    "tpuhive_generate_queue_wait_seconds",
    "Submit-to-slot-join latency: the admission-queue share of TTFT, "
    "separated so queue pressure and prefill cost are tunable apart "
    "(docs/OBSERVABILITY.md 'Request tracing & profiling').")
_INTERTOKEN_SECONDS = get_registry().histogram(
    "tpuhive_generate_intertoken_seconds",
    "Gap between consecutive emitted tokens of one sequence.")
_BATCH_EFFICIENCY = get_registry().histogram(
    "tpuhive_generate_batch_efficiency",
    "Active slots / capacity per decode step (1.0 = perfectly packed).",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
_KV_PAGES_FREE = get_registry().gauge(
    "tpuhive_generate_kv_pages_free",
    "Free KV-cache pages in the paged engine's pool (0 = admission is "
    "page-bound; the kv_pages_exhausted alert signal).")
_KV_PAGES_TOTAL = get_registry().gauge(
    "tpuhive_generate_kv_pages_total",
    "Usable KV-cache pages in the paged engine's pool (excludes the trash "
    "page parked slots write into).")
_SLOT_PAGES = get_registry().gauge(
    "tpuhive_generate_slot_kv_pages",
    "KV pages currently owned by each slot (0 when free or contiguous).",
    labels=("slot",))
_KV_BYTES_CAPACITY = get_registry().gauge(
    "tpuhive_generate_kv_bytes_capacity",
    "KV-cache HBM the paged pool can hold across all layers (payload + "
    "int8 scale side-arrays when kv_quant is on) — with _used, the "
    "bytes-level view of the int8 capacity doubling (docs/SERVING.md "
    "'Quantized KV pages').")
_KV_BYTES_USED = get_registry().gauge(
    "tpuhive_generate_kv_bytes_used",
    "KV-cache HBM currently backing granted pages across all layers — "
    "used/capacity is the byte-level pool fill the kv_quant sizing "
    "story is measured in.")
_MESH_DEVICES = get_registry().gauge(
    "tpuhive_generate_mesh_devices",
    "Devices in the serving mesh (dp x tp; 1 = single-chip engine).")
_PREFIX_HITS = get_registry().counter(
    "tpuhive_generate_prefix_hits_total",
    "Admitted requests whose prompt matched cached prefix pages (>= "
    "prefix_min_tokens skipped at prefill; docs/SERVING.md 'Prefix "
    "cache & chunked prefill').")
_PREFIX_MISSES = get_registry().counter(
    "tpuhive_generate_prefix_misses_total",
    "Admitted requests that paid a full private prefill (no usable "
    "cached prefix).")
_PREFIX_CACHED_PAGES = get_registry().gauge(
    "tpuhive_generate_prefix_cached_pages",
    "KV pages currently retained by the radix prefix cache (evictable "
    "under pool pressure once no slot shares them).")
_PREFIX_EVICTIONS = get_registry().counter(
    "tpuhive_generate_prefix_evictions_total",
    "Prefix-cache pages evicted under pool pressure — fast growth is the "
    "prefix_cache_thrash alert signal (docs/OBSERVABILITY.md).")
_PREFILL_CHUNKS = get_registry().histogram(
    "tpuhive_generate_prefill_chunks",
    "Prefill chunks dispatched per admitted request (0 = full prefix hit; "
    "long prompts split across scheduler ticks so decode latency stays "
    "flat — docs/SERVING.md 'Prefix cache & chunked prefill').",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0))
_SPEC_PROPOSED = get_registry().counter(
    "tpuhive_generate_spec_proposed_total",
    "Draft tokens proposed to the speculative verify pass (greedy slots "
    "only; docs/SERVING.md 'Speculative decoding').")
_SPEC_ACCEPTED = get_registry().counter(
    "tpuhive_generate_spec_accepted_total",
    "Draft tokens the target's batched verify accepted — "
    "accepted/proposed is the acceptance rate the spec_acceptance_low "
    "alert watches.")
_DEADLINE_TIMEOUTS = get_registry().counter(
    "tpuhive_generate_deadline_timeouts_total",
    "Requests whose per-request deadline expired, by phase: queue (never "
    "reached a slot), prefill (mid-chunk), decode (truncated mid-"
    "generation). Every timeout still ends its stream with a terminal "
    "chunk (docs/ROBUSTNESS.md 'Serving data plane').",
    labels=("phase",))
_HOST_KV_HITS = get_registry().counter(
    "tpuhive_generate_host_kv_hits_total",
    "Admitted requests whose prompt extended past the device-cached "
    "prefix into host-resident pages (>= 1 page promoted by DMA instead "
    "of recomputed; docs/SERVING.md 'KV-page tiering').")
_HOST_KV_MISSES = get_registry().counter(
    "tpuhive_generate_host_kv_misses_total",
    "Tier-on admissions the host store could not extend (no resident "
    "continuation past the device match) — hits/(hits+misses) is the "
    "host hit rate.")
_HOST_KV_DEMOTIONS = get_registry().counter(
    "tpuhive_generate_host_kv_demotions_total",
    "KV pages demoted (spilled) to the host-RAM store when the radix "
    "tree evicted them or their slot drained — sustained fast growth is "
    "the host_kv_thrash alert signal (docs/OBSERVABILITY.md).")
_HOST_KV_PROMOTIONS = get_registry().counter(
    "tpuhive_generate_host_kv_promotions_total",
    "KV pages promoted from the host store back into fresh device pages "
    "on a radix continuation hit (async copy lane; never blocks the "
    "pump).")
_HOST_KV_BYTES_USED = get_registry().gauge(
    "tpuhive_generate_host_kv_bytes_used",
    "Host RAM currently held by demoted int8 page payloads + scales.")
_HOST_KV_BYTES_CAPACITY = get_registry().gauge(
    "tpuhive_generate_host_kv_bytes_capacity",
    "Byte budget of the host page store ([generation_service] "
    "host_kv_bytes; 0 = tiering off).")

log = logging.getLogger(__name__)


# -- device functions ---------------------------------------------------------
#
# Both are jitted with EVERYTHING shape-determining static (config, slot
# count, cache length, bucket width, top_k) and all per-slot state traced:
# one step executable for the engine's lifetime, one prefill executable per
# prompt bucket. The cache is donated through both so the multi-hundred-MB
# buffer aliases in place instead of being copied per token.

def _step_body(params, tokens, positions, active, temps, cache, key,
               config: TransformerConfig, top_k: Optional[int]):
    """One fused decode step for the whole slot batch.

    tokens/positions/active/temps are [S] per-slot operands; each active
    slot consumes the token AT its own position and emits the token for
    position+1. Per-slot cache writes are a vmapped dynamic_update_slice
    (batched start indices lower to one scatter) into this layer's
    [S, max_len, Hkv, Dh] page of the 5-D buffer — the attend math itself
    is the SAME ``_decode_attend`` the single-tenant path uses (positions
    broadcast per slot), so serving and ``decode.generate`` cannot drift.
    """
    dtype = config.dtype
    x = params["tok_embed"].astype(dtype)[tokens][:, None, :]     # [S,1,D]
    rope_positions = positions[:, None]                           # [S,1]
    cache_k, cache_v = cache.k, cache.v

    write = jax.vmap(
        lambda row, update, position: jax.lax.dynamic_update_slice(
            row, update, (position, 0, 0)))

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v
        layer_k = write(cache_k[layer], k.astype(cache_k.dtype), positions)
        layer_v = write(cache_v[layer], v.astype(cache_v.dtype), positions)
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        # per-slot causal mask: broadcastable positions [S,1,1,1,1] against
        # the key iota inside _decode_attend
        return _decode_attend(q, cache_k[layer], cache_v[layer],
                              positions[:, None, None, None, None])

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, rope_positions,
                                        attend, layer_index=layer_index)
    chosen, key = _choose_next(params, x, tokens, active, temps, key,
                               config, top_k)
    return chosen, KVCache(k=cache_k, v=cache_v), key


def _choose_next(params, x, tokens, active, temps, key,
                 config: TransformerConfig, top_k: Optional[int]):
    """Shared step tail: final norm -> logits -> per-slot greedy/sampled
    choice. One copy for the contiguous and paged step bodies so the two
    cache layouts cannot drift in sampling semantics."""
    dtype = config.dtype
    x = _rmsnorm(x, params["final_norm"]["scale"])
    logits = jnp.dot(x[:, 0].astype(dtype), params["w_lm_head"].astype(dtype),
                     preferred_element_type=jnp.float32)           # [S,V]

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_temps = jnp.where(temps > 0.0, temps, 1.0)
    scaled = logits / safe_temps[:, None]
    if top_k is not None:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    key, sample_key = jax.random.split(key)
    sampled = jax.random.categorical(sample_key, scaled, axis=-1)
    chosen = jnp.where(temps > 0.0, sampled.astype(jnp.int32), greedy)
    # inactive slots keep their frozen token so their (harmless) writes
    # stay deterministic
    chosen = jnp.where(active, chosen, tokens)
    return chosen, key


_serving_step = functools.partial(
    jax.jit, static_argnames=("config", "top_k"),
    donate_argnames=("cache",))(_step_body)


def _paged_step_body(params, tokens, positions, active, temps, page_tables,
                     cache, key, config: TransformerConfig,
                     top_k: Optional[int], use_kernel: bool = False,
                     interpret: bool = False, mesh=None,
                     shard_heads: bool = False):
    """One fused decode step over the PAGED cache.

    Identical to :func:`_step_body` except for where K/V live: the cache is
    ``[layers, 1 + num_pages, page_size, kv_heads, d_head]`` and each slot's
    write lands at ``(page_tables[slot, pos // page_size], pos % page_size)``
    — a scatter with per-slot (page, offset) indices instead of a vmapped
    row update. ``page_tables`` is a TRACED operand like every other piece
    of per-slot state, so page assignment (the thing that changes on every
    join/leave) never produces a new shape and never recompiles — the same
    discipline that makes the contiguous engine's joins free.

    ``use_kernel``/``interpret`` are STATIC (they pick the attend dispatch,
    resolved once at engine construction from the ``paged_kernel`` knob):
    True streams K/V through the fused pallas kernel
    (``ops/paged_attention.py``) instead of the XLA page gather —
    fingerprinted separately as ``serving_paged_step_kernel`` so operators
    can see which dispatch compiled.

    Parked slots (``active`` False, page-table row reset to the trash page,
    position frozen at 0) scatter their garbage K/V into physical page 0,
    which no live sequence's page table ever references — the paged
    equivalent of the contiguous engine's "parked writes land in the
    parked slot's own row" argument.

    With the int8 cache (``cache`` is a :class:`QuantKVCache` —
    ``kv_quant = on``) each write quantizes onto its page's running-max
    scale (ops/kv_quant.py) and the attend dequantizes through both
    dispatches; the branch is picked by the cache PYTREE TYPE at trace
    time, so ``kv_quant=off`` engines trace the identical computation they
    always did (the byte-identical rollback).
    """
    dtype = config.dtype
    x = params["tok_embed"].astype(dtype)[tokens][:, None, :]     # [S,1,D]
    rope_positions = positions[:, None]                           # [S,1]
    cache_k, cache_v = cache.k, cache.v
    quant = isinstance(cache, QuantKVCache)
    scale_k = cache.k_scale if quant else None
    scale_v = cache.v_scale if quant else None
    page_size = cache_k.shape[2]
    slot_ids = jnp.arange(tokens.shape[0])
    pages = page_tables[slot_ids, positions // page_size]         # [S]
    offsets = positions % page_size                               # [S]

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v, scale_k, scale_v
        if quant:
            layer_k, layer_ks = kvq.step_write(
                cache_k[layer], scale_k[layer], pages, offsets, k[:, 0])
            layer_v, layer_vs = kvq.step_write(
                cache_v[layer], scale_v[layer], pages, offsets, v[:, 0])
            scale_k = jax.lax.dynamic_update_slice(
                scale_k, layer_ks[None], (layer, 0, 0))
            scale_v = jax.lax.dynamic_update_slice(
                scale_v, layer_vs[None], (layer, 0, 0))
        else:
            layer_k = cache_k[layer].at[pages, offsets].set(
                k[:, 0].astype(cache_k.dtype))
            layer_v = cache_v[layer].at[pages, offsets].set(
                v[:, 0].astype(cache_v.dtype))
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        return _paged_attend(q, cache_k[layer], cache_v[layer], page_tables,
                             positions, use_kernel=use_kernel,
                             interpret=interpret, mesh=mesh,
                             shard_heads=shard_heads,
                             k_scales=scale_k[layer] if quant else None,
                             v_scales=scale_v[layer] if quant else None)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, rope_positions,
                                        attend, layer_index=layer_index)
    chosen, key = _choose_next(params, x, tokens, active, temps, key,
                               config, top_k)
    if quant:
        return chosen, QuantKVCache(k=cache_k, v=cache_v, k_scale=scale_k,
                                    v_scale=scale_v), key
    return chosen, KVCache(k=cache_k, v=cache_v), key


_paged_serving_step = functools.partial(
    jax.jit,
    static_argnames=("config", "top_k", "use_kernel", "interpret", "mesh",
                     "shard_heads"),
    donate_argnames=("cache",))(_paged_step_body)


def _prefill_body(params, head, cache, slot, real_len,
                  config: TransformerConfig):
    """Prefill one joining sequence's prompt head into its slot row.

    ``head`` is [1, W] with W a power-of-two bucket; ``real_len`` (traced)
    zero-masks the padded K/V writes and ``slot`` (traced) selects the row,
    so every prompt length in a bucket — in any slot — reuses ONE
    executable. Mirrors models/decode.py::_prefill_body, with the write
    offset at (layer, slot, 0, 0, 0) instead of a whole-batch write.
    ``config.use_flash`` picks the attention impl like the training attend
    does (runtimes without the pallas kernels serve via the XLA reference
    path — identical math, tested exact in f32)."""
    from ..models.transformer import flash_attention
    from ..ops.flash_attention import reference_attention

    dtype = config.dtype
    batch, width = head.shape
    x = params["tok_embed"].astype(dtype)[head]
    positions = jnp.broadcast_to(jnp.arange(width, dtype=jnp.int32),
                                 (batch, width))
    valid = (jnp.arange(width, dtype=jnp.int32)
             < real_len)[None, :, None, None]
    cache_k, cache_v = cache.k, cache.v

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v
        write_k = jnp.where(valid, k, 0).astype(cache_k.dtype)
        write_v = jnp.where(valid, v, 0).astype(cache_v.dtype)
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, write_k[None], (layer, slot, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, write_v[None], (layer, slot, 0, 0, 0))
        if config.use_flash:
            return flash_attention(q, k, v, causal=True)
        return reference_attention(q, k, v, causal=True)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, positions, attend,
                                        layer_index=layer_index)
    return KVCache(k=cache_k, v=cache_v)


_serving_prefill = functools.partial(
    jax.jit, static_argnames=("config",),
    donate_argnames=("cache",))(_prefill_body)


def _paged_prefill_body(params, head, cache, page_table_row, real_len,
                        config: TransformerConfig):
    """Prefill one joining sequence's prompt head through its page table.

    Mirrors :func:`_prefill_body` — same trunk pass, same bucketed ``head``
    [1, W], same traced ``real_len`` — but the K/V of prompt position ``w``
    scatters to ``(page_table_row[w // page_size], w % page_size)`` in the
    paged cache instead of ``(layer, slot, w)``. ``page_table_row`` [mp] is
    a traced operand: one executable per bucket width serves every page
    assignment.

    Padded positions (``w >= real_len``) are routed OUT OF BOUNDS and
    dropped (``mode="drop"``) rather than zero-masked like the contiguous
    path: a padded write must not touch ANY physical page — entries of the
    row beyond the request's allocation still point at the trash page, and
    scribbling zeros there would race other joiners' padded writes for no
    benefit. The dropped cells hold stale garbage until the decode steps
    rewrite them position by position before first attending them — the
    same rewrite-before-attend argument the contiguous engine pins with
    test_slot_reuse_matches_fresh_engine."""
    from ..models.transformer import flash_attention
    from ..ops.flash_attention import reference_attention

    dtype = config.dtype
    batch, width = head.shape
    x = params["tok_embed"].astype(dtype)[head]
    positions = jnp.broadcast_to(jnp.arange(width, dtype=jnp.int32),
                                 (batch, width))
    num_physical = cache.k.shape[1]
    page_size = cache.k.shape[2]
    token_index = jnp.arange(width, dtype=jnp.int32)
    valid = token_index < real_len
    pages = jnp.where(valid, page_table_row[token_index // page_size],
                      num_physical)                       # OOB -> dropped
    offsets = token_index % page_size
    cache_k, cache_v = cache.k, cache.v
    quant = isinstance(cache, QuantKVCache)
    scale_k = cache.k_scale if quant else None
    scale_v = cache.v_scale if quant else None

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v, scale_k, scale_v
        if quant:
            # quantize-on-write through the row (ops/kv_quant.row_merge);
            # the prompt attends its own UNWRITTEN k/v below, exactly like
            # the f32 path, so only storage changes here
            layer_k, layer_ks, _ = kvq.row_merge(
                cache_k[layer], scale_k[layer], page_table_row[None],
                k, token_index[None], valid[None], dtype)
            layer_v, layer_vs, _ = kvq.row_merge(
                cache_v[layer], scale_v[layer], page_table_row[None],
                v, token_index[None], valid[None], dtype)
            scale_k = jax.lax.dynamic_update_slice(
                scale_k, layer_ks[None], (layer, 0, 0))
            scale_v = jax.lax.dynamic_update_slice(
                scale_v, layer_vs[None], (layer, 0, 0))
        else:
            layer_k = cache_k[layer].at[pages, offsets].set(
                k[0].astype(cache_k.dtype), mode="drop")
            layer_v = cache_v[layer].at[pages, offsets].set(
                v[0].astype(cache_v.dtype), mode="drop")
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        if config.use_flash:
            return flash_attention(q, k, v, causal=True)
        return reference_attention(q, k, v, causal=True)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, positions, attend,
                                        layer_index=layer_index)
    if quant:
        return QuantKVCache(k=cache_k, v=cache_v, k_scale=scale_k,
                            v_scale=scale_v)
    return KVCache(k=cache_k, v=cache_v)


_paged_serving_prefill = functools.partial(
    jax.jit, static_argnames=("config",),
    donate_argnames=("cache",))(_paged_prefill_body)


def _chunk_attend(q, k_ctx, v_ctx, q_positions):
    """Attention for a prefill chunk that does NOT start at position 0:
    queries [1, W, H, Dh] against the slot's whole gathered page run
    [K, Hkv, Dh] (cached prefix + earlier chunks + this chunk's own writes,
    laid out in logical order), masked to ``key_pos <= q_pos``.

    Mirrors :func:`~tensorhive_tpu.ops.flash_attention.reference_attention`
    term for term — GQA expanded with ``jnp.repeat``, f32 scores/probs, the
    same scale — except the causal ``tril`` becomes a positional mask (the
    chunk's queries sit at ``start + w``, its keys at absolute logical
    positions). Entries past the query position hold trash-page garbage or
    not-yet-written cells; the mask sends them to NEG_INF, the softmax
    underflows them to exactly 0.0, and 0.0 x finite garbage contributes
    exact zeros — the same argument that makes the paged decode gather
    f32-exact against the contiguous cache (models/decode._paged_attend)."""
    from ..ops.flash_attention import NEG_INF

    if k_ctx.shape[1] != q.shape[2]:
        group = q.shape[2] // k_ctx.shape[1]
        k_ctx = jnp.repeat(k_ctx, group, axis=1)
        v_ctx = jnp.repeat(v_ctx, group, axis=1)
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,khd->bhqk", q.astype(jnp.float32),
                        k_ctx.astype(jnp.float32)) * scale
    key_positions = jax.lax.iota(jnp.int32, k_ctx.shape[0])
    mask = (key_positions[None, None, None, :]
            <= q_positions[None, None, :, None])
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,khd->bqhd", probs, v_ctx.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_chunk_prefill_body(params, head, cache, page_table_row, start,
                              real_len, config: TransformerConfig):
    """Prefill ONE CHUNK of a joining prompt, starting mid-sequence.

    The workhorse of the prefix cache and of chunked prefill
    (docs/SERVING.md "Prefix cache & chunked prefill"): ``head`` is
    [1, W] holding prompt positions ``start .. start + real_len - 1``
    (W a power-of-two bucket, the tail zero-padded), and ``start`` is a
    TRACED operand — a cache hit prefills only the uncached suffix, and a
    long prompt runs through this executable once per scheduler tick, so
    neither the skip offset nor the chunk count ever mints a new shape.

    Differences from :func:`_paged_prefill_body` (which remains the
    ``prefix_cache=off`` byte-identical rollback path):

    * K/V writes scatter to ``(page_table_row[(start + w) // ps],
      (start + w) % ps)`` — the page indices beyond this chunk are never
      touched, padded positions route out of bounds and drop.
    * attention CANNOT be a pure within-window pass: queries at
      ``start + w`` must see positions ``0 .. start + w``, whose K/V live
      in the slot's pages (shared prefix pages a previous request
      computed, or this request's own earlier chunks). Writes land first,
      then the whole row gathers into logical order and
      :func:`_chunk_attend` applies the positional causal mask.

    A chunk that starts at 0 with ``real_len`` covering the whole head is
    mathematically the full prefill — the two bodies agree f32-exactly
    (pinned by the tri-equality tests running both paths against
    ``decode.generate``)."""
    dtype = config.dtype
    batch, width = head.shape
    x = params["tok_embed"].astype(dtype)[head]
    chunk_offsets = jnp.arange(width, dtype=jnp.int32)
    global_positions = start + chunk_offsets                    # [W]
    positions = jnp.broadcast_to(global_positions, (batch, width))
    num_physical = cache.k.shape[1]
    page_size = cache.k.shape[2]
    valid = chunk_offsets < real_len
    pages = jnp.where(valid, page_table_row[global_positions // page_size],
                      num_physical)                    # OOB -> dropped
    page_offsets = global_positions % page_size
    window = page_table_row.shape[0] * page_size
    safe_logical = jnp.clip(global_positions, 0, window - 1)
    cache_k, cache_v = cache.k, cache.v
    quant = isinstance(cache, QuantKVCache)
    scale_k = cache.k_scale if quant else None
    scale_v = cache.v_scale if quant else None

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v, scale_k, scale_v
        if quant:
            # merge-quantize-requantize through the row (ops/kv_quant.
            # row_merge), then attend the DEQUANTIZED post-write context —
            # the chunk sees byte-for-byte what any later reader (a
            # prefix-cache hit above all) will dequantize, which is what
            # pins hit == miss token identity under int8
            layer_k, layer_ks, ctx_k = kvq.row_merge(
                cache_k[layer], scale_k[layer], page_table_row[None],
                k, safe_logical[None], valid[None], dtype)
            layer_v, layer_vs, ctx_v = kvq.row_merge(
                cache_v[layer], scale_v[layer], page_table_row[None],
                v, safe_logical[None], valid[None], dtype)
            scale_k = jax.lax.dynamic_update_slice(
                scale_k, layer_ks[None], (layer, 0, 0))
            scale_v = jax.lax.dynamic_update_slice(
                scale_v, layer_vs[None], (layer, 0, 0))
            cache_k = jax.lax.dynamic_update_slice(
                cache_k, layer_k[None], (layer, 0, 0, 0, 0))
            cache_v = jax.lax.dynamic_update_slice(
                cache_v, layer_v[None], (layer, 0, 0, 0, 0))
            return _chunk_attend(q, ctx_k[0], ctx_v[0], global_positions)
        layer_k = cache_k[layer].at[pages, page_offsets].set(
            k[0].astype(cache_k.dtype), mode="drop")
        layer_v = cache_v[layer].at[pages, page_offsets].set(
            v[0].astype(cache_v.dtype), mode="drop")
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        # gather AFTER the writes: within-chunk causality comes from the
        # positional mask, exactly like the decode step's write-then-attend
        ctx_k = layer_k[page_table_row].reshape(window, *layer_k.shape[2:])
        ctx_v = layer_v[page_table_row].reshape(window, *layer_v.shape[2:])
        return _chunk_attend(q, ctx_k, ctx_v, global_positions)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, positions, attend,
                                        layer_index=layer_index)
    if quant:
        return QuantKVCache(k=cache_k, v=cache_v, k_scale=scale_k,
                            v_scale=scale_v)
    return KVCache(k=cache_k, v=cache_v)


_paged_chunk_serving_prefill = functools.partial(
    jax.jit, static_argnames=("config",),
    donate_argnames=("cache",))(_paged_chunk_prefill_body)


def _page_extract_body(cache, page_ids):
    """Gather whole int8 pages + scale rows out of the quantized paged
    cache for DEMOTION to the host tier (docs/SERVING.md "KV-page
    tiering"). ``page_ids`` is a fixed-width [W] operand (W =
    max_pages_per_slot) padded with ``TRASH_PAGE`` — padded lanes gather
    trash-page garbage the host side discards, so any demotion batch
    size reuses one executable. The cache is NOT donated: this is a pure
    read, and because all executables chain through the one donated
    cache buffer on the single pump thread, dispatching the extract
    BEFORE any overwriting prefill guarantees it reads the pre-overwrite
    bytes (the same dispatched-order argument the prefix cache's
    readiness rule rests on)."""
    k, k_scale = kvq.extract_pages(cache.k, cache.k_scale, page_ids)
    v, v_scale = kvq.extract_pages(cache.v, cache.v_scale, page_ids)
    return k, k_scale, v, v_scale


_serving_page_extract = jax.jit(_page_extract_body)


def _page_inject_body(cache, page_ids, k, k_scale, v, v_scale):
    """Scatter host-staged int8 pages + scales into freshly-allocated
    physical pages: the device half of PROMOTION. ``page_ids`` is the
    same fixed [W] width as the extract, padded with an out-of-range id
    so ``mode="drop"`` discards the zero payload in unused lanes. The
    cache IS donated (this write joins the step/prefill dispatch chain
    in place); byte-identity of a host round-trip is exact because the
    int8 payload and f32 scales come back untouched — no re-quantization
    happens in either direction."""
    new_k, new_ks = kvq.inject_pages(cache.k, cache.k_scale, page_ids,
                                     k, k_scale)
    new_v, new_vs = kvq.inject_pages(cache.v, cache.v_scale, page_ids,
                                     v, v_scale)
    return QuantKVCache(k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs)


_serving_page_inject = functools.partial(
    jax.jit, donate_argnames=("cache",))(_page_inject_body)


# -- request plumbing ---------------------------------------------------------

#: handle event kinds
TOKEN, DONE, ERROR = "token", "done", "error"


class GenerationHandle:
    """Consumer side of one request: a bounded event stream plus final
    summary. ``tokens()`` is what the streaming endpoint iterates."""

    def __init__(self, engine: "SlotEngine", request: "_Request") -> None:
        self._engine = engine
        self._request = request
        self._events: "queue_module.Queue[tuple]" = queue_module.Queue()
        self._summary: Optional[Dict] = None

    # -- engine side ------------------------------------------------------
    def _push(self, kind: str, payload: object) -> None:
        self._events.put((kind, payload))

    # -- consumer side ----------------------------------------------------
    def tokens(self, timeout_s: float = 30.0):
        """Yield generated token ids as they are produced. Raises
        ``TimeoutError`` if the engine produces nothing for ``timeout_s``
        (a wedged pump must cost the client a bounded wait, never a hung
        connection) and ``RuntimeError`` on engine-side failure."""
        while True:
            try:
                kind, payload = self._events.get(timeout=timeout_s)
            except queue_module.Empty:
                self.cancel()
                raise TimeoutError(
                    f"no token within {timeout_s:.0f}s") from None
            if kind == TOKEN:
                yield payload
            elif kind == DONE:
                self._summary = payload
                return
            else:
                raise RuntimeError(str(payload))

    def result(self, timeout_s: float = 30.0) -> Dict:
        """Drain the stream and return the completion summary."""
        if self._summary is None:
            for _ in self.tokens(timeout_s=timeout_s):
                pass
        assert self._summary is not None
        return self._summary

    def cancel(self) -> None:
        """Mark the request cancelled; the engine frees its slot (or drops
        it from the queue) at the next scheduler iteration."""
        self._engine._cancel(self._request)

    @property
    def done(self) -> bool:
        return self._request.finished

    @property
    def request_id(self) -> str:
        """The id the ledger, the tracer spans and the ``X-Request-Id``
        response header all key on (docs/OBSERVABILITY.md)."""
        return self._request.request_id


@dataclasses.dataclass
class _Request:
    prompt: List[int]
    max_new_tokens: int
    temperature: float
    user_key: Optional[str]
    submitted_ts: float
    request_id: str = ""
    #: wall-clock anchor for the submitted_ts engine-clock stamp: spans and
    #: ledger rows translate engine-clock offsets onto this so fake clocks
    #: stay exact while humans still get unix timestamps
    submitted_wall: float = 0.0
    record: Optional[object] = None          # observability RequestRecord
    handle: Optional[GenerationHandle] = None
    generated: List[int] = dataclasses.field(default_factory=list)
    first_token_ts: Optional[float] = None
    last_token_ts: Optional[float] = None
    #: engine-clock stamp past which the request times out (queue, prefill
    #: or mid-decode); None = no deadline (docs/ROBUSTNESS.md)
    deadline_ts: Optional[float] = None
    cancelled: bool = False
    finished: bool = False
    #: resource-time integrals the tenant meter accumulated for THIS
    #: request (observability/accounting.py); finalized onto the ledger
    #: row at finish — stay 0.0 while no meter is installed
    device_seconds: float = 0.0
    kv_byte_seconds: float = 0.0

    def wall(self, clock_ts: float) -> float:
        """Translate an engine-clock stamp to wall-clock seconds."""
        return self.submitted_wall + (clock_ts - self.submitted_ts)


@dataclasses.dataclass
class _Slot:
    request: _Request
    joined_ts: float
    #: tokens the prefix cache let this request skip (0 = full miss)
    cached_tokens: int = 0
    #: next prompt position to prefill; == prefill_target once armed
    prefill_next: int = 0
    #: last prompt position exclusive (prompt_len - 1; the final token
    #: goes through the decode step, as everywhere)
    prefill_target: int = 0
    #: False while chunks are still being dispatched — the slot is held
    #: out of the decode batch (active stays False) until armed
    prefill_done: bool = True
    prefill_chunks: int = 0
    prefill_ms: float = 0.0
    prefill_started_ts: float = 0.0
    prefill_compile: Optional[str] = None
    # -- host tier (docs/SERVING.md "KV-page tiering") --------------------
    #: pages granted from the host store at admission (0 = no host hit)
    host_hit_pages: int = 0
    #: store entries to promote; drained into the copy lane by _join
    promote_entries: List[HostPageEntry] = dataclasses.field(
        default_factory=list)
    #: physical destination pages (the fresh pages right after the
    #: device-shared run in this slot's page-table row)
    promote_pages: List[int] = dataclasses.field(default_factory=list)
    #: prompt tokens covered once the inject lands — prefill resumes here
    promote_boundary: int = 0
    #: in-flight HtoD staging job; while set the slot is PARKED exactly
    #: like mid-chunk-prefill (never enters the decode batch, cancel and
    #: deadline still fire) so a slow DMA can never stall the pump
    promote_job: Optional[LaneJob] = None
    promote_started_ts: float = 0.0
    promote_ms: float = 0.0


class SlotEngine:
    """The continuous-batching scheduler + device state.

    Host-side bookkeeping (queue, slot table, per-user counts, metrics) is
    guarded by one lock; device calls happen OUTSIDE the lock and only ever
    from the single pump thread (GenerationService), so submitters are never
    blocked behind a decode step.
    """

    def __init__(
        self,
        params,
        config: TransformerConfig,
        *,
        slots: int = 8,
        max_len: Optional[int] = None,
        queue_depth: int = 32,
        top_k: Optional[int] = None,
        eos_token: Optional[int] = None,
        max_new_tokens_cap: int = 512,
        max_concurrent_per_user: int = 0,
        paged: bool = True,
        page_size: int = 16,
        kv_pages: int = 0,
        paged_kernel: str = "auto",
        kv_quant: str = "auto",
        prefix_cache: str = "auto",
        prefix_min_tokens: int = 32,
        prefill_chunk_tokens: int = 256,
        host_kv_bytes: int = 0,
        speculative: str = "auto",
        draft_preset: str = "",
        draft_layers: int = 0,
        spec_tokens: int = 4,
        mesh=None,
        default_deadline_s: float = 0.0,
        max_deadline_s: float = 600.0,
        fault_plan: Optional[ServingFaultPlan] = None,
        clock: Callable[[], float] = time.monotonic,
        flight_recorder=None,
        tenant_meter=None,
    ) -> None:
        if not config.causal:
            raise ValueError("serving needs an autoregressive model; this "
                             "config is a bidirectional encoder")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if top_k is not None and not 0 < top_k <= config.vocab_size:
            raise ValueError(
                f"top_k must be in (0, {config.vocab_size}], got {top_k}")
        self.config = config
        self.capacity = int(slots)
        self.max_len = int(max_len or config.max_seq_len)
        self.queue_depth = int(queue_depth)
        self.top_k = top_k
        self.eos_token = eos_token
        self.max_new_tokens_cap = int(max_new_tokens_cap)
        self.max_concurrent_per_user = int(max_concurrent_per_user)
        self.paged = bool(paged)
        # -- int8 KV pages (docs/SERVING.md "Quantized KV pages"): auto =
        # on for the paged layout (the page is the quantization unit);
        # off = the byte-identical f32/bf16 rollback — the legacy
        # executables with their legacy fingerprints, never a quant op
        self.kv_quant = kvq.resolve_kv_quant(kv_quant, self.paged)
        self._quant = self.kv_quant == "on"
        self.clock = clock
        # -- fault tolerance (docs/ROBUSTNESS.md "Serving data plane") -----
        if default_deadline_s < 0 or max_deadline_s <= 0:
            raise ValueError(
                f"deadlines must be positive (default_deadline_s >= 0), got "
                f"default={default_deadline_s} max={max_deadline_s}")
        if default_deadline_s > max_deadline_s:
            raise ValueError(
                f"default_deadline_s={default_deadline_s} exceeds "
                f"max_deadline_s={max_deadline_s}")
        #: per-request wall budget applied when submit() gets no override;
        #: 0 = no deadline (the pre-PR 14 behavior, byte-identical)
        self.default_deadline_s = float(default_deadline_s)
        self.max_deadline_s = float(max_deadline_s)
        #: deterministic fault injection seam: every device dispatch
        #: consults the plan first (serving/faults.py); None in production
        self.fault_plan = fault_plan
        #: per-tick black box (serving/flight_recorder.py); None keeps
        #: step() byte-identical to the unrecorded path — the
        #: [generation_service] flight_recorder=off rollback
        self.flight_recorder = flight_recorder
        #: per-tenant resource-time attribution (observability/
        #: accounting.py TenantMeter); pure host bookkeeping stamped from
        #: the pump thread, never a traced operand. None keeps every hook
        #: a single attribute check — the [accounting] enabled=false
        #: rollback
        self.tenant_meter = tenant_meter
        #: drain mode: admission refused (EngineDrainingError -> 503 +
        #: Retry-After at the API edge) while in-flight requests finish
        self._draining = False

        # -- serving mesh (docs/SERVING.md "Multi-chip serving") -----------
        # mesh=None is the single-chip engine, byte-identical to PR 6-8:
        # params/cache stay wherever jax puts them and the executables keep
        # their original compile fingerprints (the rollback contract). With
        # a mesh, params shard via the training MeshRules machinery (heads/
        # ffn/vocab over tp, GQA-guarded), the cache pool axis shards over
        # dp so capacity scales with chips, and every per-slot operand is
        # device_put REPLICATED — still traced, so joins/leaves/page
        # assignment keep the zero-recompile contract under sharding.
        self.mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from ..parallel.mesh import (
                serving_cache_spec,
                serving_rules,
                tree_shardings,
            )

            axis_sizes = dict(mesh.shape)
            self.mesh_dp = int(axis_sizes.get("dp", 1))
            self.mesh_tp = int(axis_sizes.get("tp", 1))
            self._rules = serving_rules(config, self.mesh_tp)
            self._replicated = NamedSharding(mesh, PartitionSpec())
            self._cache_spec = serving_cache_spec(self._rules)
            self.params = jax.device_put(
                params, tree_shardings(mesh, params, self._rules))
        else:
            self.mesh_dp = self.mesh_tp = 1
            self._rules = None
            self._replicated = None
            self._cache_spec = None
            self.params = params

        self._lock = lockwitness.Lock("SlotEngine._lock",
                                      observe_wait=True)
        self._pending: Deque[_Request] = collections.deque()
        self._slots: List[Optional[_Slot]] = [None] * self.capacity
        self._user_active: Dict[str, int] = {}
        self.completed_requests = 0
        self.emitted_tokens = 0
        self.steps = 0
        #: busy slot-second integral, accumulated from the SAME dt samples
        #: the tenant meter charges from, so the conservation invariant
        #: sum(tenant device-seconds) == busy_slot_seconds x num_devices
        #: is exact under a fake clock (tests/unit/test_accounting.py);
        #: stays 0.0 while no meter is installed
        self.busy_slot_seconds = 0.0
        self._last_meter_ts: Optional[float] = None
        #: private latency views backing ``stats()`` p50/p95 (the registry
        #: children are shared across engine instances in tests)
        self._ttft_hist = Histogram()
        self._intertoken_hist = Histogram()
        self._queue_wait_hist = Histogram()

        # device state: one persistent cache + per-slot operand arrays
        # (host numpy masters; tiny, shipped per step)
        if self.paged:
            if page_size < 1:
                raise ValueError(f"page_size must be >= 1, got {page_size}")
            self.page_size = int(page_size)
            # resolve the paged_kernel knob ONCE (auto|on|off ->
            # pallas|xla); the result rides into the step executable as a
            # STATIC arg, so the dispatch is part of the compile
            # fingerprint, never a per-step branch
            self.paged_kernel = resolve_paged_kernel(
                paged_kernel, page_size=self.page_size,
                kv_heads=config.kv_heads, d_head=config.d_head,
                heads=config.n_heads, dtype=config.dtype,
                mesh_devices=self.mesh_dp * self.mesh_tp,
                quant=self._quant)
            self._use_kernel = self.paged_kernel == "pallas"
            self._kernel_interpret = jax.default_backend() != "tpu"
            max_pages_per_slot = -(-self.max_len // self.page_size)
            #: HBM one page costs across all layers (payload + the int8
            #: scale side-arrays when quantized) — the byte-accounting
            #: unit behind the kv_bytes gauges and kvBytesPerToken
            self._page_hbm_bytes = config.n_layers * (
                kvq.quant_page_bytes(self.page_size, config.kv_heads,
                                     config.d_head)
                if self._quant else
                kvq.page_bytes(self.page_size, config.kv_heads,
                               config.d_head,
                               jnp.dtype(config.dtype).itemsize))
            #: 0 = the contiguous engine's HBM at the same slot count — the
            #: rollback-neutral default; with kv_quant on the SAME byte
            #: budget holds more int8 pages (the capacity-doubling story:
            #: 2x vs bf16, ~4x vs f32, minus the scale side-array), so the
            #: default pool converts that headroom into pages outright
            if kv_pages:
                num_pages = int(kv_pages)
            else:
                num_pages = self.capacity * max_pages_per_slot
                if self._quant:
                    dtype_page = kvq.page_bytes(
                        self.page_size, config.kv_heads, config.d_head,
                        jnp.dtype(config.dtype).itemsize)
                    num_pages = (num_pages * dtype_page
                                 // kvq.quant_page_bytes(
                                     self.page_size, config.kv_heads,
                                     config.d_head))
                    num_pages -= num_pages % self.mesh_dp
            if num_pages % self.mesh_dp:
                raise ValueError(
                    f"kv_pages={num_pages} must be divisible by mesh "
                    f"dp={self.mesh_dp} (the page pool shards over dp)")
            # the pages axis shards over dp, and jax refuses uneven
            # shardings — reserve dp trash rows (page 0 + dp-1 padding)
            # so trash + usable stays divisible (paging.PagePool)
            self._pool = PagePool(num_pages=num_pages,
                                  page_size=self.page_size,
                                  slots=self.capacity,
                                  max_pages_per_slot=max_pages_per_slot,
                                  trash_pages=self.mesh_dp)
            shape = (config.n_layers, self._pool.physical_pages,
                     self.page_size, config.kv_heads, config.d_head)
        else:
            if prefix_cache == "on":
                raise ValueError(
                    "prefix_cache=on needs the paged cache layout (pages "
                    "are the sharing unit); set paged=true or prefix_cache="
                    "auto/off")
            self.page_size = None
            self._pool = None
            self.paged_kernel = None
            self._use_kernel = False
            self._kernel_interpret = False
            self._page_hbm_bytes = None
            #: one slot's reserved contiguous KV footprint (the whole
            #: max_len row, K+V, all layers) — the byte-accounting unit
            #: the tenant meter charges for slot residency when there is
            #: no page pool to count
            self._slot_kv_bytes = (2 * config.n_layers * self.max_len
                                   * config.kv_heads * config.d_head
                                   * jnp.dtype(config.dtype).itemsize)
            if self.capacity % self.mesh_dp:
                raise ValueError(
                    f"slots={self.capacity} must be divisible by mesh "
                    f"dp={self.mesh_dp} (the slot pool shards over dp)")
            shape = (config.n_layers, self.capacity, self.max_len,
                     config.kv_heads, config.d_head)
        #: kernel dispatch under a mesh: the pallas call runs in shard_map
        #: (models/decode._paged_attend), splitting q heads AND kv_heads
        #: over tp only when both divide — contiguous head blocks keep the
        #: GQA ``i // group`` mapping aligned per shard; otherwise the
        #: kernel runs replicated (the GQA guard's kernel analog)
        self._kernel_shard_heads = (
            self.mesh is not None and self._rules.heads == "tp"
            and self._rules.kv_heads == "tp")
        if self._quant:
            # int8 payload + per-(page, kv_head) f32 scale side-arrays,
            # indexed by the same physical page ids the tables resolve
            scale_shape = (config.n_layers, shape[1], config.kv_heads)
            self._cache = QuantKVCache(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                k_scale=jnp.zeros(scale_shape, jnp.float32),
                v_scale=jnp.zeros(scale_shape, jnp.float32))
        else:
            self._cache = KVCache(k=jnp.zeros(shape, config.dtype),
                                  v=jnp.zeros(shape, config.dtype))
        if self.mesh is not None:
            from jax.sharding import NamedSharding

            from ..parallel.mesh import normalized_spec, serving_scale_spec

            cache_spec = self._cache_spec
            scale_spec = serving_scale_spec(self._rules)
            if self._use_kernel:
                # page tables hold GLOBAL physical indices, so the kernel's
                # shard_map needs every shard to hold the whole page pool:
                # pages replicate (no dp sharding) and the kv_heads axis
                # shards only when the head-aligned split applies — the
                # scale side-arrays follow their pages
                cache_spec = normalized_spec(
                    None, None, None,
                    "tp" if self._kernel_shard_heads else None, None)
                scale_spec = normalized_spec(
                    None, None, "tp" if self._kernel_shard_heads else None)
            sharding = NamedSharding(self.mesh, cache_spec)
            if self._quant:
                scale_sharding = NamedSharding(self.mesh, scale_spec)
                self._cache = jax.device_put(
                    self._cache, QuantKVCache(
                        k=sharding, v=sharding,
                        k_scale=scale_sharding, v_scale=scale_sharding))
            else:
                self._cache = jax.device_put(
                    self._cache, KVCache(k=sharding, v=sharding))
        self._tokens = np.zeros(self.capacity, np.int32)
        self._positions = np.zeros(self.capacity, np.int32)
        self._active = np.zeros(self.capacity, bool)
        self._temps = np.zeros(self.capacity, np.float32)
        self._key = self._operand(jax.random.PRNGKey(0))

        # -- radix prefix cache + chunked prefill (docs/SERVING.md "Prefix
        # cache & chunked prefill"). auto = on for the paged layout (the
        # shared-prefix capacity/TTFT lever is the default serving story),
        # off for contiguous (no pages, nothing to share). "off" is the
        # byte-identical PR 7-10 rollback: the legacy whole-prompt prefill
        # executable, untouched fingerprints, refcounts all 1.
        if prefix_cache not in ("auto", "on", "off"):
            raise ValueError(
                f"prefix_cache must be auto|on|off, got {prefix_cache!r}")
        if prefill_chunk_tokens < 0:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 0, got "
                f"{prefill_chunk_tokens}")
        self.prefix_cache = ("on" if self.paged and prefix_cache != "off"
                             else "off")
        self.prefix_min_tokens = max(0, int(prefix_min_tokens))
        #: per-chunk position budget; 0 = one chunk per prompt (the
        #: executable still handles the start offset for cache hits)
        self.prefill_chunk_tokens = int(prefill_chunk_tokens)
        #: the new-subsystem dispatch switch: prefix on routes ALL prefills
        #: (miss included, start=0) through the chunked executable so one
        #: code path serves hit/miss/chunked; off keeps the legacy pair
        self._use_chunk_prefill = self.prefix_cache == "on"
        self._prefix = (PrefixCache(self._pool,
                                    min_tokens=self.prefix_min_tokens)
                        if self._use_chunk_prefill else None)
        self.prefix_hits = 0
        self.prefix_misses = 0

        # -- KV-page tiering (docs/SERVING.md "KV-page tiering"). A bounded
        # host-RAM store catches pages the radix tree would otherwise
        # discard (eviction victims, drained slots' prefixes) and hands
        # them back by DMA on the next content hit — re-fill at copy
        # bandwidth instead of recompute FLOPs. All tier state is host
        # bookkeeping behind the same traced page tables, so tier
        # membership can never recompile; host_kv_bytes=0 is the
        # byte-identical rollback (no store, no lane, no spill hook, and
        # the extract/inject executables are never compiled).
        if host_kv_bytes < 0:
            raise ValueError(
                f"host_kv_bytes must be >= 0, got {host_kv_bytes}")
        self.host_kv_bytes = int(host_kv_bytes)
        if self.host_kv_bytes:
            if not (self.paged and self._quant
                    and self._prefix is not None):
                raise ValueError(
                    "host_kv_bytes > 0 needs the paged int8 layout with "
                    "the prefix cache on (pages are the tier unit and the "
                    "radix key is the content identity); set paged=true, "
                    "kv_quant=auto/on, prefix_cache=auto/on — or "
                    "host_kv_bytes=0 to disable tiering")
            self._host_store: Optional[HostPageStore] = HostPageStore(
                self.host_kv_bytes)
            self._host_lane: Optional[HostCopyLane] = HostCopyLane()
            self._prefix.spill = self._spill_page_locked
        else:
            self._host_store = None
            self._host_lane = None
        #: (content_key, physical_page) demotion descriptors queued under
        #: the lock; drained + dispatched OUTSIDE it on the pump thread
        self._pending_demotes: List[Tuple[bytes, int]] = []
        #: in-flight DtoH materialization jobs awaiting adoption
        self._demote_jobs: List[LaneJob] = []
        self.host_kv_hits = 0
        self.host_kv_misses = 0
        self.host_kv_demotions = 0
        self.host_kv_promotions = 0

        # -- speculative decoding lane (docs/SERVING.md "Speculative
        # decoding"). auto = on only on real TPU (the CPU draft overhead
        # makes speculation a slowdown there — resolve_speculative); off is
        # a byte-identical rollback: serving/speculative.py is never
        # imported into the dispatch path, the PR 6-11 executables keep
        # their fingerprints, and the stats/ledger spec fields read
        # off/None. With the lane on, the legacy step executable is never
        # dispatched: every tick is draft-propose + batched verify, and a
        # zero-accepted tick emits exactly the one token the legacy step
        # would have (the token-identity contract test_speculative.py pins).
        if spec_tokens < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {spec_tokens}")
        self.spec_tokens = int(spec_tokens)
        self.speculative = resolve_speculative(speculative)
        self._spec = None
        if self.speculative == "on":
            draft_params, draft_config, shares = build_draft(
                self.params, config, draft_preset=draft_preset,
                draft_layers=draft_layers)
            self._spec = SpeculativeLane(self, draft_params, draft_config,
                                         shares)
        self.spec_proposed = 0
        self.spec_accepted = 0
        #: per-slot tokens accepted since the draft lane last caught up
        #: (the right-aligned propose window; [] while the slot is free)
        self._spec_windows: List[List[int]] = [[] for _ in
                                               range(self.capacity)]
        #: per-slot last legal write position (prompt + max_new - 1); -1
        #: for free slots so speculative writes to them always drop
        self._pos_limits = np.full(self.capacity, -1, np.int32)

        _QUEUE_CAPACITY.set(self.queue_depth)
        _SLOTS_TOTAL.set(self.capacity)
        _QUEUE_DEPTH.set(0)
        _SLOTS_BUSY.set(0)
        _MESH_DEVICES.set(self.num_devices)
        if self.paged:
            _KV_PAGES_TOTAL.set(self._pool.num_pages)
            _KV_PAGES_FREE.set(self._pool.free_pages)
            _KV_BYTES_CAPACITY.set(self._pool.num_pages
                                   * self._page_hbm_bytes)
            _KV_BYTES_USED.set(self._pool.used_pages * self._page_hbm_bytes)
            for index in range(self.capacity):
                _SLOT_PAGES.labels(slot=str(index)).set(0)
        if self._prefix is not None:
            _PREFIX_CACHED_PAGES.set(0)
        if self._host_store is not None:
            _HOST_KV_BYTES_CAPACITY.set(self.host_kv_bytes)
            _HOST_KV_BYTES_USED.set(0)

    @property
    def num_devices(self) -> int:
        """Chips the engine spans (dp x tp; 1 = single-chip)."""
        return self.mesh_dp * self.mesh_tp

    @property
    def mesh_shape(self) -> str:
        """Human-readable mesh layout for stats/dashboard: ``"dp x tp"``
        rendered as e.g. ``"2x2"`` (``"1x1"`` = the single-chip engine)."""
        return f"{self.mesh_dp}x{self.mesh_tp}"

    def _operand(self, value):
        """Ship one per-slot operand (or the PRNG key) to the device state:
        plain ``jnp.asarray`` single-chip; device_put REPLICATED across the
        mesh — per-slot state is values, never shapes, under either
        placement, so the executables' zero-recompile contract holds."""
        if self.mesh is None:
            return jnp.asarray(value)
        return jax.device_put(value, self._replicated)

    @property
    def step_executable(self):
        """The jitted step function this engine dispatches —
        ``.step_executable._cache_size()`` is the recompile ground truth
        the smoke gate and tests assert on (paged and contiguous engines
        use different executables; a speculative engine's "step" is the
        batched verify pass, the legacy step never runs)."""
        if self._spec is not None:
            return _paged_spec_verify if self.paged else _spec_verify
        return _paged_serving_step if self.paged else _serving_step

    @property
    def prefill_executable(self):
        if self._use_chunk_prefill:
            return _paged_chunk_serving_prefill
        return _paged_serving_prefill if self.paged else _serving_prefill

    @property
    def spec_draft_executable(self):
        """The draft lane's jitted propose function (None with the lane
        off) — the other half of the speculative zero-recompile ground
        truth (draft prefill mirrors ride ``prefill_executable``)."""
        if self._spec is None:
            return None
        return self._spec.propose_executable

    # -- admission --------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0,
               user_key: Optional[str] = None,
               deadline_s: Optional[float] = None) -> GenerationHandle:
        """Queue one request; raises ``ValueError`` on malformed input,
        ``RateLimitError``/``QueueFullError`` on admission failure,
        ``EngineDrainingError`` while the engine is draining.

        ``deadline_s`` overrides the engine's ``default_deadline_s`` wall
        budget (capped by ``max_deadline_s``); the deadline binds in queue,
        mid-prefill and mid-decode — a request past it finishes with an
        honest ``timeout`` outcome and a terminal stream chunk, never an
        eternal wait (docs/ROBUSTNESS.md "Serving data plane")."""
        if self._draining:
            # checked before any ledger record is minted: a drain is an
            # operator action, not admission-control signal worth a row
            raise EngineDrainingError(
                "engine is draining: in-flight requests are finishing, no "
                "new admissions; retry after the drain completes",
                retry_after_s=self.drain_retry_after())
        if deadline_s is not None:
            deadline_s = float(deadline_s)
            if not 0.0 < deadline_s <= self.max_deadline_s:
                raise ValueError(
                    f"deadline_s must be in (0, {self.max_deadline_s:g}], "
                    f"got {deadline_s:g}")
        elif self.default_deadline_s > 0:
            deadline_s = self.default_deadline_s
        prompt = [int(token) for token in prompt]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        if any(not 0 <= t < self.config.vocab_size for t in prompt):
            raise ValueError(
                f"prompt tokens must be in [0, {self.config.vocab_size})")
        if not 1 <= max_new_tokens <= self.max_new_tokens_cap:
            raise ValueError(
                f"max_new_tokens must be in [1, {self.max_new_tokens_cap}], "
                f"got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+new = {len(prompt) + max_new_tokens} exceeds the "
                f"engine sequence budget {self.max_len}")
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if self.paged:
            needed = self._pool.pages_for(len(prompt) + max_new_tokens)
            if needed > self._pool.num_pages:
                # can NEVER be admitted — an honest 422, not an eternal wait
                raise ValueError(
                    f"request needs {needed} KV pages but the pool only has "
                    f"{self._pool.num_pages}; shorten the prompt or "
                    "max_new_tokens")
        ledger = get_request_ledger()
        submitted_ts = self.clock()
        request = _Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                           temperature=float(temperature),
                           user_key=str(user_key) if user_key else None,
                           submitted_ts=submitted_ts,
                           request_id=ledger.new_request_id(),
                           submitted_wall=time.time(),
                           deadline_ts=(submitted_ts + deadline_s
                                        if deadline_s else None))
        request.record = ledger.begin(
            request.request_id, prompt_tokens=len(prompt),
            max_new_tokens=request.max_new_tokens,
            temperature=request.temperature, user_key=request.user_key,
            submitted_ts=request.submitted_wall)
        handle = GenerationHandle(self, request)
        request.handle = handle
        with self._lock:
            if (self.max_concurrent_per_user > 0 and request.user_key
                    and self._user_active.get(request.user_key, 0)
                    >= self.max_concurrent_per_user):
                _REQUESTS.labels(outcome="rejected_ratelimit").inc()
                self._record_rejection_locked(request, "rejected_ratelimit")
                raise RateLimitError(
                    f"user has {self.max_concurrent_per_user} generation "
                    "requests in flight; retry when one completes",
                    retry_after_s=self._retry_after_locked(),
                    request_id=request.request_id)
            if len(self._pending) >= self.queue_depth:
                _REQUESTS.labels(outcome="rejected_queue").inc()
                self._record_rejection_locked(request, "rejected_queue")
                raise QueueFullError(
                    f"admission queue is full ({self.queue_depth} waiting); "
                    "retry shortly",
                    retry_after_s=self._retry_after_locked(
                        needed_pages=(self._pool.pages_for(
                            len(prompt) + max_new_tokens)
                            if self.paged else None),
                        prompt=prompt),
                    request_id=request.request_id)
            if request.user_key:
                self._user_active[request.user_key] = (
                    self._user_active.get(request.user_key, 0) + 1)
            self._pending.append(request)
            _QUEUE_DEPTH.set(len(self._pending))
        return handle

    def _retry_after_locked(self, needed_pages: Optional[int] = None,
                            prompt: Optional[Sequence[int]] = None) -> float:
        """Honest Retry-After (floor 1 s). Contiguous: time for the
        shortest-remaining running sequence to free its slot at the observed
        inter-token p50. Paged with ``needed_pages``: the wait is for PAGES,
        not a slot — walk running sequences in completion order accumulating
        the pages each will make available on top of the current headroom,
        and answer the completion time at which ``needed_pages`` fit (a
        long-context request correctly waits for several short ones, not
        just the first).

        With the prefix cache on, pages can be SHARED, and a leaving slot
        frees only pages whose refcount drops to 0 — so the walk simulates
        per-page slot refcounts and counts a page exactly when its LAST
        holder completes (it is then free outright, or cache-retained and
        therefore evictable on demand — either way available to admission).
        Summing ``owned_count`` would over-promise: two sharers' departures
        must not count the same page twice.

        With ``prompt`` given, the ask's prefix discounts the page bill:
        device-cached prefix pages are granted SHARED at admission (they
        cost no fresh page — physically exact), and with the host tier on,
        host-resident continuation pages count as zero-cost headroom too.
        The host half is a latency HINT, not a page identity: a promoted
        page still occupies a fresh physical page, but its fill is a DMA
        at copy bandwidth instead of recompute, so by the time this many
        pages free the retry will mostly ride the tiers — and the probes
        double as LRU touches that keep the retry's prefix warm."""
        per_token = self._intertoken_hist.quantile(0.5) or 0.05
        running = [
            (slot.request.max_new_tokens - len(slot.request.generated), index)
            for index, slot in enumerate(self._slots) if slot is not None]
        if not running:
            return 1.0
        if self.paged and needed_pages is not None:
            if prompt is not None and self._prefix is not None:
                _, shared = self._prefix.match(prompt)
                discount = len(shared)
                if self._host_store is not None:
                    limit = (self._prefix.cacheable_tokens(len(prompt))
                             // self.page_size)
                    index = len(shared)
                    while index < limit and page_content_key(
                            prompt, index, self.page_size) in self._host_store:
                        discount += 1
                        index += 1
                needed_pages = max(1, needed_pages - discount)
            available = self._pool.free_pages
            if self._prefix is not None:
                # cache-only pages are evictable the moment admission asks
                available += self._pool.cached_only_pages()
            if available < needed_pages:
                slot_refs = self._pool.slot_ref_counts()
                eta_tokens = 0
                for remaining, index in sorted(running):
                    for page in self._pool.owned_pages(index):
                        slot_refs[page] -= 1
                        if slot_refs[page] == 0:
                            available += 1      # net-releasable NOW
                    eta_tokens = remaining
                    if available >= needed_pages:
                        break
                return max(1.0, round(eta_tokens * per_token, 1))
        return max(1.0, round(min(r for r, _ in running) * per_token, 1))

    def _cancel(self, request: _Request) -> None:
        with self._lock:
            if not request.finished:
                request.cancelled = True

    # -- drain (docs/ROBUSTNESS.md "Serving data plane") -------------------
    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> None:
        """Stop admitting new requests; everything queued or running keeps
        finishing through the normal pump. ``submit()`` raises
        ``EngineDrainingError`` (503 + Retry-After at the API edge) until
        :meth:`resume`. Idempotent."""
        self._draining = True

    def resume(self) -> None:
        """Re-open admission after a drain. Idempotent."""
        self._draining = False

    def drain_retry_after(self) -> float:
        """Honest Retry-After while draining: the estimated time for every
        in-flight request to finish at the observed inter-token p50 — the
        slowest running slot plus the queued work amortized over the slot
        pool (an estimate, floor 1 s; exact completion depends on EOS)."""
        with self._lock:
            per_token = self._intertoken_hist.quantile(0.5) or 0.05
            running = [slot.request.max_new_tokens
                       - len(slot.request.generated)
                       for slot in self._slots if slot is not None]
            queued = sum(request.max_new_tokens
                         for request in self._pending)
            tokens_left = (max(running) if running else 0) + (
                queued / max(1, self.capacity))
            return max(1.0, round(tokens_left * per_token, 1))

    def _record_rejection_locked(self, request: _Request,
                                 outcome: str) -> None:
        """Ledger a shed request: rejections are the requests admission
        tuning most needs to see, so they get a record with their outcome
        even though no phase beyond the submit ever ran."""
        record = request.record
        if record is not None:
            get_request_ledger().finish(
                record, outcome, finished_ts=request.wall(self.clock()))

    # -- scheduler --------------------------------------------------------
    def has_work(self) -> bool:
        with self._lock:
            # tier backlog counts: queued demotions still need their
            # extraction dispatched and in-flight DtoH copies need adopting
            # into the host store — the pump must keep ticking until the
            # lane drains, or a trailing spill waits for the next request
            return (bool(self._pending)
                    or any(slot is not None for slot in self._slots)
                    or bool(self._pending_demotes)
                    or bool(self._demote_jobs))

    def _meter_tick(self) -> None:
        """Integrate one pump-tick's resource-time products into the
        tenant meter — pure host bookkeeping on the pump thread (clock
        reads, page counts, dict updates; never a traced operand, so the
        zero-recompile contract is untouched). Every busy slot is charged
        from ONE dt sample and the engine's own ``busy_slot_seconds``
        integral accumulates from the same samples, which is what makes
        the conservation invariant ``sum(tenant device-seconds) ==
        busy_slot_seconds x num_devices`` exact rather than approximate.
        The meter lock is a leaf taken after the engine lock is released;
        no new lock-order cycle is possible (TH-LOCK)."""
        meter = self.tenant_meter
        now = self.clock()
        last = self._last_meter_ts
        self._last_meter_ts = now
        if last is None:
            return
        dt = now - last
        if dt <= 0:
            return
        devices = self.num_devices
        charges: Dict[str, List[float]] = {}
        with self._lock:
            for index, slot in enumerate(self._slots):
                if slot is None:
                    continue
                request = slot.request
                tenant = request.user_key or ANONYMOUS_TENANT
                self.busy_slot_seconds += dt
                entry = charges.get(tenant)
                if entry is None:
                    entry = charges[tenant] = [0.0, 0.0, 0.0]
                device_s = dt * devices
                if self.paged:
                    kv_byte_s = (self._pool.owned_count(index)
                                 * self._page_hbm_bytes * dt)
                else:
                    kv_byte_s = self._slot_kv_bytes * dt
                entry[0] += device_s
                entry[1] += kv_byte_s
                request.device_seconds += device_s
                request.kv_byte_seconds += kv_byte_s
                if slot.promote_entries:
                    # host-tier residency: pages this request's promote
                    # lane still holds in the host store (parked on DMA)
                    entry[2] += sum(e.nbytes
                                    for e in slot.promote_entries) * dt
        if charges:
            meter.charge_tick({tenant: (entry[0], entry[1], entry[2])
                               for tenant, entry in charges.items()})

    def step(self) -> int:
        """One scheduler iteration: admit joins, advance every in-progress
        prefill by ONE chunk, then advance the running batch one token —
        FlexNPU-style phase co-location, with the chunk budget
        (``prefill_chunk_tokens``) bounding how much prefill work any tick
        can insert between two decode steps, so a 4k-token join can never
        stall the running batch's inter-token latency. Returns the number
        of active slots stepped.

        With a flight recorder installed the tick is additionally stamped
        into the per-tick ring — pure host bookkeeping (counts and clock
        reads, never a traced operand), recorded in a ``finally`` so the
        tick that *raises* is the one tick the post-mortem needs most.
        ``flight_recorder is None`` is the byte-identical unrecorded
        path."""
        if self.tenant_meter is not None:
            self._meter_tick()
        recorder = self.flight_recorder
        if recorder is None:
            if self._host_store is not None:
                self._pump_host_lane()
            self._admit()
            self._advance_prefills()
            return self._decode_step()
        started = self.clock()
        compiles_before = len(_compile_seen)
        faults_before = self._faults_injected()
        demotions_before = self.host_kv_demotions
        promotions_before = self.host_kv_promotions
        admitted = chunks = stepped = 0
        try:
            if self._host_store is not None:
                self._pump_host_lane()
            admitted = self._admit()
            chunks = self._advance_prefills() or 0
            stepped = self._decode_step()
            return stepped
        finally:
            with self._lock:
                busy = self._busy_locked()
                depth = len(self._pending)
            pages_free = self._pool.free_pages if self.paged else 0
            recorder.record(
                duration_s=self.clock() - started,
                admitted=admitted,
                prefill_chunks=chunks,
                decode_slots=stepped,
                slots_busy=busy,
                queue_depth=depth,
                pages_free=pages_free,
                compiles=len(_compile_seen) - compiles_before,
                faults=self._faults_injected() - faults_before,
                host_demotions=self.host_kv_demotions - demotions_before,
                host_promotions=self.host_kv_promotions - promotions_before,
            )

    def _faults_injected(self) -> int:
        """Total injections the fault plan has performed (0 without a
        plan) — the recorder diffs this per tick."""
        plan = self.fault_plan
        if plan is None:
            return 0
        return sum(plan.faults_injected.values())

    def pump(self, budget_s: Optional[float] = None,
             should_stop: Optional[Callable[[], bool]] = None) -> int:
        """Run scheduler iterations until idle, the wall budget is spent,
        or ``should_stop()`` — the GenerationService tick body."""
        deadline = None if budget_s is None else self.clock() + budget_s
        steps = 0
        while self.has_work():
            if should_stop is not None and should_stop():
                break
            if deadline is not None and self.clock() >= deadline:
                break
            self.step()
            steps += 1
        return steps

    def warmup(self, prompt_lens: Sequence[int] = ()) -> None:
        """Compile the step executable and the prefill executable for each
        bucket the given prompt lengths map to (plus the smallest bucket),
        so steady-state traffic never pays a compile.

        With the prefix cache on, the chunked executable's widths are
        warmed instead: each prompt length expands to its chunk sequence
        (``prefill_chunk_tokens``-sized pieces + the bucketed tail), plus
        the floor bucket — cache-hit suffixes are usually short, and a hit
        must never pay the compile the miss path was warmed out of."""
        if self._use_chunk_prefill:
            widths = {_prefill_bucket(1, self.max_len - 1)}
            for length in prompt_lens:
                remaining = max(1, length - 1)
                while remaining > 0:
                    chunk = min(remaining,
                                self.prefill_chunk_tokens or remaining)
                    widths.add(_prefill_bucket(chunk, self.max_len - 1))
                    remaining -= chunk
            for width in sorted(widths):
                # real_len 0: every write routes out of bounds and drops —
                # warmup compiles without touching any page
                self._dispatch_chunk_prefill(np.zeros((1, width), np.int32),
                                             slot=0, start=0, real_len=0)
                if self._spec is not None:
                    self._spec.chunk_prefill(np.zeros((1, width), np.int32),
                                             0, 0, 0)
            if self._host_store is not None:
                # tier executables: an all-trash-ids extract (reads trash-
                # page garbage, discarded) and an all-OOB inject with a
                # zero payload (every write drops) — both fixed-width, so
                # steady-state demotions/promotions never pay a compile
                width = self._pool.max_pages_per_slot
                extracted = self._dispatch_page_extract(
                    np.full(width, TRASH_PAGE, np.int32))
                np.asarray(extracted[0])    # force the compile
                config = self.config
                payload_shape = (config.n_layers, width, self.page_size,
                                 config.kv_heads, config.d_head)
                scale_shape = (config.n_layers, width, config.kv_heads)
                self._dispatch_page_inject(
                    np.full(width, self._pool.physical_pages, np.int32),
                    self._operand(np.zeros(payload_shape, np.int8)),
                    self._operand(np.zeros(scale_shape, np.float32)),
                    self._operand(np.zeros(payload_shape, np.int8)),
                    self._operand(np.zeros(scale_shape, np.float32)))
        else:
            buckets = {_prefill_bucket(max(1, length - 1), self.max_len - 1)
                       for length in prompt_lens} or {
                           _prefill_bucket(1, self.max_len - 1)}
            for width in sorted(buckets):
                # real_len 0: every write is masked (contiguous) or dropped
                # (paged — slot 0's table row still points at the trash
                # page), so warmup compiles without touching any page
                self._dispatch_prefill(np.zeros((1, width), np.int32),
                                       slot=0, real_len=0)
                if self._spec is not None:
                    self._spec.prefill(np.zeros((1, width), np.int32), 0, 0)
        if self._spec is not None:
            # a speculative engine's steady state is propose + verify, not
            # the legacy step — warm exactly those (fresh-engine state:
            # empty windows, limits -1, so every speculative write drops)
            with self._lock:
                window, lens, limits, page_table = \
                    self._spec_operands_locked()
            proposals = np.asarray(self._spec.propose(
                window, lens, self._positions, limits, page_table))
            verify_window = np.concatenate(
                [self._tokens[:, None], proposals], axis=1)
            greedy, _ = self._run_spec_verify(verify_window, limits,
                                              page_table)
            np.asarray(greedy)  # force the compile before traffic arrives
            return
        chosen, self._cache, self._key = self._run_step()
        np.asarray(chosen)      # force the compile before traffic arrives

    # -- internals --------------------------------------------------------
    def _fault_point(self, kind: str) -> None:
        """Fault-injection seam: consulted BEFORE every device dispatch
        (serving/faults.py) — an injected fault therefore never leaves a
        half-donated cache, which is what makes transient classification
        honest for injected faults. A no-op without a plan."""
        if self.fault_plan is not None:
            self.fault_plan.before_dispatch(kind)

    def _fingerprint_fn(self, base: str) -> str:
        """Compile-counter fn name: mesh engines get a ``serving_mesh_*``
        variant and int8 engines a ``*_q`` suffix (docs/OBSERVABILITY.md)
        so operators can tell WHICH executables compiled — and the
        rollback tests can assert a 1x1 / kv_quant=off config never mints
        a mesh or quant fingerprint."""
        if self._quant:
            base = base + "_q"
        if self.mesh is None:
            return base
        return base.replace("serving_", "serving_mesh_", 1)

    def _mesh_fingerprint(self) -> tuple:
        return (self.mesh_dp, self.mesh_tp) if self.mesh is not None else ()

    def _count_prefill_compile(self, width: int) -> str:
        if self.paged:
            fn = self._fingerprint_fn("serving_paged_prefill")
            return _count_compile(fn,
                                  (fn, self.config,
                                   self._pool.num_pages, self.page_size,
                                   self._pool.max_pages_per_slot, width)
                                  + self._mesh_fingerprint())
        fn = self._fingerprint_fn("serving_prefill")
        return _count_compile(fn,
                              (fn, self.config, self.capacity,
                               self.max_len, width)
                              + self._mesh_fingerprint())

    def _count_chunk_prefill_compile(self, width: int) -> str:
        fn = self._fingerprint_fn("serving_paged_chunk_prefill")
        return _count_compile(fn,
                              (fn, self.config,
                               self._pool.num_pages, self.page_size,
                               self._pool.max_pages_per_slot, width)
                              + self._mesh_fingerprint())

    def _dispatch_chunk_prefill(self, head, slot: int, start: int,
                                real_len: int) -> str:
        """Run one prefill chunk (positions ``start .. start+real_len-1``)
        through the slot's page-table row. ``start``/``real_len``/the row
        are traced operands: one executable per bucket width serves every
        skip offset, chunk boundary and page assignment. Returns the
        compile fingerprint event ("hit"/"miss") for the request ledger."""
        self._fault_point("prefill")
        compile_event = self._count_chunk_prefill_compile(head.shape[1])
        self._cache = _paged_chunk_serving_prefill(
            self.params, self._operand(head), self._cache,
            self._operand(self._pool.page_table[slot]),
            self._operand(np.int32(start)),
            self._operand(np.int32(real_len)), self.config)
        return compile_event

    # -- KV-page tiering internals (docs/SERVING.md "KV-page tiering") -----
    #
    # Thread discipline, because it is what makes the tier lock-free on the
    # store: the HostPageStore is read/written ONLY on the pump thread; the
    # copy lane's thread runs nothing but the raw transfers (np.asarray =
    # DtoH, _operand = HtoD) and publishes through LaneJob.done. Device
    # dispatches (extract/inject) happen on the pump thread OUTSIDE the
    # engine lock, like every other dispatch — ordering against the
    # prefill/step executables comes from dispatch order on the one donated
    # cache buffer, never from blocking.

    def _page_copy_count_compile(self, base: str) -> str:
        fn = self._fingerprint_fn(base)
        return _count_compile(fn,
                              (fn, self.config,
                               self._pool.num_pages, self.page_size,
                               self._pool.max_pages_per_slot)
                              + self._mesh_fingerprint())

    def _dispatch_page_extract(self, page_ids: np.ndarray):
        """Gather whole pages + scales for demotion (fixed [W] width,
        TRASH_PAGE-padded; ``serving_page_extract`` fingerprint). A pure
        read of the cache — must be dispatched BEFORE any executable that
        overwrites the extracted pages (see _page_extract_body)."""
        self._fault_point("extract")
        self._page_copy_count_compile("serving_page_extract")
        return _serving_page_extract(self._cache, self._operand(page_ids))

    def _dispatch_page_inject(self, page_ids: np.ndarray, k, k_scale,
                              v, v_scale) -> None:
        """Scatter staged host pages into fresh device pages (promotion;
        ``serving_page_inject`` fingerprint, OOB-padded ids drop). Donates
        and reassigns the cache, joining the normal dispatch chain."""
        self._fault_point("inject")
        self._page_copy_count_compile("serving_page_inject")
        self._cache = _serving_page_inject(
            self._cache, self._operand(page_ids), k, k_scale, v, v_scale)

    def _spill_page_locked(self, key: bytes, page: int) -> None:
        """PrefixCache.evict victim hook (runs under the engine lock,
        BEFORE the victim's reference drops): queue a demotion descriptor.
        The payload is extracted by _dispatch_demotions on the pump thread
        right after the lock releases — before any prefill can be
        dispatched at the recycled page."""
        if key not in self._host_store:
            self._pending_demotes.append((key, page))

    def _queue_slot_demotions_locked(self, index: int, state: _Slot) -> None:
        """Queue demotions for a draining slot's sole-held prefix pages:
        fully-dispatched cacheable pages with refcount 1 (the tree never
        adopted them, or already let go — either way release() is about to
        net-free them and their K/V would be lost). Shared pages are the
        tree's to spill when IT evicts them."""
        prompt = state.request.prompt
        covered = min(state.prefill_next,
                      self._prefix.cacheable_tokens(len(prompt)))
        row = self._pool.owned_pages(index)
        for page_index in range(covered // self.page_size):
            page = row[page_index]
            if self._pool.refcount(page) != 1:
                continue
            key = page_content_key(prompt, page_index, self.page_size)
            if key in self._host_store:
                continue
            self._pending_demotes.append((key, page))

    def _probe_host_locked(self, prompt: Sequence[int],
                           start_pages: int) -> List[HostPageEntry]:
        """Walk successive content keys past the device match; returns the
        resident continuation run (LRU-touched). Applies the same
        prefix_min_tokens worthiness gate as match(): a promotion whose
        total covered span is below the gate is not worth its DMA."""
        limit = self._prefix.cacheable_tokens(len(prompt)) // self.page_size
        entries: List[HostPageEntry] = []
        index = start_pages
        while index < limit:
            entry = self._host_store.get(
                page_content_key(prompt, index, self.page_size))
            if entry is None:
                break
            entries.append(entry)
            index += 1
        if entries and index * self.page_size < self.prefix_min_tokens:
            entries = []
        if entries:
            self.host_kv_hits += 1
            _HOST_KV_HITS.inc()
        else:
            self.host_kv_misses += 1
            _HOST_KV_MISSES.inc()
        return entries

    def _pump_host_lane(self) -> None:
        """Tick the tier's async machinery — FIRST thing in step(), before
        admission, so completed copies are adopted and queued extractions
        are dispatched ahead of anything that could overwrite their pages.
        Everything here is poll-and-dispatch; a copy still in flight is
        simply picked up on a later tick (the never-blocks-the-pump
        contract, pinned by the fake-clock test)."""
        self._dispatch_demotions()
        self._adopt_demotions()
        self._adopt_promotions()

    def _dispatch_demotions(self) -> None:
        """Drain queued demotion descriptors and dispatch their page
        extractions (pump thread, outside the lock), then hand the device
        results to the lane for DtoH materialization."""
        with self._lock:
            pending, self._pending_demotes = self._pending_demotes, []
        if not pending:
            return
        width = self._pool.max_pages_per_slot
        for start in range(0, len(pending), width):
            group = pending[start:start + width]
            page_ids = np.full(width, TRASH_PAGE, np.int32)
            for offset, (_, page) in enumerate(group):
                page_ids[offset] = page
            extracted = self._dispatch_page_extract(page_ids)
            keys = [key for key, _ in group]
            self._demote_jobs.append(self._host_lane.submit(
                functools.partial(self._materialize_demotion, keys,
                                  extracted)))

    @staticmethod
    def _materialize_demotion(keys: List[bytes], extracted):
        """(copy lane thread) Pull the extracted pages to host RAM —
        np.asarray blocks on the device result, which is exactly the work
        the lane exists to keep off the pump."""
        k, k_scale, v, v_scale = (np.asarray(array) for array in extracted)
        return keys, k, k_scale, v, v_scale

    def _adopt_demotions(self) -> None:
        """Adopt completed DtoH jobs into the host store (pump thread —
        the store's single-writer discipline)."""
        still_running: List[LaneJob] = []
        for job in self._demote_jobs:
            if not job.done:
                still_running.append(job)
                continue
            if job.error is not None:
                log.warning("host-kv demotion dropped: %s", job.error)
                continue
            keys, k, k_scale, v, v_scale = job.result
            adopted = 0
            for offset, key in enumerate(keys):
                # per-page copies: a view into the [L, W, ...] batch would
                # pin the whole transfer buffer and lie to byte accounting
                if self._host_store.put(key,
                                        k[:, offset].copy(),
                                        v[:, offset].copy(),
                                        k_scale[:, offset].copy(),
                                        v_scale[:, offset].copy()):
                    adopted += 1
            if adopted:
                with self._lock:
                    self.host_kv_demotions += adopted
                _HOST_KV_DEMOTIONS.inc(adopted)
            _HOST_KV_BYTES_USED.set(self._host_store.bytes_used)
        self._demote_jobs = still_running

    def _stage_promotion(self, entries: List[HostPageEntry]):
        """(copy lane thread) Assemble the promotion run into the fixed
        [W]-wide payload and ship it to the device — the HtoD half of the
        tier. Unused lanes stay zero; their inject ids are OOB and drop."""
        config = self.config
        width = self._pool.max_pages_per_slot
        k = np.zeros((config.n_layers, width, self.page_size,
                      config.kv_heads, config.d_head), np.int8)
        v = np.zeros_like(k)
        k_scale = np.zeros((config.n_layers, width, config.kv_heads),
                           np.float32)
        v_scale = np.zeros_like(k_scale)
        for offset, entry in enumerate(entries):
            k[:, offset] = entry.k
            v[:, offset] = entry.v
            k_scale[:, offset] = entry.k_scale
            v_scale[:, offset] = entry.v_scale
        return (self._operand(k), self._operand(k_scale),
                self._operand(v), self._operand(v_scale))

    def _adopt_promotions(self) -> None:
        """Poll parked slots' staging jobs; for each completed one,
        dispatch the inject and resume the slot's prefill past
        promote_boundary. Slot frees happen only on this pump thread, so
        the identity check under the lock stays valid through the
        dispatch that follows it."""
        with self._lock:
            parked = [(index, state)
                      for index, state in enumerate(self._slots)
                      if state is not None and state.promote_job is not None]
        for index, state in parked:
            job = state.promote_job
            if not job.done:
                continue
            if job.error is not None:
                log.warning("host-kv promotion failed (slot %d): %s — "
                            "falling back to recompute", index, job.error)
                with self._lock:
                    if self._slots[index] is state:
                        state.promote_job = None
                        state.promote_entries = []
                        state.promote_pages = []
                        state.promote_boundary = 0
                        state.host_hit_pages = 0
                continue
            with self._lock:
                if self._slots[index] is not state:
                    continue        # cancelled + freed while the DMA ran
            width = self._pool.max_pages_per_slot
            page_ids = np.full(width, self._pool.physical_pages, np.int32)
            page_ids[:len(state.promote_pages)] = state.promote_pages
            k, k_scale, v, v_scale = job.result
            self._dispatch_page_inject(page_ids, k, k_scale, v, v_scale)
            promoted = len(state.promote_pages)
            now = self.clock()
            finish = False
            with self._lock:
                if self._slots[index] is not state:
                    continue
                state.promote_job = None
                state.promote_entries = []
                state.promote_ms = (now - state.promote_started_ts) * 1e3
                state.prefill_next = max(state.prefill_next,
                                         min(state.promote_boundary,
                                             state.prefill_target))
                self.host_kv_promotions += promoted
                # injected pages are fully-dispatched content — adopt them
                # into the radix tree so the NEXT identical prompt hits on
                # device without touching the store at all
                self._prefix.insert(state.request.prompt,
                                    self._pool.page_table[index],
                                    state.promote_boundary)
                _PREFIX_CACHED_PAGES.set(self._prefix.cached_pages)
                finish = state.prefill_next >= state.prefill_target
            _HOST_KV_PROMOTIONS.inc(promoted)
            if finish:
                self._finish_prefill(index, state)

    def _dispatch_prefill(self, head, slot: int, real_len: int) -> str:
        """Run the joining sequence's trunk pass through whichever cache
        layout this engine uses. Paged passes the slot's page-table ROW as
        a traced operand (the executable never sees the slot index);
        contiguous passes the traced slot index. Returns the compile
        fingerprint event ("hit"/"miss") for the request ledger."""
        self._fault_point("prefill")
        compile_event = self._count_prefill_compile(head.shape[1])
        if self.paged:
            self._cache = _paged_serving_prefill(
                self.params, self._operand(head), self._cache,
                self._operand(self._pool.page_table[slot]),
                self._operand(np.int32(real_len)), self.config)
        else:
            self._cache = _serving_prefill(
                self.params, self._operand(head), self._cache,
                self._operand(np.int32(slot)),
                self._operand(np.int32(real_len)), self.config)
        return compile_event

    def _run_step(self):
        chosen, cache, key = self._run_step_dispatch()
        if self.mesh is not None:
            # GSPMD is free to hand the PRNG key back sharded over a size-1
            # axis (observed: P('fsdp') — same bytes everywhere, different
            # label); feeding that back verbatim would miss the executable
            # compiled for the replicated key and recompile once. Re-pin the
            # 8-byte key to the replicated sharding every step — a no-op
            # transfer that keeps the one-executable contract airtight.
            key = jax.device_put(key, self._replicated)
        return chosen, cache, key

    def _run_step_dispatch(self):
        self._fault_point("step")
        if self.paged:
            # the kernel dispatch gets its own fingerprint so operators can
            # tell WHICH paged step compiled (docs/OBSERVABILITY.md); page
            # tables/positions stay traced operands either way — page
            # assignment never recompiles regardless of dispatch
            fn = self._fingerprint_fn(
                "serving_paged_step_kernel" if self._use_kernel
                else "serving_paged_step")
            _count_compile(fn,
                           (fn, self.config, self.capacity,
                            self._pool.num_pages, self.page_size,
                            self._pool.max_pages_per_slot, self.top_k,
                            self._kernel_interpret)
                           + self._mesh_fingerprint())
            page_table = self._pool.page_table
            if self._use_chunk_prefill:
                # a mid-prefill slot's row already points at REAL pages
                # (shared prefix pages above all), but the step writes
                # K/V for every slot at its frozen position — route
                # inactive rows to the trash page so that scribble can
                # never land on a page another sequence reads. Same
                # dtype/shape, traced value only: no fingerprint change.
                page_table = page_table.copy()
                page_table[~self._active] = TRASH_PAGE
            return _paged_serving_step(
                self.params, self._operand(self._tokens),
                self._operand(self._positions), self._operand(self._active),
                self._operand(self._temps),
                self._operand(page_table),
                self._cache, self._key,
                config=self.config, top_k=self.top_k,
                use_kernel=self._use_kernel,
                interpret=self._kernel_interpret,
                mesh=self.mesh if self._use_kernel else None,
                shard_heads=self._kernel_shard_heads)
        fn = self._fingerprint_fn("serving_step")
        _count_compile(fn,
                       (fn, self.config, self.capacity,
                        self.max_len, self.top_k) + self._mesh_fingerprint())
        return _serving_step(
            self.params, self._operand(self._tokens),
            self._operand(self._positions), self._operand(self._active),
            self._operand(self._temps), self._cache, self._key,
            config=self.config, top_k=self.top_k)

    def _admit(self) -> int:
        """Move pending requests into free slots (prefill co-located with
        decode: every scheduler iteration does its joins first, then the
        batch step — FlexNPU's dynamic phase mixing on one chip)."""
        joined = 0
        while True:
            with self._lock:
                self._drop_cancelled_pending_locked()
                free = next((index for index, slot
                             in enumerate(self._slots) if slot is None), None)
                if free is None or not self._pending:
                    _QUEUE_DEPTH.set(len(self._pending))
                    return joined
                request = self._pending[0]
                cached_tokens = 0
                if self.paged:
                    needed = self._pool.pages_for(
                        len(request.prompt) + request.max_new_tokens)
                    if self._prefix is not None:
                        # charge only the unique suffix: matched prefix
                        # pages are granted shared (refcount bump, read-
                        # only), fresh pages cover the rest — and pool
                        # pressure first reclaims LRU cache-only pages
                        # (eviction never touches a page a slot holds)
                        cached_tokens, shared = self._prefix.match(
                            request.prompt)
                        fresh = needed - len(shared)
                        shortfall = fresh - self._pool.free_pages
                        if shortfall > 0:
                            evicted = self._prefix.evict(shortfall)
                            if evicted:
                                _PREFIX_EVICTIONS.inc(evicted)
                                _PREFIX_CACHED_PAGES.set(
                                    self._prefix.cached_pages)
                        granted = self._pool.assign_shared(free, shared,
                                                           fresh)
                    else:
                        granted = self._pool.assign(free, needed)
                    if not granted:
                        # head-of-line waits for pages. Strict FIFO on
                        # purpose: letting smaller requests overtake would
                        # starve long-context requests under sustained
                        # short-request load (submit() already rejected
                        # anything that can NEVER fit)
                        _QUEUE_DEPTH.set(len(self._pending))
                        return joined
                    host_entries: List[HostPageEntry] = []
                    if self._prefix is not None:
                        if cached_tokens > 0:
                            self.prefix_hits += 1
                            _PREFIX_HITS.inc()
                        else:
                            self.prefix_misses += 1
                            _PREFIX_MISSES.inc()
                        if self._host_store is not None:
                            # the host tier can only EXTEND the device
                            # match: probe the store for successive
                            # content keys past the shared run — hits are
                            # promoted into this slot's first fresh pages
                            # by DMA instead of recomputed
                            host_entries = self._probe_host_locked(
                                request.prompt, len(shared))
                    _KV_PAGES_FREE.set(self._pool.free_pages)
                    _KV_BYTES_USED.set(self._pool.used_pages
                                       * self._page_hbm_bytes)
                    _SLOT_PAGES.labels(slot=str(free)).set(needed)
                self._pending.popleft()
                joined_ts = self.clock()
                self._slots[free] = _Slot(request=request,
                                          joined_ts=joined_ts,
                                          cached_tokens=cached_tokens)
                if self.paged and host_entries:
                    # the promoted run lands in the fresh pages right
                    # after the shared run (logical order); allocation is
                    # unchanged — promotion replaces the FILL (recompute
                    # -> DMA), not the pages
                    state = self._slots[free]
                    row = self._pool.owned_pages(free)
                    state.promote_entries = host_entries
                    state.promote_pages = row[
                        len(shared):len(shared) + len(host_entries)]
                    state.promote_boundary = (
                        (len(shared) + len(host_entries)) * self.page_size)
                    state.host_hit_pages = len(host_entries)
                # last legal write position for the speculative window
                # (free slots sit at -1 so their speculative writes drop)
                self._pos_limits[free] = (len(request.prompt)
                                          + request.max_new_tokens - 1)
                # the queue phase closes HERE, separately from TTFT: the
                # queue share is what admission tuning moves, the prefill
                # share is what bucket/kernel work moves
                queue_wait_s = joined_ts - request.submitted_ts
                _QUEUE_WAIT_SECONDS.observe(queue_wait_s)
                self._queue_wait_hist.observe(queue_wait_s)
                meter = self.tenant_meter
                if meter is not None:
                    # queue phase closes here; prompt tokens split into
                    # what the cache served vs what prefill will compute
                    tenant = request.user_key or ANONYMOUS_TENANT
                    meter.charge_queue(tenant, queue_wait_s)
                    meter.count_tokens(tenant, "cached", cached_tokens)
                    meter.count_tokens(tenant, "prefill",
                                       len(request.prompt) - cached_tokens)
                record = request.record
                if record is not None:
                    record.queue_ms = queue_wait_s * 1e3
                    record.slot = free
                    if self.paged:
                        record.kv_pages = needed
                    if self._prefix is not None:
                        record.cached_tokens = cached_tokens
                get_tracer().record_span(
                    "generate.queue", kind="generate",
                    start_ts=request.submitted_wall,
                    duration_s=queue_wait_s,
                    request_id=request.request_id, slot=free)
                _QUEUE_DEPTH.set(len(self._pending))
                _SLOTS_BUSY.set(self._busy_locked())
            if self._host_store is not None:
                # evict() above may have queued spill descriptors for the
                # pages it reclaimed; dispatch their extractions NOW —
                # before this join's prefill chunks (or a later join in
                # this same loop) can be dispatched against the recycled
                # pages, the extract must already be in the dispatch chain
                self._dispatch_demotions()
            self._join(free, request)
            joined += 1

    def _drop_cancelled_pending_locked(self) -> None:
        """Cancelled requests leave the queue; so do deadline-expired ones
        (a head-of-line request waiting for pages must time out honestly
        instead of waiting forever — the queue-phase deadline)."""
        now = self.clock()
        kept: Deque[_Request] = collections.deque()
        for request in self._pending:
            if request.cancelled:
                self._finish_locked(request, outcome="cancelled")
            elif (request.deadline_ts is not None
                    and now >= request.deadline_ts):
                _DEADLINE_TIMEOUTS.labels(phase="queue").inc()
                self._finish_locked(request, outcome="timeout")
            else:
                kept.append(request)
        self._pending = kept

    def _join(self, slot: int, request: _Request) -> None:
        """Prefill the prompt head into the slot row and arm the per-slot
        operands; the first decode step after this emits the request's
        first token.

        Prefix-cache engines instead SCHEDULE the prefill: the slot starts
        parked (active False, its page-table row masked to the trash page
        in the step operand) at the first uncached position, and
        :meth:`_advance_prefills` — called in this same tick, right after
        admission — dispatches one chunk per tick until the slot arms. A
        full-prefix hit arms immediately: zero chunks, zero prefill."""
        prompt = request.prompt
        prompt_len = len(prompt)
        record = request.record
        if self._use_chunk_prefill:
            state = self._slots[slot]
            state.prefill_target = prompt_len - 1
            state.prefill_next = min(state.cached_tokens,
                                     state.prefill_target)
            state.prefill_done = False
            state.prefill_started_ts = self.clock()
            if state.promote_entries:
                # host-tier hit: PARK the slot (exactly like mid-chunk-
                # prefill) and stage the HtoD copy on the async lane — the
                # pump thread never waits on the DMA; _pump_host_lane
                # adopts the staged payload at a later tick, dispatches
                # the inject, and resumes prefill past promote_boundary
                state.promote_started_ts = self.clock()
                state.promote_job = self._host_lane.submit(
                    functools.partial(self._stage_promotion,
                                      list(state.promote_entries)))
                return
            if state.prefill_next >= state.prefill_target:
                self._finish_prefill(slot, state)
            return
        if prompt_len > 1:
            width = _prefill_bucket(prompt_len - 1, self.max_len - 1)
            head = np.zeros((1, width), np.int32)
            head[0, :prompt_len - 1] = prompt[:-1]
            started = self.clock()
            try:
                compile_event = self._dispatch_prefill(head, slot,
                                                       prompt_len - 1)
                if self._spec is not None:
                    # mirror the prompt into the draft lane's K/V — same
                    # head, same slot/table row, draft params
                    # (speculative.py)
                    self._spec.prefill(head, slot, prompt_len - 1)
            except Exception:
                # a failed whole-prompt prefill must not wedge the slot:
                # this path runs once per admission (unlike the chunked
                # path, which naturally re-dispatches), so free the slot
                # and requeue the request at the HEAD before letting the
                # failure propagate to the supervisor — a transient retry
                # then re-admits it cleanly, in order
                with self._lock:
                    if self._slots[slot] is not None and \
                            self._slots[slot].request is request:
                        self._free_slot_locked(slot)
                    self._pending.appendleft(request)
                    _QUEUE_DEPTH.set(len(self._pending))
                    _SLOTS_BUSY.set(self._busy_locked())
                raise
            # host dispatch time: the device work itself drains inside the
            # first decode step (jax is async), which TTFT captures — a
            # block_until_ready here would serialize joins against the
            # running batch just to relabel the same latency
            prefill_s = self.clock() - started
            if record is not None:
                record.prefill_bucket = width
                record.prefill_compile = compile_event
                record.prefill_ms = prefill_s * 1e3
            get_tracer().record_span(
                "generate.prefill", kind="generate",
                start_ts=request.wall(started), duration_s=prefill_s,
                request_id=request.request_id, slot=slot, bucket=width,
                compile=compile_event)
        elif record is not None:
            # single-token prompt: nothing to prefill, the phase is 0 by
            # construction (None would read as "never reached")
            record.prefill_ms = 0.0
        with self._lock:
            self._tokens[slot] = prompt[-1]
            self._positions[slot] = prompt_len - 1
            self._temps[slot] = request.temperature
            self._active[slot] = True
            # the draft's first catch-up window: just the current token
            self._spec_windows[slot] = [int(prompt[-1])]

    def _advance_prefills(self) -> int:
        """Dispatch ONE prefill chunk for every slot still mid-prefill —
        the per-tick budget that keeps a long joining prompt from wedging
        the running decode batch (docs/SERVING.md "Prefix cache & chunked
        prefill"). Cancels are honored here too, so a cancel mid-chunk
        frees the slot (and its net-releasable pages) without ever arming.
        Returns the number of chunks dispatched (the flight recorder's
        per-tick prefill count)."""
        if not self._use_chunk_prefill:
            return 0    # legacy paths prefill whole prompts inside _join
        with self._lock:
            pending = [(index, slot) for index, slot in enumerate(self._slots)
                       if slot is not None and not slot.prefill_done]
        chunks = 0
        for index, state in pending:
            if state.request.cancelled:
                with self._lock:
                    if self._slots[index] is state:
                        self._free_slot_locked(index)
                        self._finish_locked(state.request,
                                            outcome="cancelled")
                continue
            deadline = state.request.deadline_ts
            if deadline is not None and self.clock() >= deadline:
                # a deadline expiring mid-prefill frees the slot (and its
                # net-releasable pages) exactly like a cancel, with the
                # honest outcome
                with self._lock:
                    if self._slots[index] is state:
                        _DEADLINE_TIMEOUTS.labels(phase="prefill").inc()
                        self._free_slot_locked(index)
                        self._finish_locked(state.request,
                                            outcome="timeout")
                continue
            if state.promote_job is not None:
                # parked mid-promote: the copy lane owns the resume
                # (_pump_host_lane) — but cancel/deadline above still
                # fired, so a hung DMA can never wedge the slot
                continue
            self._advance_prefill_slot(index, state)
            chunks += 1
        return chunks

    def _advance_prefill_slot(self, index: int, state: _Slot) -> None:
        """One chunk of ``state``'s prompt through the chunked executable:
        positions ``prefill_next .. prefill_next + chunk - 1``, width
        bucketed, start/length traced. Pages wholly covered by dispatched
        chunks are adopted into the radix tree immediately — every later
        reader is dispatched after this chunk on the same pump thread and
        chains through the donated cache, so 'dispatched' is exactly the
        sharing-safety line (prefix_cache.py module docstring)."""
        request = state.request
        prompt = request.prompt
        start = state.prefill_next
        remaining = state.prefill_target - start
        length = min(remaining, self.prefill_chunk_tokens or remaining)
        width = _prefill_bucket(length, self.max_len - 1)
        head = np.zeros((1, width), np.int32)
        head[0, :length] = prompt[start:start + length]
        started = self.clock()
        event = self._dispatch_chunk_prefill(head, index, start, length)
        if self._spec is not None:
            # mirror the chunk into the draft lane BEFORE the radix tree
            # adopts its pages below — a cached page must carry both
            # lanes' K/V for its tokens (speculative.py)
            self._spec.chunk_prefill(head, index, start, length)
        state.prefill_ms += (self.clock() - started) * 1e3
        state.prefill_chunks += 1
        if state.prefill_compile != "miss":
            # a single missed chunk marks the whole request "miss" — the
            # ledger field answers "did this request pay a compile"
            state.prefill_compile = event
        record = request.record
        if record is not None and record.prefill_bucket is None:
            record.prefill_bucket = width
        state.prefill_next = start + length
        with self._lock:
            if self._slots[index] is state and self._prefix is not None:
                self._prefix.insert(prompt, self._pool.page_table[index],
                                    state.prefill_next)
                _PREFIX_CACHED_PAGES.set(self._prefix.cached_pages)
        if state.prefill_next >= state.prefill_target:
            self._finish_prefill(index, state)

    def _finish_prefill(self, index: int, state: _Slot) -> None:
        """Arm a slot whose prefill (possibly zero chunks — a full-prefix
        hit) is fully dispatched: the next decode step emits its first
        token. Closes the ledger's prefill phase and the prefill span."""
        request = state.request
        record = request.record
        now = self.clock()
        if record is not None:
            record.prefill_ms = state.prefill_ms
            record.prefill_compile = state.prefill_compile
            record.prefill_chunks = state.prefill_chunks
            if self._host_store is not None:
                # the DMA share of TTFT, split out of prefill_ms so "slow
                # join" triages to copy bandwidth vs recompute honestly
                record.host_hit_pages = state.host_hit_pages
                if state.host_hit_pages:
                    record.promote_ms = round(state.promote_ms, 3)
        _PREFILL_CHUNKS.observe(state.prefill_chunks)
        if state.prefill_chunks > 0:
            get_tracer().record_span(
                "generate.prefill", kind="generate",
                start_ts=request.wall(state.prefill_started_ts),
                duration_s=now - state.prefill_started_ts,
                request_id=request.request_id, slot=index,
                bucket=(record.prefill_bucket if record is not None
                        else None),
                compile=state.prefill_compile,
                chunks=state.prefill_chunks,
                cached_tokens=state.cached_tokens)
        with self._lock:
            if self._slots[index] is not state:
                return                       # cancelled and freed meanwhile
            if request.cancelled:
                self._free_slot_locked(index)
                self._finish_locked(request, outcome="cancelled")
                return
            state.prefill_done = True
            self._tokens[index] = request.prompt[-1]
            self._positions[index] = state.prefill_target
            self._temps[index] = request.temperature
            self._active[index] = True
            self._spec_windows[index] = [int(request.prompt[-1])]

    # -- speculative tick (docs/SERVING.md "Speculative decoding") ---------

    def _spec_operands_locked(self):
        """Host operands for the two speculative dispatches: the
        right-aligned catch-up window (tokens accepted since the draft
        last ran, ending at each slot's current token), per-slot write
        limits, and — paged — the step page table with inactive rows
        masked to the trash page (the chunk-prefill discipline: a parked
        or freed slot's speculative writes must never land on a real or
        shared page)."""
        width = self.spec_tokens + 1
        window = np.zeros((self.capacity, width), np.int32)
        lens = np.zeros(self.capacity, np.int32)
        for index in range(self.capacity):
            if not self._active[index]:
                continue
            tokens = (self._spec_windows[index]
                      or [int(self._tokens[index])])[-width:]
            lens[index] = len(tokens)
            window[index, width - len(tokens):] = tokens
        limits = self._pos_limits.copy()
        page_table = None
        if self.paged:
            page_table = self._pool.page_table.copy()
            page_table[~self._active] = TRASH_PAGE
        return window, lens, limits, page_table

    def _run_spec_verify(self, verify_window, limits, page_table):
        """Dispatch the batched target verify over ``[S, k+1]`` window
        tokens (current token + draft proposals); reassigns the donated
        cache/key and returns the device greedy/chosen arrays."""
        self._fault_point("verify")
        fn = self._fingerprint_fn("serving_spec_verify")
        _count_compile(fn,
                       (fn, self.config, self.capacity, self.spec_tokens,
                        self.top_k,
                        (self._pool.num_pages, self.page_size,
                         self._pool.max_pages_per_slot) if self.paged
                        else (self.max_len,))
                       + self._mesh_fingerprint())
        if self.paged:
            greedy, chosen, self._cache, key = _paged_spec_verify(
                self.params, self._operand(verify_window),
                self._operand(self._positions), self._operand(self._active),
                self._operand(self._temps), self._operand(limits),
                self._operand(page_table), self._cache, self._key,
                config=self.config, top_k=self.top_k)
        else:
            greedy, chosen, self._cache, key = _spec_verify(
                self.params, self._operand(verify_window),
                self._operand(self._positions), self._operand(self._active),
                self._operand(self._temps), self._operand(limits),
                self._cache, self._key,
                config=self.config, top_k=self.top_k)
        if self.mesh is not None:
            # same PRNG-key re-pin as _run_step: GSPMD may hand the key
            # back labelled over a size-1 axis, which would miss the
            # replicated-key executable once
            key = jax.device_put(key, self._replicated)
        self._key = key
        return greedy, chosen

    def _spec_decode_step(self) -> int:
        """One speculative tick: draft catch-up + k proposals, ONE batched
        target verify over all k+1 positions, then longest-matching-prefix
        acceptance as pure slot arithmetic. Greedy slots emit the target's
        own greedy tokens (matched proposals + the bonus token — identical
        to k+1 legacy steps by construction); sampled slots emit exactly
        the verify pass's one ``_choose_next`` token. Rollback is nothing
        but "don't advance past the last accepted token": rejected
        positions hold stale K/V that the next tick's writes overwrite
        before anything attends them, in both lanes."""
        with self._lock:
            stepped = [(index, slot.request)
                       for index, slot in enumerate(self._slots)
                       if slot is not None and bool(self._active[index])]
            if not stepped:
                return 0
            window, lens, limits, page_table = self._spec_operands_locked()
        # the "step" fault point covers the draft propose half of the spec
        # tick (the batched verify has its own "verify" point), so a
        # fault-plan "step" schedule hits speculative engines too; outside
        # the lock like every dispatch — an injected slow dispatch must not
        # block submitters
        self._fault_point("step")
        proposals = np.asarray(self._spec.propose(
            window, lens, self._positions, limits, page_table))
        verify_window = np.concatenate(
            [self._tokens[:, None], proposals], axis=1)
        greedy_dev, chosen_dev = self._run_spec_verify(verify_window, limits,
                                                       page_table)
        greedy = np.asarray(greedy_dev)
        chosen = np.asarray(chosen_dev)
        now = self.clock()
        with self._lock:
            self.steps += 1
            _BATCH_EFFICIENCY.observe(len(stepped) / self.capacity)
            for index, request in stepped:
                if self._slots[index] is None or (
                        self._slots[index].request is not request):
                    continue        # freed between snapshot and apply
                if self._temps[index] > 0.0:
                    # sampled slots don't speculate: one categorical token
                    # per tick, proposals discarded and not counted
                    emitted = [int(chosen[index])]
                    proposed = matched = 0
                else:
                    matched = 0
                    while (matched < self.spec_tokens
                           and int(proposals[index, matched])
                           == int(greedy[index, matched])):
                        matched += 1
                    emitted = [int(greedy[index, j])
                               for j in range(matched + 1)]
                    proposed = self.spec_tokens
                if proposed:
                    self.spec_proposed += proposed
                    self.spec_accepted += matched
                    if self.tenant_meter is not None:
                        self.tenant_meter.count_tokens(
                            request.user_key or ANONYMOUS_TENANT,
                            "spec_accepted", matched)
                    _SPEC_PROPOSED.inc(proposed)
                    # inc(0) still materializes the series: an all-rollback
                    # engine must scrape accepted=0, not an absent family
                    _SPEC_ACCEPTED.inc(matched)
                    record = request.record
                    if record is not None:
                        record.draft_tokens = (record.draft_tokens
                                               or 0) + proposed
                        record.accepted_tokens = (record.accepted_tokens
                                                  or 0) + matched
                consumed: List[int] = []
                for token in emitted:
                    # EOS inside the accepted run, the max_new budget and a
                    # pending cancel all truncate HERE, token by token —
                    # the same _apply_token_locked the legacy step uses, so
                    # the emitted stream can never outrun what the
                    # non-speculative path would have produced
                    self._tokens[index] = token
                    self._positions[index] += 1
                    self._apply_token_locked(index, request, token, now)
                    consumed.append(token)
                    if self._slots[index] is None or request.finished:
                        break
                if (self._slots[index] is not None
                        and self._slots[index].request is request):
                    # next tick's draft catch-up window = what was accepted
                    self._spec_windows[index] = consumed
            _SLOTS_BUSY.set(self._busy_locked())
        return len(stepped)

    def _decode_step(self) -> int:
        if self._spec is not None:
            return self._spec_decode_step()
        with self._lock:
            # slots still chunk-prefilling are parked (active False): they
            # join the batch only once armed, so a half-prefilled sequence
            # can never consume a decode token
            stepped = [(index, slot.request)
                       for index, slot in enumerate(self._slots)
                       if slot is not None and bool(self._active[index])]
        if not stepped:
            return 0
        chosen, self._cache, self._key = self._run_step()
        emitted = np.asarray(chosen)
        now = self.clock()
        with self._lock:
            self.steps += 1
            _BATCH_EFFICIENCY.observe(len(stepped) / self.capacity)
            for index, request in stepped:
                if self._slots[index] is None or (
                        self._slots[index].request is not request):
                    continue        # freed between snapshot and apply
                token = int(emitted[index])
                self._tokens[index] = token
                self._positions[index] += 1
                self._apply_token_locked(index, request, token, now)
            _SLOTS_BUSY.set(self._busy_locked())
        return len(stepped)

    def _apply_token_locked(self, index: int, request: _Request,
                            token: int, now: float) -> None:
        if request.cancelled:
            self._free_slot_locked(index)
            self._finish_locked(request, outcome="cancelled")
            return
        request.generated.append(token)
        self.emitted_tokens += 1
        _TOKENS.inc()
        if self.tenant_meter is not None:
            self.tenant_meter.count_tokens(
                request.user_key or ANONYMOUS_TENANT, "decode", 1)
        record = request.record
        if request.first_token_ts is None:
            request.first_token_ts = now
            ttft = now - request.submitted_ts
            _TTFT_SECONDS.observe(ttft)
            self._ttft_hist.observe(ttft)
            if record is not None:
                record.ttft_ms = ttft * 1e3
        else:
            gap = now - (request.last_token_ts or now)
            _INTERTOKEN_SECONDS.observe(gap)
            self._intertoken_hist.observe(gap)
            if record is not None:
                record._gaps_ms.append(gap * 1e3)
        request.last_token_ts = now
        if record is not None:
            record.tokens = len(request.generated)
        if request.handle is not None:
            request.handle._push(TOKEN, token)
        hit_eos = (self.eos_token is not None and token == self.eos_token)
        if hit_eos or len(request.generated) >= request.max_new_tokens:
            self._free_slot_locked(index)
            self._finish_locked(request, outcome="completed")
        elif (request.deadline_ts is not None
                and now >= request.deadline_ts):
            # mid-decode deadline: truncate AFTER delivering this token —
            # the stream ends with a terminal done chunk carrying the
            # honest "timeout" reason and whatever was generated
            _DEADLINE_TIMEOUTS.labels(phase="decode").inc()
            self._free_slot_locked(index)
            self._finish_locked(request, outcome="timeout")

    def _free_slot_locked(self, index: int) -> None:
        state = self._slots[index]
        if self._host_store is not None and state is not None:
            # a draining slot's prefix pages that NOBODY else holds (not
            # the tree, not a sharer) are about to be net-freed — spill
            # them to the host tier first, so the next identical prompt
            # promotes by DMA instead of recomputing (docs/SERVING.md
            # "KV-page tiering")
            self._queue_slot_demotions_locked(index, state)
        self._slots[index] = None
        self._active[index] = False
        self._spec_windows[index] = []
        # speculative writes to a freed slot must drop (contiguous keeps
        # its position frozen, so the limit is the only guard there)
        self._pos_limits[index] = -1
        if self.paged:
            # the pages go back to the pool NOW (they may be reassigned on
            # the very next _admit), so the parked slot must stop writing
            # through them: release() points the whole page-table row at
            # the trash page and the position resets to 0 — parked writes
            # land at (trash, 0) forever, never on a recycled page
            self._pool.release(index)
            self._positions[index] = 0
            _KV_PAGES_FREE.set(self._pool.free_pages)
            _KV_BYTES_USED.set(self._pool.used_pages
                               * self._page_hbm_bytes)
            _SLOT_PAGES.labels(slot=str(index)).set(0)
        # (contiguous) position stays frozen: the parked slot's masked
        # writes keep landing on one already-consumed coordinate of its own
        # row (see module docstring)

    def _finish_locked(self, request: _Request, outcome: str,
                       error: Optional[str] = None) -> None:
        """Terminal bookkeeping, exactly once per request. With ``error``
        the handle gets an ERROR event (the stream's ``{"error": ...}``
        terminal chunk — the supervisor's fail-fast path); otherwise a DONE
        summary carrying ``outcome`` (completed/cancelled/timeout)."""
        if request.finished:
            return
        request.finished = True
        now = self.clock()
        _REQUESTS.labels(outcome=outcome).inc()
        if outcome == "completed":
            self.completed_requests += 1
        if request.user_key:
            remaining = self._user_active.get(request.user_key, 1) - 1
            if remaining <= 0:
                self._user_active.pop(request.user_key, None)
            else:
                self._user_active[request.user_key] = remaining
        record = request.record
        if record is not None:
            if (request.first_token_ts is not None
                    and request.last_token_ts is not None):
                record.decode_ms = (request.last_token_ts
                                    - request.first_token_ts) * 1e3
            record.total_ms = (now - request.submitted_ts) * 1e3
            if self.tenant_meter is not None:
                # finalize the meter's per-request integrals onto the
                # ledger row (deviceSeconds / kvByteSeconds)
                record.device_seconds = request.device_seconds
                record.kv_byte_seconds = request.kv_byte_seconds
            get_request_ledger().finish(record, outcome,
                                        finished_ts=request.wall(now))
        if request.first_token_ts is not None:
            # the decode phase span closes with the request; spans for a
            # request that never produced a token (queue cancel, rejection)
            # would carry nothing the ledger row doesn't
            get_tracer().record_span(
                "generate.decode", kind="generate",
                start_ts=request.wall(request.first_token_ts),
                duration_s=(request.last_token_ts
                            - request.first_token_ts),
                request_id=request.request_id,
                tokens=len(request.generated), outcome=outcome)
        if request.handle is not None:
            if error is not None:
                request.handle._push(ERROR, error)
            else:
                request.handle._push(DONE, {
                    "requestId": request.request_id,
                    "tokens": list(request.generated),
                    "outcome": outcome,
                    "ttftS": (round(request.first_token_ts
                                    - request.submitted_ts, 6)
                              if request.first_token_ts is not None
                              else None),
                    "durationS": round(now - request.submitted_ts, 6),
                })

    def fail_all_inflight(self, message: str) -> int:
        """Fail-fast every queued and running request with a terminal
        ``{"error": ...}`` chunk and an ``outcome=failed`` ledger row — the
        supervisor calls this the moment a pump failure is classified
        fatal, BEFORE rebuilding the engine, so no stream ever hangs
        waiting on a dead device (docs/ROBUSTNESS.md "Serving data
        plane"). Returns how many requests were failed. Safe to call on a
        half-wedged engine: touches only host bookkeeping."""
        with self._lock:
            failed = 0
            for request in list(self._pending):
                self._finish_locked(request, outcome="failed", error=message)
                failed += 1
            self._pending.clear()
            for index, slot in enumerate(self._slots):
                if slot is None:
                    continue
                self._free_slot_locked(index)
                self._finish_locked(slot.request, outcome="failed",
                                    error=message)
                failed += 1
            _QUEUE_DEPTH.set(0)
            _SLOTS_BUSY.set(0)
            return failed

    # -- introspection ----------------------------------------------------
    def _busy_locked(self) -> int:
        return sum(1 for slot in self._slots if slot is not None)

    def stalled_slots(self, older_than_s: float) -> int:
        """Busy slots that have not emitted a token for ``older_than_s`` —
        the generate_slot_leak alert signal."""
        now = self.clock()
        with self._lock:
            count = 0
            for slot in self._slots:
                if slot is None:
                    continue
                last = (slot.request.last_token_ts
                        or slot.request.first_token_ts or slot.joined_ts)
                if now - last > older_than_s:
                    count += 1
            return count

    def stats(self) -> Dict:
        """SLO snapshot for ``GET /api/generate/stats`` + the dashboard."""
        def ms(value: Optional[float]) -> Optional[float]:
            return None if value is None else round(value * 1e3, 3)

        with self._lock:
            busy = self._busy_locked()
            return {
                "slots": self.capacity,
                "slotsBusy": busy,
                "queueDepth": len(self._pending),
                "queueCapacity": self.queue_depth,
                "draining": self._draining,
                "maxSeqLen": self.max_len,
                "meshShape": self.mesh_shape,
                "numDevices": self.num_devices,
                "paged": self.paged,
                "pageSize": self.page_size,
                "pagedKernel": self.paged_kernel,
                "kvPagesTotal": self._pool.num_pages if self.paged else None,
                "kvPagesFree": self._pool.free_pages if self.paged else None,
                "kvQuant": self.kv_quant,
                "kvBytesPerToken": (
                    round(self._page_hbm_bytes / self.page_size, 1)
                    if self.paged else None),
                "prefixCache": self.prefix_cache,
                "prefixHits": self.prefix_hits,
                "prefixMisses": self.prefix_misses,
                "prefixHitRate": (
                    round(self.prefix_hits
                          / (self.prefix_hits + self.prefix_misses), 4)
                    if self.prefix_hits + self.prefix_misses else None),
                "cachedPages": (self._prefix.cached_pages
                                if self._prefix is not None else None),
                "prefillChunkTokens": (self.prefill_chunk_tokens
                                       if self._use_chunk_prefill else None),
                "hostKvBytes": (self.host_kv_bytes
                                if self._host_store is not None else None),
                "hostPagesResident": (
                    self._host_store.resident_pages
                    if self._host_store is not None else None),
                "hostBytesUsed": (self._host_store.bytes_used
                                  if self._host_store is not None else None),
                "hostHitRate": (
                    round(self.host_kv_hits
                          / (self.host_kv_hits + self.host_kv_misses), 4)
                    if self._host_store is not None
                    and self.host_kv_hits + self.host_kv_misses else None),
                "speculative": self.speculative,
                "specTokens": (self.spec_tokens if self._spec is not None
                               else None),
                "specProposed": self.spec_proposed,
                "specAccepted": self.spec_accepted,
                "specAcceptanceRate": (
                    round(self.spec_accepted / self.spec_proposed, 4)
                    if self.spec_proposed else None),
                "requestsCompleted": self.completed_requests,
                "tokensEmitted": self.emitted_tokens,
                "steps": self.steps,
                "busySlotSeconds": (round(self.busy_slot_seconds, 6)
                                    if self.tenant_meter is not None
                                    else None),
                "ttftP50Ms": ms(self._ttft_hist.quantile(0.5)),
                "ttftP95Ms": ms(self._ttft_hist.quantile(0.95)),
                "intertokenP50Ms": ms(self._intertoken_hist.quantile(0.5)),
                "intertokenP95Ms": ms(self._intertoken_hist.quantile(0.95)),
            }

    def ttft_p95_s(self) -> Optional[float]:
        return self._ttft_hist.quantile(0.95)

    def queue_wait_p95_s(self) -> Optional[float]:
        """p95 admission-queue wait — the queue_wait_slo alert signal
        (None before the first join: an idle queue has no wait to breach)."""
        return self._queue_wait_hist.quantile(0.95)

    def queue_saturation(self) -> float:
        with self._lock:
            return len(self._pending) / self.queue_depth

    def spec_acceptance_rate(self,
                             min_proposed: int = 64) -> Optional[float]:
        """Lifetime draft-token acceptance rate — the spec_acceptance_low
        alert signal. None while the lane is off OR fewer than
        ``min_proposed`` tokens have been proposed (a handful of unlucky
        early ticks must not page anyone)."""
        if self._spec is None:
            return None
        with self._lock:
            if self.spec_proposed < min_proposed:
                return None
            return self.spec_accepted / self.spec_proposed

    def kv_page_saturation(self) -> Optional[float]:
        """Pool-fill fraction, 1.0 = exhausted (None for the contiguous
        engine — no pool, nothing to alert on). Pages held ONLY by the
        prefix cache do not count as used: they are evictable the moment
        admission needs them, and alerting on a deliberately-full cache
        would make a healthy warm cache look like exhaustion."""
        if not self.paged:
            return None
        with self._lock:
            used = self._pool.used_pages
            if self._prefix is not None:
                used -= self._pool.cached_only_pages()
            return used / self._pool.num_pages
