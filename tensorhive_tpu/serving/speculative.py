"""Speculative decoding lane: draft proposals, batched verify, exact accept.

Decode through the slot engine is one token per active slot per tick — the
per-request latency floor is one full target-model pass per token. This
module adds the classic speculative-decoding trade (ROADMAP item 2): a
small DRAFT model proposes ``spec_tokens`` (k) greedy continuations per
slot per tick from its own KV lane, and the TARGET model verifies all
``k + 1`` positions in ONE batched window pass — when the draft is right,
one target pass emits several tokens; when it is wrong, the tick degrades
to exactly the one token the non-speculative step would have emitted.

Exactness is draft-INDEPENDENT, by construction: the verify pass computes
the target's greedy token at every window position given the true prefix,
and acceptance is longest-matching-prefix arithmetic over (proposal,
target-greedy) pairs — the emitted stream is always the target's own
greedy tokens, so greedy speculative output is token-identical to greedy
non-speculative output no matter how good or bad the draft is (the hard
gate tools/spec_smoke.py and tests/unit/test_speculative.py pin, across
paged/contiguous layouts and under a dp x tp mesh). A bad draft costs
throughput, never correctness.

Design, in the order the constraints forced it:

* **The draft lane rides the engine's page table.** The draft KV cache is
  a SECOND physical array (``[draft_layers, pages, page_size, kv_heads,
  d_head]``) indexed by the SAME per-slot page tables as the target cache:
  no second allocator, no second accounting, and the PR 11 pool invariant
  (free + live == pool size) holds with the lane on by construction.
  Shared prefix pages carry BOTH lanes' K/V — the draft prefill mirrors
  every target prefill chunk through the same table row, so a radix-tree
  hit skips the cached positions in both lanes at once.
* **Catch-up makes rollback free for the draft.** Each tick the draft
  first re-processes the tokens ACCEPTED last tick (a right-aligned
  ``[S, k+1]`` window ending at the slot's current position) — overwriting
  whatever speculative K/V it wrote while proposing — and only then rolls
  k fresh proposals. By induction the draft lane's K/V below the current
  position always encodes the true accepted stream, so rejected proposals
  need no scrub pass in either lane: both lanes "roll back" by pure
  position arithmetic, exactly like the engine's parked-slot argument
  (stale cells sit at positions > position, masked until rewritten).
* **Verify reuses the chunk-prefill attend seam.** The verify executable
  is the PR 11 chunked-prefill trunk generalized from ``[1, W]`` to
  ``[S, W]``: write the window's K/V through the page-table rows, gather
  each slot's page run into logical order, and attend under the
  positional causal mask (:func:`_window_attend` is
  ``models/decode._decode_attend`` generalized from one query to W —
  same grouped einsum, same mask constant, same f32 softmax — so the
  window pass and the single-token step cannot drift).
* **Everything traced, two fingerprints.** Window tokens/lengths,
  positions, per-slot write limits and page tables are operands; only the
  window width (``spec_tokens + 1``) and the configs are static. The two
  new executables are fingerprinted ``serving_spec_draft`` (catch-up +
  propose, plus the draft-lane prefill mirrors) and ``serving_spec_verify``
  through the ``_count_compile`` seam, so the zero-recompile gates see
  them and TH-JIT polices the dispatches.
* **Sampled slots don't speculate.** Exact speculative SAMPLING needs
  rejection-sampling bookkeeping this lane does not ship; a slot with
  temperature > 0 takes exactly one token per tick from the verify pass's
  first position (sampled with the same ``_choose_next`` semantics as the
  legacy step — note the PRNG stream advances once per TICK, not once per
  token, so sampled streams differ from the non-speculative path; greedy
  is unaffected). Draft work for sampled slots is discarded and not
  counted in the acceptance metrics.
* **The draft is free when self-drafting.** With no ``draft_preset``
  configured the draft is the target's own first ``draft_layers`` layers
  (embedding/head/final-norm shared by reference — zero extra param HBM);
  ``draft_layers = n_layers`` makes the draft exactly the target (100%
  acceptance — the full-accept test lever), a separate preset gives an
  independent draft that must share the tokenizer/vocab.

``speculative = off`` is a byte-identical rollback: the engine never
imports this module, dispatches the PR 6-11 executables with untouched
fingerprints, and the stats/ledger speculative fields read off/None
(docs/SERVING.md "Speculative decoding").
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.decode import (
    KVCache,
    QuantKVCache,
    _count_compile,
    _decode_attend,
    _paged_attend,
)
from ..ops import kv_quant as kvq
from ..models.transformer import (
    TransformerConfig,
    TransformerLM,
    _rmsnorm,
)


def resolve_speculative(mode: str) -> str:
    """Resolve the ``speculative = auto|on|off`` knob once at engine
    construction (the ``paged_kernel`` pattern): ``auto`` enables the lane
    only on a real TPU backend, where the batched verify is cheap relative
    to the draft's extra passes — on CPU the draft overhead routinely makes
    speculation a slowdown (bench records it honestly), so auto stays off
    there and enabling is an explicit operator decision."""
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"speculative must be auto|on|off, got {mode!r}")
    if mode != "auto":
        return mode
    return "on" if jax.default_backend() == "tpu" else "off"


def build_draft(params, config: TransformerConfig, draft_preset: str = "",
                draft_layers: int = 0) -> Tuple[dict, TransformerConfig,
                                                bool]:
    """Build the draft model: ``(draft_params, draft_config, shares_target)``.

    Self-draft (no preset): the draft IS the target truncated to its first
    ``draft_layers`` blocks (default half, min 1) — embedding, final norm
    and LM head are the SAME arrays (and, under a mesh, already carry the
    target's shardings), so the lane costs zero extra parameter HBM and its
    proposals are correlated with the target by construction.
    ``draft_layers = n_layers`` degenerates to draft == target (always
    accepts — the deterministic full-accept lever the tests use).

    A named ``draft_preset`` builds an independent model that must share
    the tokenizer/vocab (the proposals are token ids the target verifies);
    its params are random-init — serving a trained draft rides the same
    checkpoint story as the target (not wired yet; acceptance with init
    params is honest noise, and exactness never depends on it)."""
    if draft_preset:
        from ..models.transformer import PRESETS

        if draft_preset not in PRESETS:
            raise ValueError(
                f"draft_preset {draft_preset!r} unknown; choose from "
                f"{sorted(PRESETS)}")
        base = PRESETS[draft_preset]
        if base.vocab_size != config.vocab_size:
            raise ValueError(
                f"draft_preset {draft_preset!r} has vocab "
                f"{base.vocab_size}, the target serves {config.vocab_size} "
                "— speculative proposals are token ids, the tokenizers "
                "must match")
        draft_config = dataclasses.replace(
            base, dtype=config.dtype, use_flash=config.use_flash,
            remat=config.remat,
            max_seq_len=max(base.max_seq_len, config.max_seq_len),
            causal=True)
        draft_params = TransformerLM.init(jax.random.PRNGKey(7),
                                          draft_config)
        return draft_params, draft_config, False
    layers = int(draft_layers) or max(1, config.n_layers // 2)
    if not 1 <= layers <= config.n_layers:
        raise ValueError(
            f"draft_layers must be in [1, {config.n_layers}], got {layers}")
    draft_config = dataclasses.replace(config, n_layers=layers)
    draft_params = {
        "tok_embed": params["tok_embed"],
        "blocks": list(params["blocks"][:layers]),
        "final_norm": params["final_norm"],
        "w_lm_head": params["w_lm_head"],
    }
    return draft_params, draft_config, True


def _window_attend(q, k_ctx, v_ctx, q_positions):
    """Attention for a ``[S, W]`` token window against each slot's full
    logical context: :func:`models/decode._decode_attend` generalized from
    one query per slot to W — the SAME grouped einsum spec, the same
    ``-1e30`` mask constant, the same f32 softmax and ``probs.astype(
    v.dtype)`` product, so at W == 1 this is bit-for-bit the decode attend
    and the verify window cannot drift from the step path it replaces.

    ``q``: [S, W, H, Dh]; ``k_ctx``/``v_ctx``: [S, K, Hkv, Dh] — the slot's
    gathered page run (paged) or its contiguous cache row; ``q_positions``:
    [S, W] absolute positions. The mask attends key position p from query
    position w only when ``p <= w``; cells past the query hold stale or
    trash K/V, sent to -1e30 and exp-underflowed to exactly 0.0 — the same
    argument that makes the paged gather and the chunk-prefill attend
    (engine._chunk_attend) f32-exact."""
    num_slots, width, heads, d_head = q.shape
    kv_heads = k_ctx.shape[2]
    group = heads // kv_heads
    scale = d_head ** -0.5
    q_grouped = q.reshape(num_slots, width, kv_heads, group, d_head)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", q_grouped, k_ctx,
                        preferred_element_type=jnp.float32) * scale
    key_positions = jax.lax.iota(jnp.int32, k_ctx.shape[1])
    mask = (key_positions[None, None, None, None, :]
            <= q_positions[:, None, None, :, None])
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v_ctx.dtype), v_ctx,
                     preferred_element_type=jnp.float32)
    return out.reshape(num_slots, width, heads, d_head).astype(q.dtype)


def _head_logits(params, x, config: TransformerConfig):
    """Final norm + LM head over a ``[S, W, D]`` trunk output — the
    ``_choose_next`` tail generalized to a window (same per-element
    contraction, so position 0 of this and ``_choose_next``'s own logits
    agree bit-for-bit)."""
    x = _rmsnorm(x, params["final_norm"]["scale"])
    return jnp.einsum("swd,dv->swv", x.astype(config.dtype),
                      params["w_lm_head"].astype(config.dtype),
                      preferred_element_type=jnp.float32)


# -- draft lane: catch-up + propose ------------------------------------------

def _paged_draft_step(params, token, step_positions, limits, page_tables,
                      cache_k, cache_v, config: TransformerConfig,
                      scale_k=None, scale_v=None):
    """One greedy draft step at traced per-slot positions over the paged
    draft cache: write the token's K/V through the page-table row (writes
    past ``limits`` — or through an inactive slot's trash-masked row —
    route out of bounds and drop), attend via the XLA page gather, argmax.
    Mirrors ``engine._paged_step_body`` minus sampling — including the
    int8 branch (``scale_k``/``scale_v`` present), which quantizes the
    write onto its page's running-max scale and attends the dequantized
    gather (ops/kv_quant.py)."""
    dtype = config.dtype
    num_slots = token.shape[0]
    num_physical = cache_k.shape[1]
    page_size = cache_k.shape[2]
    max_pages = page_tables.shape[1]
    slot_ids = jnp.arange(num_slots)
    safe = jnp.clip(step_positions, 0, max_pages * page_size - 1)
    rows = page_tables[slot_ids, safe // page_size]
    pages = jnp.where(step_positions <= limits, rows, num_physical)
    offsets = safe % page_size
    quant = scale_k is not None
    x = params["tok_embed"].astype(dtype)[token][:, None, :]
    rope_positions = step_positions[:, None]

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v, scale_k, scale_v
        if quant:
            layer_k, layer_ks = kvq.step_write(
                cache_k[layer], scale_k[layer], pages, offsets, k[:, 0])
            layer_v, layer_vs = kvq.step_write(
                cache_v[layer], scale_v[layer], pages, offsets, v[:, 0])
            scale_k = jax.lax.dynamic_update_slice(
                scale_k, layer_ks[None], (layer, 0, 0))
            scale_v = jax.lax.dynamic_update_slice(
                scale_v, layer_vs[None], (layer, 0, 0))
        else:
            layer_k = cache_k[layer].at[pages, offsets].set(
                k[:, 0].astype(cache_k.dtype), mode="drop")
            layer_v = cache_v[layer].at[pages, offsets].set(
                v[:, 0].astype(cache_v.dtype), mode="drop")
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        return _paged_attend(q, cache_k[layer], cache_v[layer], page_tables,
                             step_positions,
                             k_scales=scale_k[layer] if quant else None,
                             v_scales=scale_v[layer] if quant else None)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, rope_positions,
                                        attend, layer_index=layer_index)
    logits = _head_logits(params, x, config)[:, 0]
    return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
            cache_k, cache_v, scale_k, scale_v)


def _paged_draft_propose_body(params, window_tokens, window_lens, positions,
                              limits, page_tables, cache,
                              config: TransformerConfig):
    """Catch up the draft lane on last tick's accepted tokens, then propose
    ``k = W - 1`` greedy continuations per slot.

    ``window_tokens`` [S, W] is RIGHT-ALIGNED: entry ``W-1`` is the slot's
    current token at ``positions[s]``, entry ``W-1-j`` the token j
    positions earlier; only the last ``window_lens[s]`` entries are real
    (the tokens emitted since the draft last ran — at most k+1 on a full
    accept, exactly 1 at a fresh join). Phase A writes their K/V through
    the page table (overwriting last tick's speculative writes — the draft
    lane's whole rollback) and attends the batched window; its last
    position's argmax is proposal 1. Phase B rolls k-1 single-token steps,
    each writing speculative K/V at ``positions + j`` before attending it.
    Invalid window cells (padding, positions past ``limits``) route out of
    bounds and drop, so a parked or freed slot's lane is never touched."""
    dtype = config.dtype
    num_slots, width = window_tokens.shape
    cache_k, cache_v = cache.k, cache.v
    quant = isinstance(cache, QuantKVCache)
    scale_k = cache.k_scale if quant else None
    scale_v = cache.v_scale if quant else None
    num_physical = cache_k.shape[1]
    page_size = cache_k.shape[2]
    max_pages = page_tables.shape[1]
    window_ctx = max_pages * page_size
    win = jnp.arange(width, dtype=jnp.int32)
    global_positions = positions[:, None] - (width - 1) + win[None, :]
    valid = ((win[None, :] >= width - window_lens[:, None])
             & (global_positions >= 0)
             & (global_positions <= limits[:, None]))
    safe_pos = jnp.clip(global_positions, 0, window_ctx - 1)
    rows = jnp.take_along_axis(page_tables, safe_pos // page_size, axis=1)
    pages = jnp.where(valid, rows, num_physical)          # OOB -> dropped
    offsets = safe_pos % page_size
    x = params["tok_embed"].astype(dtype)[window_tokens]

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v, scale_k, scale_v
        if quant:
            layer_k, layer_ks, ctx_k = kvq.row_merge(
                cache_k[layer], scale_k[layer], page_tables,
                k, safe_pos, valid, dtype)
            layer_v, layer_vs, ctx_v = kvq.row_merge(
                cache_v[layer], scale_v[layer], page_tables,
                v, safe_pos, valid, dtype)
            scale_k = jax.lax.dynamic_update_slice(
                scale_k, layer_ks[None], (layer, 0, 0))
            scale_v = jax.lax.dynamic_update_slice(
                scale_v, layer_vs[None], (layer, 0, 0))
        else:
            layer_k = cache_k[layer].at[pages, offsets].set(
                k.astype(cache_k.dtype), mode="drop")
            layer_v = cache_v[layer].at[pages, offsets].set(
                v.astype(cache_v.dtype), mode="drop")
            ctx_k = layer_k[page_tables].reshape(num_slots, window_ctx,
                                                 *layer_k.shape[2:])
            ctx_v = layer_v[page_tables].reshape(num_slots, window_ctx,
                                                 *layer_v.shape[2:])
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        return _window_attend(q, ctx_k, ctx_v, safe_pos)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, safe_pos, attend,
                                        layer_index=layer_index)
    logits = _head_logits(params, x[:, -1:], config)[:, 0]
    token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    proposals = [token]
    for step in range(1, width - 1):
        token, cache_k, cache_v, scale_k, scale_v = _paged_draft_step(
            params, token, positions + step, limits, page_tables,
            cache_k, cache_v, config, scale_k=scale_k, scale_v=scale_v)
        proposals.append(token)
    if quant:
        return jnp.stack(proposals, axis=1), QuantKVCache(
            k=cache_k, v=cache_v, k_scale=scale_k, v_scale=scale_v)
    return jnp.stack(proposals, axis=1), KVCache(k=cache_k, v=cache_v)


def _draft_step(params, token, step_positions, limits, cache_k, cache_v,
                config: TransformerConfig):
    """Contiguous twin of :func:`_paged_draft_step`: the write lands at
    ``(slot, position)`` of the slot's own cache row (past-limit writes
    route out of bounds and drop) and the attend is the plain masked
    decode attend over the row."""
    dtype = config.dtype
    num_slots = token.shape[0]
    max_len = cache_k.shape[2]
    slot_ids = jnp.arange(num_slots)
    write_pos = jnp.where(step_positions <= limits, step_positions, max_len)
    x = params["tok_embed"].astype(dtype)[token][:, None, :]
    rope_positions = step_positions[:, None]

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v
        layer_k = cache_k[layer].at[slot_ids, write_pos].set(
            k[:, 0].astype(cache_k.dtype), mode="drop")
        layer_v = cache_v[layer].at[slot_ids, write_pos].set(
            v[:, 0].astype(cache_v.dtype), mode="drop")
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        return _decode_attend(q, cache_k[layer], cache_v[layer],
                              step_positions[:, None, None, None, None])

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, rope_positions,
                                        attend, layer_index=layer_index)
    logits = _head_logits(params, x, config)[:, 0]
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache_k, cache_v


def _draft_propose_body(params, window_tokens, window_lens, positions,
                        limits, cache, config: TransformerConfig):
    """Contiguous twin of :func:`_paged_draft_propose_body`: same window
    layout and phases, writes scattered into each slot's cache row and the
    attend context IS the row (no gather)."""
    dtype = config.dtype
    num_slots, width = window_tokens.shape
    cache_k, cache_v = cache.k, cache.v
    max_len = cache_k.shape[2]
    slot_ids = jnp.arange(num_slots)
    win = jnp.arange(width, dtype=jnp.int32)
    global_positions = positions[:, None] - (width - 1) + win[None, :]
    valid = ((win[None, :] >= width - window_lens[:, None])
             & (global_positions >= 0)
             & (global_positions <= limits[:, None]))
    safe_pos = jnp.clip(global_positions, 0, max_len - 1)
    write_pos = jnp.where(valid, safe_pos, max_len)       # OOB -> dropped
    x = params["tok_embed"].astype(dtype)[window_tokens]

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v
        layer_k = cache_k[layer].at[slot_ids[:, None], write_pos].set(
            k.astype(cache_k.dtype), mode="drop")
        layer_v = cache_v[layer].at[slot_ids[:, None], write_pos].set(
            v.astype(cache_v.dtype), mode="drop")
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        return _window_attend(q, layer_k, layer_v, safe_pos)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, safe_pos, attend,
                                        layer_index=layer_index)
    logits = _head_logits(params, x[:, -1:], config)[:, 0]
    token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    proposals = [token]
    for step in range(1, width - 1):
        token, cache_k, cache_v = _draft_step(
            params, token, positions + step, limits, cache_k, cache_v,
            config)
        proposals.append(token)
    return jnp.stack(proposals, axis=1), KVCache(k=cache_k, v=cache_v)


_paged_spec_draft = functools.partial(
    jax.jit, static_argnames=("config",),
    donate_argnames=("cache",))(_paged_draft_propose_body)
_spec_draft = functools.partial(
    jax.jit, static_argnames=("config",),
    donate_argnames=("cache",))(_draft_propose_body)


# -- target verify ------------------------------------------------------------

def _paged_spec_verify_body(params, window_tokens, positions, active, temps,
                            limits, page_tables, cache, key,
                            config: TransformerConfig, top_k: Optional[int]):
    """Verify all ``k + 1`` window positions in one batched target pass.

    ``window_tokens`` [S, W] is LEFT-ALIGNED: entry 0 is the slot's current
    token at ``positions[s]``, entries 1..k the draft proposals at the k
    positions after it. Every position's K/V is written through the page
    table (positions past ``limits`` drop — near the end of a request's
    budget the tail of the window is discarded host-side anyway), the
    whole page run gathers into logical order, and :func:`_window_attend`
    applies the positional causal mask — the chunk-prefill seam batched
    over slots. Returns the target's greedy token at EVERY window position
    (``greedy[s, j]`` is the token for position ``positions[s] + j + 1``
    given the true prefix plus proposals 1..j — exactly what the
    sequential step path would emit, which is the whole identity
    argument), plus the ``_choose_next`` pick for position 0 (greedy slots
    get argmax; sampled slots get their one categorical token per tick)."""
    from .engine import _choose_next

    dtype = config.dtype
    num_slots, width = window_tokens.shape
    cache_k, cache_v = cache.k, cache.v
    quant = isinstance(cache, QuantKVCache)
    scale_k = cache.k_scale if quant else None
    scale_v = cache.v_scale if quant else None
    num_physical = cache_k.shape[1]
    page_size = cache_k.shape[2]
    max_pages = page_tables.shape[1]
    window_ctx = max_pages * page_size
    win = jnp.arange(width, dtype=jnp.int32)
    global_positions = positions[:, None] + win[None, :]
    writable = global_positions <= limits[:, None]
    safe_pos = jnp.clip(global_positions, 0, window_ctx - 1)
    rows = jnp.take_along_axis(page_tables, safe_pos // page_size, axis=1)
    pages = jnp.where(writable, rows, num_physical)       # OOB -> dropped
    offsets = safe_pos % page_size
    x = params["tok_embed"].astype(dtype)[window_tokens]

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v, scale_k, scale_v
        if quant:
            layer_k, layer_ks, ctx_k = kvq.row_merge(
                cache_k[layer], scale_k[layer], page_tables,
                k, safe_pos, writable, dtype)
            layer_v, layer_vs, ctx_v = kvq.row_merge(
                cache_v[layer], scale_v[layer], page_tables,
                v, safe_pos, writable, dtype)
            scale_k = jax.lax.dynamic_update_slice(
                scale_k, layer_ks[None], (layer, 0, 0))
            scale_v = jax.lax.dynamic_update_slice(
                scale_v, layer_vs[None], (layer, 0, 0))
        else:
            layer_k = cache_k[layer].at[pages, offsets].set(
                k.astype(cache_k.dtype), mode="drop")
            layer_v = cache_v[layer].at[pages, offsets].set(
                v.astype(cache_v.dtype), mode="drop")
            ctx_k = layer_k[page_tables].reshape(num_slots, window_ctx,
                                                 *layer_k.shape[2:])
            ctx_v = layer_v[page_tables].reshape(num_slots, window_ctx,
                                                 *layer_v.shape[2:])
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        return _window_attend(q, ctx_k, ctx_v, safe_pos)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, safe_pos, attend,
                                        layer_index=layer_index)
    chosen, key = _choose_next(params, x[:, :1], window_tokens[:, 0],
                               active, temps, key, config, top_k)
    greedy = jnp.argmax(_head_logits(params, x, config),
                        axis=-1).astype(jnp.int32)
    if quant:
        return greedy, chosen, QuantKVCache(
            k=cache_k, v=cache_v, k_scale=scale_k, v_scale=scale_v), key
    return greedy, chosen, KVCache(k=cache_k, v=cache_v), key


def _spec_verify_body(params, window_tokens, positions, active, temps,
                      limits, cache, key, config: TransformerConfig,
                      top_k: Optional[int]):
    """Contiguous twin of :func:`_paged_spec_verify_body`: window K/V
    scatters into each slot's cache row (past-limit and freed-slot writes
    drop — a freed contiguous slot's limit is -1, so verify never touches
    its row) and the attend context is the row itself."""
    from .engine import _choose_next

    dtype = config.dtype
    num_slots, width = window_tokens.shape
    cache_k, cache_v = cache.k, cache.v
    max_len = cache_k.shape[2]
    slot_ids = jnp.arange(num_slots)
    win = jnp.arange(width, dtype=jnp.int32)
    global_positions = positions[:, None] + win[None, :]
    writable = global_positions <= limits[:, None]
    safe_pos = jnp.clip(global_positions, 0, max_len - 1)
    write_pos = jnp.where(writable, safe_pos, max_len)    # OOB -> dropped
    x = params["tok_embed"].astype(dtype)[window_tokens]

    def attend(q, k, v, layer):
        nonlocal cache_k, cache_v
        layer_k = cache_k[layer].at[slot_ids[:, None], write_pos].set(
            k.astype(cache_k.dtype), mode="drop")
        layer_v = cache_v[layer].at[slot_ids[:, None], write_pos].set(
            v.astype(cache_v.dtype), mode="drop")
        cache_k = jax.lax.dynamic_update_slice(
            cache_k, layer_k[None], (layer, 0, 0, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(
            cache_v, layer_v[None], (layer, 0, 0, 0, 0))
        return _window_attend(q, layer_k, layer_v, safe_pos)

    for layer_index, block in enumerate(params["blocks"]):
        x = TransformerLM.block_forward(x, block, config, safe_pos, attend,
                                        layer_index=layer_index)
    chosen, key = _choose_next(params, x[:, :1], window_tokens[:, 0],
                               active, temps, key, config, top_k)
    greedy = jnp.argmax(_head_logits(params, x, config),
                        axis=-1).astype(jnp.int32)
    return greedy, chosen, KVCache(k=cache_k, v=cache_v), key


_paged_spec_verify = functools.partial(
    jax.jit, static_argnames=("config", "top_k"),
    donate_argnames=("cache",))(_paged_spec_verify_body)
_spec_verify = functools.partial(
    jax.jit, static_argnames=("config", "top_k"),
    donate_argnames=("cache",))(_spec_verify_body)


# -- the lane -----------------------------------------------------------------

class SpeculativeLane:
    """The draft side of the speculative engine: draft params/config, the
    draft KV cache (same layout family and page tables as the target's),
    and the dispatchers that mirror the engine's prefills and roll the
    per-tick proposals. Device calls follow the engine's discipline: only
    the pump thread dispatches, every donated buffer is reassigned from
    the output, and every dispatch is fingerprinted through
    ``_count_compile`` (family ``serving_spec_draft``)."""

    def __init__(self, engine, draft_params, draft_config: TransformerConfig,
                 shares_target: bool) -> None:
        self._engine = engine
        self.draft_config = draft_config
        self.shares_target = shares_target
        if engine.paged:
            shape = (draft_config.n_layers, engine._pool.physical_pages,
                     engine.page_size, draft_config.kv_heads,
                     draft_config.d_head)
        else:
            shape = (draft_config.n_layers, engine.capacity, engine.max_len,
                     draft_config.kv_heads, draft_config.d_head)
        if engine._quant:
            # the draft lane quantizes like the target lane: its own int8
            # pages + scale side-arrays behind the SAME page tables, so the
            # kv_quant capacity math covers both lanes' HBM equally
            scale_shape = (draft_config.n_layers, shape[1],
                           draft_config.kv_heads)
            cache = QuantKVCache(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                k_scale=jnp.zeros(scale_shape, jnp.float32),
                v_scale=jnp.zeros(scale_shape, jnp.float32))
        else:
            cache = KVCache(k=jnp.zeros(shape, draft_config.dtype),
                            v=jnp.zeros(shape, draft_config.dtype))
        self.params = draft_params
        if engine.mesh is not None:
            from jax.sharding import NamedSharding

            from ..parallel.mesh import (
                serving_cache_spec,
                serving_rules,
                tree_shardings,
            )

            rules = serving_rules(draft_config, engine.mesh_tp)
            if not shares_target:
                # a preset draft's fresh params need their own shardings;
                # self-draft params ARE the target's leaves, already placed
                self.params = jax.device_put(
                    draft_params,
                    tree_shardings(engine.mesh, draft_params, rules))
            sharding = NamedSharding(engine.mesh, serving_cache_spec(rules))
            if engine._quant:
                from ..parallel.mesh import serving_scale_spec

                scale_sharding = NamedSharding(engine.mesh,
                                               serving_scale_spec(rules))
                cache = jax.device_put(cache, QuantKVCache(
                    k=sharding, v=sharding,
                    k_scale=scale_sharding, v_scale=scale_sharding))
            else:
                cache = jax.device_put(cache,
                                       KVCache(k=sharding, v=sharding))
        self.cache = cache

    # -- fingerprints ------------------------------------------------------
    def _count_compile_draft(self, kind: str, *shape_bits) -> str:
        engine = self._engine
        fn = engine._fingerprint_fn("serving_spec_draft")
        if engine.paged:
            pool = (engine._pool.num_pages, engine.page_size,
                    engine._pool.max_pages_per_slot)
        else:
            pool = (engine.capacity, engine.max_len)
        return _count_compile(fn, (fn, kind, self.draft_config, pool,
                                   *shape_bits) + engine._mesh_fingerprint())

    # -- prefill mirrors ---------------------------------------------------
    def prefill(self, head, slot: int, real_len: int) -> None:
        """Mirror one legacy whole-prompt prefill into the draft lane:
        same head tokens, same slot/table row, the DRAFT params/config/
        cache — the shared jitted prefill bodies compile one extra
        executable per bucket for the draft config (warmed like the
        target's) and the lane's K/V for the prompt lands in the same
        pages the target's did."""
        engine = self._engine
        from .engine import _paged_serving_prefill, _serving_prefill

        self._count_compile_draft("prefill", head.shape[1])
        if engine.paged:
            self.cache = _paged_serving_prefill(
                self.params, engine._operand(head), self.cache,
                engine._operand(engine._pool.page_table[slot]),
                engine._operand(np.int32(real_len)), self.draft_config)
        else:
            self.cache = _serving_prefill(
                self.params, engine._operand(head), self.cache,
                engine._operand(np.int32(slot)),
                engine._operand(np.int32(real_len)), self.draft_config)

    def chunk_prefill(self, head, slot: int, start: int,
                      real_len: int) -> None:
        """Mirror one chunked prefill (prefix-cache path) into the draft
        lane — dispatched right after the target's chunk and BEFORE the
        radix tree adopts the chunk's pages, so a page entering the tree
        always carries both lanes' K/V for its tokens."""
        engine = self._engine
        from .engine import _paged_chunk_serving_prefill

        self._count_compile_draft("chunk_prefill", head.shape[1])
        self.cache = _paged_chunk_serving_prefill(
            self.params, engine._operand(head), self.cache,
            engine._operand(engine._pool.page_table[slot]),
            engine._operand(np.int32(start)),
            engine._operand(np.int32(real_len)), self.draft_config)

    # -- propose -----------------------------------------------------------
    def propose(self, window, lens, positions, limits, page_table):
        """Catch up on last tick's accepted tokens and roll ``spec_tokens``
        proposals per slot; returns the device array of proposals
        ``[S, k]`` (the engine syncs it once per tick)."""
        engine = self._engine
        self._count_compile_draft("propose", window.shape[1])
        if engine.paged:
            proposals, self.cache = _paged_spec_draft(
                self.params, engine._operand(window), engine._operand(lens),
                engine._operand(positions), engine._operand(limits),
                engine._operand(page_table), self.cache,
                config=self.draft_config)
        else:
            proposals, self.cache = _spec_draft(
                self.params, engine._operand(window), engine._operand(lens),
                engine._operand(positions), engine._operand(limits),
                self.cache, config=self.draft_config)
        return proposals

    @property
    def propose_executable(self):
        """The jitted propose function this lane dispatches —
        ``._cache_size()`` is the draft side of the zero-recompile ground
        truth (the prefill mirrors ride the engine's prefill executables)."""
        return _paged_spec_draft if self._engine.paged else _spec_draft
