"""Radix prefix cache: shared-prompt page reuse for the paged slot engine.

At production fan-in most generate requests open with the same long system
prompt, yet the PR 7 engine charges every admit the FULL page count and
re-prefills the whole prompt (ROADMAP item 1 — "the single biggest
capacity *and* latency lever left in the data plane"). This module is the
host side of closing that gap: a radix tree over token-id prefixes maps
"prompt prefix -> physical page run" so that

* **admission charges only the unique suffix** — matched pages are granted
  SHARED (``PagePool.assign_shared`` bumps their refcount; the joiner never
  writes them), and only ``pages_for(prompt + max_new) - matched`` fresh
  pages come off the free list;
* **prefill skips straight to the first uncached position** — the engine's
  chunked prefill executable takes the start offset as a traced operand,
  so a 4k-token prompt whose first 4k-ε tokens are cached prefills ε
  positions (docs/SERVING.md "Prefix cache & chunked prefill");
* **pool pressure evicts LRU, never a referenced page** — tree nodes whose
  page no slot holds (refcount 1 = cache-only) are reclaimed leaf-first in
  least-recently-matched order when admission runs short.

Granularity and the copy-on-write rule: the sharing unit is one FULL page
(``page_size`` positions). K/V at position ``p`` depends only on tokens
``0..p`` at the same positions, so a page is reusable exactly when the
whole token prefix through its last position matches — the tree therefore
keys each edge on a page-sized token tuple. A request whose prompt
diverges (or merely ends) MID-page never writes the shared page: the match
stops at the last fully-matched page boundary, the divergent page is
realized as a freshly-allocated private page, and its positions are
recomputed by the prefill chunk (copy-by-recompute: at most
``page_size - 1`` positions, cheaper than a device page copy and — more
importantly — it keeps the executable set fixed, so COW can never
recompile). Writes to shared pages are impossible by construction, which
is what lets refcount bookkeeping alone guarantee isolation.

Quantized engines (``kv_quant = on``) change nothing here: a cached page
carries int8 K/V plus its per-(page, kv_head) scales (ops/kv_quant.py),
the COW rule already guarantees no sharer ever writes it — and since a
window write can only requantize pages it actually wrote, a shared page's
bytes AND scales are frozen while referenced, which is what makes a hit
read byte-for-byte what the miss stored (hit ≡ miss token identity, pinned
under quantization in tests/unit/test_kv_quant.py).

Readiness: a page enters the tree only after the prefill chunk covering
its last position has been DISPATCHED. All executables chain through the
one donated cache buffer on the single pump thread, so any later-dispatched
reader observes the writer's output — "dispatched" is the exact safety
line, and it lets a burst of identical prompts share pages the first
request is still computing ticks ahead of them.

Like :mod:`tensorhive_tpu.serving.paging`, this module is deliberately
jax-free host bookkeeping: the property tests churn joins/leaves/cancels/
evictions over it without a device. The engine serializes all calls under
its own lock (match/insert/evict mutate LRU stamps and refcounts).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .paging import PagePool, page_content_key


class _Node:
    """One cached page: the radix-tree edge for its ``page_size``-token
    chunk. Children key on the NEXT page's token tuple — edges are
    page-granular, so path compression would never merge anything and the
    'radix tree' is a trie whose edge labels are page-sized token runs."""

    __slots__ = ("tokens", "page", "parent", "children", "last_used")

    def __init__(self, tokens: Tuple[int, ...], page: int,
                 parent: Optional["_Node"], last_used: int) -> None:
        self.tokens = tokens
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = last_used


class PrefixCache:
    """Radix tree over token-id prefixes -> physical page runs.

    Holds one :class:`~tensorhive_tpu.serving.paging.PagePool` reference
    per cached page (``cache_ref``), so cached pages survive their
    computing slot's departure and are reclaimable (``evict``) the moment
    no slot shares them. ``min_tokens`` gates matching (a 16-token hit is
    not worth the shared-grant bookkeeping on a 4k prompt), never
    insertion — short prefixes still seed the tree for longer ones.
    """

    def __init__(self, pool: PagePool, min_tokens: int = 0) -> None:
        self.pool = pool
        self.page_size = pool.page_size
        self.min_tokens = max(0, int(min_tokens))
        self._root: Dict[Tuple[int, ...], _Node] = {}
        self._nodes = 0
        self._tick = 0          # monotonic LRU stamp (no wall clock needed)
        self.evictions = 0      # lifetime pages evicted (the thrash signal)
        #: demote-on-evict hook (docs/SERVING.md "KV-page tiering"): called
        #: as ``spill(content_key, page)`` for every eviction victim BEFORE
        #: its reference drops — the page's payload is still intact in HBM
        #: at that moment, so the engine can queue a host-tier extraction
        #: of exactly the bytes the tree is letting go. None = no tiering
        #: (the host_kv_bytes=0 rollback: evict behaves byte-identically
        #: to PR 11).
        self.spill: Optional[Callable[[bytes, int], None]] = None

    # -- introspection -----------------------------------------------------
    @property
    def cached_pages(self) -> int:
        """Pages currently retained by the tree (the
        ``tpuhive_generate_prefix_cached_pages`` gauge)."""
        return self._nodes

    def evictable_pages(self) -> int:
        """Cached pages no slot currently shares — reclaimable headroom."""
        return sum(1 for node in self._iter_nodes()
                   if self.pool.refcount(node.page) == 1)

    def _iter_nodes(self):
        stack = list(self._root.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    # -- the cacheable span of a prompt ------------------------------------
    def cacheable_tokens(self, prompt_len: int) -> int:
        """How many leading tokens of a ``prompt_len`` prompt are ever
        shareable: whole pages only, and never the page holding position
        ``prompt_len - 1`` — the first decode step writes there, and a
        shared page must never be written (the COW rule)."""
        return ((max(0, prompt_len - 1)) // self.page_size) * self.page_size

    # -- match -------------------------------------------------------------
    def match(self, prompt: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached prefix of ``prompt``: ``(cached_tokens, pages)``.

        ``cached_tokens`` is a multiple of ``page_size`` capped at
        :meth:`cacheable_tokens`; ``pages`` is the physical run backing it,
        in logical order, suitable for ``PagePool.assign_shared``. Every
        node on the path gets an LRU touch. Matches shorter than
        ``min_tokens`` report a miss (0, []) — the caller then pays a full
        private prefill, exactly as if the tree were empty."""
        limit_pages = self.cacheable_tokens(len(prompt)) // self.page_size
        children = self._root
        pages: List[int] = []
        for index in range(limit_pages):
            key = tuple(prompt[index * self.page_size:
                               (index + 1) * self.page_size])
            node = children.get(key)
            if node is None:
                break
            self._tick += 1
            node.last_used = self._tick
            pages.append(node.page)
            children = node.children
        cached = len(pages) * self.page_size
        if cached < self.min_tokens:
            return 0, []
        return cached, pages

    # -- insert ------------------------------------------------------------
    def insert(self, prompt: Sequence[int], row_pages: Sequence[int],
               upto_tokens: int) -> int:
        """Adopt the fully-dispatched pages of a prompt into the tree.

        ``row_pages`` is the slot's page-table row (logical order);
        ``upto_tokens`` is how far prefill has been dispatched — only pages
        wholly inside ``min(upto_tokens, cacheable_tokens)`` are adopted.
        Nodes already present keep their existing page (first writer wins:
        both copies hold identical K/V, so the later one simply stays
        private to its slot and dies with it). Returns newly-adopted page
        count."""
        span = min(int(upto_tokens), self.cacheable_tokens(len(prompt)))
        children = self._root
        parent: Optional[_Node] = None
        adopted = 0
        for index in range(span // self.page_size):
            key = tuple(prompt[index * self.page_size:
                               (index + 1) * self.page_size])
            node = children.get(key)
            if node is None:
                page = int(row_pages[index])
                self.pool.cache_ref(page)
                self._tick += 1
                node = _Node(key, page, parent, self._tick)
                children[key] = node
                self._nodes += 1
                adopted += 1
            else:
                self._tick += 1
                node.last_used = self._tick
            parent = node
            children = node.children
        return adopted

    # -- eviction ----------------------------------------------------------
    def evict(self, need_pages: int) -> int:
        """Free up to ``need_pages`` pages by dropping LRU cache-only
        leaves (refcount 1: no slot shares them — a referenced page is
        never evicted, pinned by the churn property test). Evicting a leaf
        can expose its parent as the next candidate, so long dead branches
        unwind fully. Returns pages actually freed."""
        freed = 0
        while freed < need_pages:
            victim: Optional[_Node] = None
            for node in self._iter_nodes():
                if node.children:
                    continue                      # interior: children first
                if self.pool.refcount(node.page) != 1:
                    continue                      # a slot still shares it
                if victim is None or node.last_used < victim.last_used:
                    victim = node
            if victim is None:
                break
            if self.spill is not None:
                self.spill(self._content_key(victim), victim.page)
            self._detach(victim)
            if self.pool.cache_unref(victim.page):
                freed += 1
            self.evictions += 1
        return freed

    def _content_key(self, node: _Node) -> bytes:
        """The victim's radix content key: the FULL token prefix through
        its page's last position (walk to the root — a page's K/V depends
        on every earlier token, so identity is the whole path, not the
        edge label)."""
        parts: List[Tuple[int, ...]] = []
        cursor: Optional[_Node] = node
        while cursor is not None:
            parts.append(cursor.tokens)
            cursor = cursor.parent
        prefix: List[int] = [token for part in reversed(parts)
                             for token in part]
        return page_content_key(prefix, len(parts) - 1, self.page_size)

    def _detach(self, node: _Node) -> None:
        siblings = (node.parent.children if node.parent is not None
                    else self._root)
        siblings.pop(node.tokens, None)
        self._nodes -= 1

    def clear(self) -> int:
        """Drop every cached page (engine teardown); returns pages freed."""
        freed = 0
        for node in list(self._iter_nodes()):
            if self.pool.cache_unref(node.page):
                freed += 1
        self._root = {}
        self._nodes = 0
        return freed
