"""Serving subsystem: continuous-batching inference over the decode fast
path (docs/SERVING.md).

This package root is deliberately jax-free: the API controller and the
alert-rule sources import it on every boot, and they must not drag the
model stack (jax + models/) into processes that never serve. The heavy
engine lives in :mod:`tensorhive_tpu.serving.engine` and is imported only
by whoever constructs one (GenerationService, tests, smokes, bench).

The process-wide engine is set in ONE place (GenerationService boot, or a
test/smoke harness) and read by the API controller and the alert-rule
sources; ``get_engine`` never constructs — an unconfigured process simply
has no serving plane, and the controller answers 503.
"""
from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .engine import SlotEngine


class AdmissionError(Exception):
    """Base for load-shedding rejections; carries the Retry-After hint the
    API layer surfaces on its 429 response, and the ``request_id`` the
    ledger recorded the rejection under (so a 429 is quotable against
    ``GET /api/admin/requests`` just like a completion is)."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 request_id: Optional[str] = None) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.request_id = request_id


class QueueFullError(AdmissionError):
    """Admission queue is at capacity — the API layer answers 429."""


class RateLimitError(AdmissionError):
    """Per-user concurrency cap exceeded — the API layer answers 429."""


class CheckpointLoadError(Exception):
    """A configured ``[generation_service] checkpoint_path`` could not be
    served (missing, unreadable, or params shaped for a different model
    config). GenerationService catches this at boot, leaves the engine
    unpublished and records the reason — the API answers 503 with it
    instead of the process crashing or silently serving init params."""


__all__ = [
    "AdmissionError",
    "CheckpointLoadError",
    "QueueFullError",
    "RateLimitError",
    "get_engine",
    "get_unavailable_reason",
    "set_engine",
    "set_unavailable_reason",
]

_engine: Optional["SlotEngine"] = None
_unavailable_reason: Optional[str] = None
_engine_lock = threading.Lock()


def get_engine() -> Optional["SlotEngine"]:
    """The process-wide serving engine, or None when serving is disabled.
    Never constructs (building an engine allocates model + cache buffers)."""
    with _engine_lock:
        return _engine


def set_engine(engine: Optional["SlotEngine"]) -> None:
    """Install (or with None: clear) the process-wide engine — called by
    GenerationService at boot and by tests/smokes for isolation. Installing
    a real engine clears any recorded unavailability reason."""
    global _engine, _unavailable_reason
    with _engine_lock:
        _engine = engine
        if engine is not None:
            _unavailable_reason = None


def get_unavailable_reason() -> Optional[str]:
    """Why serving is down beyond 'not enabled' (e.g. a checkpoint shape
    mismatch at boot) — surfaced in the controller's 503 body."""
    with _engine_lock:
        return _unavailable_reason


def set_unavailable_reason(reason: Optional[str]) -> None:
    global _unavailable_reason
    with _engine_lock:
        _unavailable_reason = reason
