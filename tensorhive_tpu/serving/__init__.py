"""Serving subsystem: continuous-batching inference over the decode fast
path (docs/SERVING.md).

This package root is deliberately jax-free: the API controller and the
alert-rule sources import it on every boot, and they must not drag the
model stack (jax + models/) into processes that never serve. The heavy
engine lives in :mod:`tensorhive_tpu.serving.engine` and is imported only
by whoever constructs one (GenerationService, tests, smokes, bench).

The process-wide engine is set in ONE place (GenerationService boot, or a
test/smoke harness) and read by the API controller and the alert-rule
sources; ``get_engine`` never constructs — an unconfigured process simply
has no serving plane, and the controller answers 503.
"""
from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Optional

from ..utils import lockwitness

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .engine import SlotEngine


class AdmissionError(Exception):
    """Base for load-shedding rejections; carries the Retry-After hint the
    API layer surfaces on its 429 response, and the ``request_id`` the
    ledger recorded the rejection under (so a 429 is quotable against
    ``GET /api/admin/requests`` just like a completion is)."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 request_id: Optional[str] = None) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.request_id = request_id


class QueueFullError(AdmissionError):
    """Admission queue is at capacity — the API layer answers 429."""


class RateLimitError(AdmissionError):
    """Per-user concurrency cap exceeded — the API layer answers 429."""


class EngineDrainingError(AdmissionError):
    """The engine is draining (admin drain or shutdown in progress): no new
    admissions while in-flight requests finish. The API layer answers 503
    with an honest Retry-After — unlike the 429s above, this is not load
    shedding; the plane is deliberately going quiet
    (docs/ROBUSTNESS.md "Serving data plane")."""


class CheckpointLoadError(Exception):
    """A configured ``[generation_service] checkpoint_path`` could not be
    served (missing, unreadable, or params shaped for a different model
    config). GenerationService catches this at boot, leaves the engine
    unpublished and records the reason — the API answers 503 with it
    instead of the process crashing or silently serving init params."""


__all__ = [
    "AdmissionError",
    "CheckpointLoadError",
    "EngineDrainingError",
    "QueueFullError",
    "RateLimitError",
    "get_engine",
    "get_serving_state",
    "get_unavailable_reason",
    "set_engine",
    "set_unavailable_reason",
    "update_serving_state",
]

_engine: Optional["SlotEngine"] = None
_unavailable_reason: Optional[str] = None
_engine_lock = lockwitness.Lock("tensorhive_tpu.serving._engine_lock")

#: supervisor lifecycle state (docs/ROBUSTNESS.md "Serving data plane"),
#: published by GenerationService and read by the controller's 503 path
#: (retry_after_s), the engine_crash_loop alert source and /api/readyz.
#: Jax-free on purpose, like everything else in this package root.
_serving_state = {
    #: a GenerationService supervisor owns this process's serving plane —
    #: readyz only reports a serving component while this is True (or a
    #: drain is in progress on a harness-installed engine)
    "supervisor_active": False,
    #: the restart budget was exhausted inside the window: the breaker is
    #: open and the plane 503s until a cooldown-gated rebuild succeeds
    "crash_loop": False,
    #: successful engine rebuilds since the supervisor started
    "restarts": 0,
    #: honest Retry-After hint for the 503 path (seconds); None = use the
    #: controller's default
    "retry_after_s": None,
}


def get_serving_state() -> dict:
    """Snapshot of the supervisor lifecycle state (copy; see module var)."""
    with _engine_lock:
        return dict(_serving_state)


def update_serving_state(**updates) -> None:
    """Merge supervisor lifecycle updates (unknown keys rejected — the
    state is a contract between the supervisor and its readers)."""
    with _engine_lock:
        for key, value in updates.items():
            if key not in _serving_state:
                raise KeyError(f"unknown serving state key {key!r}")
            _serving_state[key] = value


def get_engine() -> Optional["SlotEngine"]:
    """The process-wide serving engine, or None when serving is disabled.
    Never constructs (building an engine allocates model + cache buffers)."""
    with _engine_lock:
        return _engine


def set_engine(engine: Optional["SlotEngine"]) -> None:
    """Install (or with None: clear) the process-wide engine — called by
    GenerationService at boot and by tests/smokes for isolation. Installing
    a real engine clears any recorded unavailability reason and the
    crash-loop flag (a published engine IS the recovery signal)."""
    global _engine, _unavailable_reason
    with _engine_lock:
        _engine = engine
        if engine is not None:
            _unavailable_reason = None
            _serving_state["crash_loop"] = False
            _serving_state["retry_after_s"] = None


def get_unavailable_reason() -> Optional[str]:
    """Why serving is down beyond 'not enabled' (e.g. a checkpoint shape
    mismatch at boot) — surfaced in the controller's 503 body."""
    with _engine_lock:
        return _unavailable_reason


def set_unavailable_reason(reason: Optional[str]) -> None:
    global _unavailable_reason
    with _engine_lock:
        _unavailable_reason = reason
