"""Serving flight recorder: a per-tick ring plus crash dumps on fatal.

The PR 14 supervisor classifies pump failures and tears a fatal engine
down — but until now it recorded nothing about the ticks that led there:
by the time anyone looks, the engine (queue depths, slot occupancy, the
fault that fired) is gone. The flight recorder fixes that post-mortem
gap:

* :class:`FlightRecorder` — a bounded ring the SlotEngine stamps once
  per ``step()``: tick duration, per-phase work counts (admitted /
  prefill chunks / decode slots), slots busy, free KV pages, queue
  depth, compile events and fault-plan injections. Preallocated numpy
  columns, single writer (the pump thread), no locks beyond an index
  bump — near-zero overhead, and **pure host bookkeeping**: nothing here
  touches a traced operand, so the zero-recompile gates are untouched.
* :func:`write_crash_dump` — on fatal classification the supervisor
  snapshots the last N ticks, the in-flight ledger rows and the firing
  alerts into a JSON file under ``{config_dir}/flightrec/`` *before*
  failing the in-flight requests, so the dump shows what was actually
  running. Old dumps are pruned past ``flightrec_dumps``.

Served live at ``GET /api/admin/flightrec`` and post-mortem at
``GET /api/admin/flightrec/dumps`` (docs/OBSERVABILITY.md "History,
SLOs & flight recorder"). This module is jax-free so the supervisor and
controllers can import it on any host.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import re
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)

DUMP_SCHEMA_VERSION = 1

#: ring columns in storage order; ``snapshot()`` emits them camelCased
FIELDS = (
    "duration_s",
    "admitted",
    "prefill_chunks",
    "decode_slots",
    "slots_busy",
    "queue_depth",
    "pages_free",
    "compiles",
    "faults",
    "host_demotions",
    "host_promotions",
)

_CAMEL = {
    "duration_s": "durationS",
    "prefill_chunks": "prefillChunks",
    "decode_slots": "decodeSlots",
    "slots_busy": "slotsBusy",
    "queue_depth": "queueDepth",
    "pages_free": "pagesFree",
    "host_demotions": "hostDemotions",
    "host_promotions": "hostPromotions",
}

_DUMP_NAME_RE = re.compile(r"^crash-\d{8}T\d{6}-\d+(-\d{3})?\.json$")

#: per-process dump sequence: two fatals inside the same wall-clock second
#: (a crash loop chewing its restart budget) must not overwrite each other
_dump_seq = itertools.count()


class FlightRecorder:
    """Bounded per-tick ring over preallocated numpy columns. The single
    pump-thread writer appends with a plain index bump; readers take
    consistent-enough snapshots (a torn in-progress row is acceptable —
    this is a black box, not a ledger)."""

    def __init__(self, capacity: int = 512) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ts = np.zeros(self.capacity, dtype=np.float64)
        self._duration = np.zeros(self.capacity, dtype=np.float64)
        self._counts = np.zeros((len(FIELDS) - 1, self.capacity),
                                dtype=np.int64)
        self._idx = 0       # monotone tick counter; ring slot = idx % cap

    def record(self, *, duration_s: float, admitted: int = 0,
               prefill_chunks: int = 0, decode_slots: int = 0,
               slots_busy: int = 0, queue_depth: int = 0,
               pages_free: int = 0, compiles: int = 0,
               faults: int = 0, host_demotions: int = 0,
               host_promotions: int = 0,
               ts: Optional[float] = None) -> None:
        """Stamp one tick. Hot path: column writes + one index bump."""
        slot = self._idx % self.capacity
        self._ts[slot] = time.time() if ts is None else ts
        self._duration[slot] = duration_s
        col = self._counts
        col[0, slot] = admitted
        col[1, slot] = prefill_chunks
        col[2, slot] = decode_slots
        col[3, slot] = slots_busy
        col[4, slot] = queue_depth
        col[5, slot] = pages_free
        col[6, slot] = compiles
        col[7, slot] = faults
        col[8, slot] = host_demotions
        col[9, slot] = host_promotions
        self._idx += 1

    @property
    def recorded(self) -> int:
        """Total ticks ever recorded (not capped at capacity)."""
        return self._idx

    def __len__(self) -> int:
        return min(self._idx, self.capacity)

    def snapshot(self, last_n: Optional[int] = None) -> List[Dict]:
        """Last ``last_n`` ticks (default: all retained), oldest first,
        as JSON-ready dicts."""
        count = len(self)
        if last_n is not None:
            count = min(count, max(int(last_n), 0))
        end = self._idx
        rows: List[Dict] = []
        for tick in range(end - count, end):
            slot = tick % self.capacity
            row = {
                "tick": tick,
                "ts": round(float(self._ts[slot]), 6),
                "durationS": round(float(self._duration[slot]), 6),
            }
            for offset, name in enumerate(FIELDS[1:]):
                row[_CAMEL.get(name, name)] = int(self._counts[offset, slot])
            rows.append(row)
        return rows

    def clear(self) -> None:
        self._idx = 0
        self._ts.fill(0.0)
        self._duration.fill(0.0)
        self._counts.fill(0)


# -- crash dumps --------------------------------------------------------------

def write_crash_dump(directory: str, *, reason: str,
                     recorder: Optional[FlightRecorder],
                     inflight: Sequence[Dict] = (),
                     alerts: Sequence = (),
                     max_dumps: int = 8,
                     now: Optional[float] = None) -> str:
    """Snapshot the recorder ring + in-flight ledger rows + firing alerts
    into ``{directory}/crash-<utc>-<pid>.json`` and prune the oldest
    dumps past ``max_dumps``. Returns the written path. Callers (the
    supervisor's fail-fast path) must treat failures as best-effort —
    never let the post-mortem block the teardown."""
    if now is None:
        now = time.time()
    os.makedirs(directory, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
    # fixed-width sequence keeps lexical order == write order within the
    # same second, so prune/list newest-first stay correct
    seq = next(_dump_seq) % 1000
    path = os.path.join(
        directory, f"crash-{stamp}-{os.getpid()}-{seq:03d}.json")
    dump = {
        "schemaVersion": DUMP_SCHEMA_VERSION,
        "writtenTs": round(now, 3),
        "reason": str(reason),
        "ticks": recorder.snapshot() if recorder is not None else [],
        "ticksRecorded": recorder.recorded if recorder is not None else 0,
        "inFlight": list(inflight),
        "firingAlerts": list(alerts),
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as handle:
        json.dump(dump, handle, indent=1)
    os.replace(tmp, path)
    _prune_dumps(directory, max_dumps)
    return path


def _prune_dumps(directory: str, max_dumps: int) -> None:
    dumps = sorted(name for name in os.listdir(directory)
                   if _DUMP_NAME_RE.match(name))
    for name in dumps[:max(len(dumps) - max(int(max_dumps), 1), 0)]:
        try:
            os.remove(os.path.join(directory, name))
        except OSError:     # pragma: no cover - racing prune is fine
            log.warning("flightrec: could not prune %s", name)


def list_crash_dumps(directory: str) -> List[Dict]:
    """Summaries (newest first) of the dumps on disk — the
    ``/api/admin/flightrec/dumps`` index."""
    if not os.path.isdir(directory):
        return []
    summaries: List[Dict] = []
    for name in sorted(os.listdir(directory), reverse=True):
        if not _DUMP_NAME_RE.match(name):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path, "r", encoding="utf-8") as handle:
                dump = json.load(handle)
        except (OSError, ValueError):
            log.warning("flightrec: unreadable dump %s", name)
            continue
        summaries.append({
            "file": name,
            "writtenTs": dump.get("writtenTs"),
            "reason": dump.get("reason"),
            "ticks": len(dump.get("ticks", [])),
            "inFlight": len(dump.get("inFlight", [])),
            "firingAlerts": len(dump.get("firingAlerts", [])),
        })
    return summaries


def load_crash_dump(directory: str, name: str) -> Optional[Dict]:
    """Load one dump by filename; the strict name pattern doubles as
    path-traversal validation. None when missing or unreadable."""
    if not _DUMP_NAME_RE.match(name):
        return None
    path = os.path.join(directory, name)
    try:
        with open(path, "r", encoding="utf-8") as handle:
            return json.load(handle)
    except (OSError, ValueError):
        return None
