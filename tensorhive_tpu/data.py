"""Token data pipeline: memmapped shards → deterministic batches → device.

The reference leaves data entirely to the launched user program (its job
module just spawns commands, SURVEY.md §0); a complete training framework
needs the input path too. Design goals, TPU-first:

* **Stateless, step-addressable sampling** — ``batch_at(step)`` derives the
  batch purely from (seed, step), so preemption/resume (the queued-workload
  path, examples/queued_training) needs no iterator state in checkpoints:
  restoring the step count restores the data position exactly.
* **Multihost sharding** — each host materializes only its slice of the
  global batch (``host_batch_at``), matching ``parallel/mesh.batch_sharding``
  row order, so a jax.distributed run feeds per-host shards that concatenate
  to the same global batch every single-host run would see.
* **Host→device prefetch** — double-buffered ``jax.device_put`` so the next
  batch's transfer overlaps the current step (HBM stays the bottleneck, not
  PCIe/host).

Shard format: raw little-endian token files (uint16 for vocab ≤ 65536,
uint32 otherwise), concatenated logically in sorted filename order — the
format produced by the common GPT tokenizer dump scripts.
"""
from __future__ import annotations

import dataclasses
import glob as globlib
import threading
from typing import Iterator, List, Optional

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class DataConfig:
    pattern: str                 # glob for token shard files
    seq_len: int = 1024          # model sequence length (batches are +1 wide)
    batch_size: int = 8          # GLOBAL batch size
    seed: int = 0
    dtype: str = "uint16"
    #: model vocabulary size; when set, every produced batch is validated —
    #: jax's gather silently CLAMPS out-of-range ids, so a tokenizer/model
    #: vocab mismatch would otherwise train on corrupted data with healthy-
    #: looking metrics
    vocab_size: Optional[int] = None


class TokenDataset:
    """Logically concatenated memmapped token shards with deterministic,
    step-addressable window sampling."""

    def __init__(self, config: DataConfig) -> None:
        self.config = config
        paths = sorted(globlib.glob(config.pattern))
        if not paths:
            raise FileNotFoundError(f"no token shards match {config.pattern!r}")
        self._shards: List[np.memmap] = [
            np.memmap(path, dtype=np.dtype(config.dtype), mode="r")
            for path in paths
        ]
        lengths = [len(shard) for shard in self._shards]
        #: exclusive prefix sums: shard i covers [starts[i], starts[i+1])
        self._starts = np.concatenate([[0], np.cumsum(lengths)])
        self.total_tokens = int(self._starts[-1])
        self.window = config.seq_len + 1          # inputs + shifted targets
        if self.total_tokens < self.window:
            raise ValueError(
                f"dataset has {self.total_tokens} tokens < one "
                f"window of {self.window}")

    # -- addressing ---------------------------------------------------------

    def _read_window(self, offset: int) -> np.ndarray:
        """Window [offset, offset+window) across shard boundaries."""
        out = np.empty(self.window, np.int32)
        filled = 0
        while filled < self.window:
            pos = offset + filled
            shard_index = int(np.searchsorted(self._starts, pos, side="right")) - 1
            shard = self._shards[shard_index]
            local = pos - int(self._starts[shard_index])
            take = min(self.window - filled, len(shard) - local)
            out[filled:filled + take] = shard[local:local + take]
            filled += take
        return out

    def _offsets_at(self, step: int) -> np.ndarray:
        """All window offsets for ``step``, from a counter-based RNG keyed
        on (seed, step) — any process computes the identical offsets for a
        given step, across restarts, hosts, and topology changes."""
        config = self.config
        rng = np.random.Generator(np.random.Philox(
            key=np.uint64(config.seed), counter=[0, 0, 0, np.uint64(step)]))
        return rng.integers(
            0, self.total_tokens - self.window + 1, size=config.batch_size)

    def _check_vocab(self, batch: np.ndarray) -> np.ndarray:
        vocab = self.config.vocab_size
        if vocab is not None:
            top = int(batch.max())
            if top >= vocab:
                raise ValueError(
                    f"shard token id {top} >= model vocab_size {vocab} — "
                    f"tokenizer/model mismatch (jax would silently clamp)")
        return batch

    def batch_at(self, step: int) -> np.ndarray:
        """Global batch for ``step``: [batch_size, seq_len+1] int32."""
        return self._check_vocab(np.stack(
            [self._read_window(int(o)) for o in self._offsets_at(step)]))

    def host_batch_at(self, step: int, process_index: Optional[int] = None,
                      process_count: Optional[int] = None) -> np.ndarray:
        """This host's contiguous row-slice of the global batch (row order
        matches batch_sharding). Only this host's rows touch disk — offsets
        are cheap to generate globally, windows are not."""
        if process_index is None:
            process_index = jax.process_index()
        if process_count is None:
            process_count = jax.process_count()
        if self.config.batch_size % process_count:
            raise ValueError(
                f"global batch {self.config.batch_size} not divisible by "
                f"{process_count} processes")
        rows = self.config.batch_size // process_count
        offsets = self._offsets_at(step)[process_index * rows:
                                         (process_index + 1) * rows]
        return self._check_vocab(
            np.stack([self._read_window(int(o)) for o in offsets]))


def prefetch_to_device(
    dataset: TokenDataset,
    start_step: int,
    num_steps: int,
    sharding=None,
    buffer_size: int = 2,
) -> Iterator[jax.Array]:
    """Iterate device-resident batches for steps [start_step, start_step +
    num_steps), reading + transferring ``buffer_size`` batches ahead of the
    consumer on a background thread."""
    import queue

    todo = queue.Queue(maxsize=buffer_size)
    stop = threading.Event()
    multihost = jax.process_count() > 1

    def to_device(host_rows):
        if multihost:
            # each process contributes only its local rows; jax assembles
            # the global array matching the sharding's per-process layout
            return jax.make_array_from_process_local_data(sharding, host_rows)
        return jax.device_put(host_rows, sharding)

    def enqueue(item) -> bool:
        """put() that keeps observing stop so an abandoned consumer never
        leaves this thread parked on a full queue holding device buffers."""
        while not stop.is_set():
            try:
                todo.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for step in range(start_step, start_step + num_steps):
                if stop.is_set():
                    return
                host = dataset.host_batch_at(step) if multihost \
                    else dataset.batch_at(step)
                if not enqueue(to_device(host)):
                    return
            enqueue(None)
        except BaseException as exc:  # surfaces in the consumer, not lost
            enqueue(exc)

    thread = threading.Thread(target=producer, daemon=True,
                              name="data-prefetch")
    thread.start()
    try:
        while True:
            item = todo.get()
            if item is None:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def fake_shards(directory, num_shards: int = 2, tokens_per_shard: int = 4096,
                vocab_size: int = 32_000, seed: int = 0,
                dtype: str = "uint16") -> str:
    """Write synthetic token shards; returns the glob pattern. Test/demo
    helper so examples are runnable without a corpus."""
    rng = np.random.default_rng(seed)
    from pathlib import Path

    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    for index in range(num_shards):
        tokens = rng.integers(0, vocab_size, size=tokens_per_shard)
        tokens.astype(np.dtype(dtype)).tofile(directory / f"shard_{index:04d}.bin")
    return str(directory / "shard_*.bin")
