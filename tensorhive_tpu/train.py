"""Sharded training: optimizer, jitted step, checkpointing, data.

The training loop the acceptance workloads run (BASELINE.json configs 3-5).
One ``make_train_step`` builds a donated, fully-sharded jit:

* params/opt-state sharded by the mesh rules (fsdp/tp),
* batches sharded dp+fsdp over batch and sp over sequence,
* loss/grad in f32 with bf16 matmuls (models/transformer.py),
* gradient sync is implicit — XLA inserts psum/reduce-scatter from the
  shardings (the scaling-book recipe; no hand-written collectives).

Checkpoint/resume via orbax (the reference has no training checkpoints —
SURVEY.md §5 "checkpoint/resume: user program's concern"; here the user
program is part of the framework, so it IS our concern).
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .models.transformer import Params, TransformerConfig, TransformerLM
from .parallel.mesh import batch_sharding, tree_shardings

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10_000
    weight_decay: float = 0.01
    max_grad_norm: float = 1.0
    batch_size: int = 8          # GLOBAL tokens-batch per optimizer step
    seq_len: int = 512
    #: microbatches per optimizer step (1 = none). The [batch_size, L+1]
    #: step input is split into grad_accum_steps microbatches scanned
    #: sequentially with f32 gradient accumulation — big effective batches
    #: on small slices at 1/grad_accum_steps the activation memory
    grad_accum_steps: int = 1


def make_optimizer(config: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=config.learning_rate,
        warmup_steps=config.warmup_steps,
        decay_steps=config.total_steps,
    )
    return optax.chain(
        optax.clip_by_global_norm(config.max_grad_norm),
        optax.adamw(schedule, weight_decay=config.weight_decay),
    )


def init_train_state(
    key: jax.Array,
    model_config: TransformerConfig,
    train_config: TrainConfig,
    mesh: Optional[Mesh] = None,
) -> Tuple[Params, Any]:
    """Initialize params + opt state, placed according to the mesh rules
    (init runs through jit with out_shardings so large models materialize
    directly sharded, never replicated on one device)."""
    if mesh is None:
        params = TransformerLM.init(key, model_config)
        opt_state = make_optimizer(train_config).init(params)
        return params, opt_state

    param_shape = jax.eval_shape(lambda k: TransformerLM.init(k, model_config), key)
    shardings = tree_shardings(mesh, param_shape)
    params = jax.jit(
        lambda k: TransformerLM.init(k, model_config), out_shardings=shardings
    )(key)
    optimizer = make_optimizer(train_config)
    opt_shape = jax.eval_shape(optimizer.init, param_shape)
    opt_shardings = _opt_state_shardings(mesh, opt_shape, shardings)
    opt_state = jax.jit(optimizer.init, out_shardings=opt_shardings)(params)
    return params, opt_state


def _opt_state_shardings(mesh: Mesh, opt_shape, param_shardings):
    """Shardings for the optimizer state: any subtree structurally identical
    to the param tree (Adam's mu/nu moments) mirrors the param shardings;
    everything else (step counts, schedule state) replicates."""
    param_flat, param_def = jax.tree_util.tree_flatten(param_shardings)
    replicated = NamedSharding(mesh, P())

    def walk(node):
        flat, treedef = jax.tree_util.tree_flatten(node)
        if treedef == param_def:
            return jax.tree_util.tree_unflatten(treedef, param_flat)
        if isinstance(node, dict):
            return {key: walk(child) for key, child in node.items()}
        if hasattr(node, "_fields"):  # NamedTuple state records
            return type(node)(*(walk(child) for child in node))
        if isinstance(node, tuple):
            return tuple(walk(child) for child in node)
        if isinstance(node, list):
            return [walk(child) for child in node]
        return replicated

    return walk(opt_shape)


def make_train_step(
    model_config: TransformerConfig,
    train_config: TrainConfig,
    mesh: Optional[Mesh] = None,
    loss_fn: Callable = TransformerLM.loss,
) -> Callable:
    """Build the jitted train step: (params, opt_state, tokens) ->
    (params, opt_state, metrics). Params/opt-state buffers are donated.

    ``loss_fn(params, batch, model_config, mesh)`` defaults to the causal
    LM loss; the MLM encoder family passes models/encoder.mlm_loss_packed
    with its [B, 3, L] packed batches — everything else (sharding,
    donation, grad accumulation) is objective-agnostic."""
    optimizer = make_optimizer(train_config)
    accum = train_config.grad_accum_steps
    if accum < 1:
        raise ValueError(f"grad_accum_steps must be >= 1, got {accum}")
    if accum > 1 and train_config.batch_size % accum:
        raise ValueError(
            f"batch_size {train_config.batch_size} not divisible by "
            f"grad_accum_steps {accum}")

    def loss_and_grads(params, tokens):
        if accum <= 1:
            return jax.value_and_grad(loss_fn)(
                params, tokens, model_config, mesh)
        micro = train_config.batch_size // accum
        micro_tokens = tokens.reshape(accum, micro, *tokens.shape[1:])

        def one_micro(carry, batch_slice):
            loss_sum, grads_sum = carry
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch_slice, model_config, mesh)
            grads = jax.tree_util.tree_map(
                lambda acc, g: acc + g.astype(acc.dtype), grads_sum, grads)
            return (loss_sum + loss, grads), None

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (loss_sum, grads), _ = jax.lax.scan(
            one_micro, (jnp.float32(0.0), zeros), micro_tokens)
        scale = 1.0 / accum
        return loss_sum * scale, jax.tree_util.tree_map(
            lambda g: (g * scale).astype(jnp.float32), grads)

    def step(params, opt_state, tokens):
        loss, grads = loss_and_grads(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        grad_norm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": grad_norm}

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))

    data_sharding = batch_sharding(mesh)
    return jax.jit(
        step,
        in_shardings=(None, None, data_sharding),  # params keep their placement
        donate_argnums=(0, 1),
    )


def synthetic_batch(key: jax.Array, train_config: TrainConfig,
                    vocab_size: int) -> jax.Array:
    """Deterministic synthetic LM batch [B, L+1] (benchmarks + tests)."""
    return jax.random.randint(
        key, (train_config.batch_size, train_config.seq_len + 1), 0, vocab_size,
        dtype=jnp.int32,
    )


# -- checkpointing (orbax) ---------------------------------------------------

def save_checkpoint(path: str, step: int, params: Params, opt_state,
                    max_to_keep: int = 3) -> None:
    """Save one step, retaining only the newest ``max_to_keep`` steps — a
    preemption-resumable long run (examples/queued_training) checkpoints
    every few hundred steps and must not grow the disk without bound."""
    import orbax.checkpoint as ocp

    options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep)
    with ocp.CheckpointManager(path, options=options) as manager:
        manager.save(step, args=ocp.args.PyTreeSave({"params": params,
                                                     "opt_state": opt_state}))


def abstract_train_state(
    model_config: TransformerConfig,
    train_config: TrainConfig,
    mesh: Optional[Mesh] = None,
) -> Tuple[Any, Any]:
    """(params, opt_state) as ShapeDtypeStructs carrying shardings — zero
    device allocation. Feed these to ``restore_checkpoint`` on the resume
    path so restore never holds a throwaway initialized copy next to the
    restored one (at ~2× model+optimizer memory, large presets OOM exactly
    on the preemption-resume path the checkpoints exist for)."""
    key = jax.random.PRNGKey(0)      # shapes only — never materialized
    param_shape = jax.eval_shape(
        lambda k: TransformerLM.init(k, model_config), key)
    optimizer = make_optimizer(train_config)
    opt_shape = jax.eval_shape(optimizer.init, param_shape)
    if mesh is None:
        placement = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        shardings = jax.tree_util.tree_map(lambda _: placement, param_shape)
        opt_shardings = jax.tree_util.tree_map(lambda _: placement, opt_shape)
    else:
        shardings = tree_shardings(mesh, param_shape)
        opt_shardings = _opt_state_shardings(mesh, opt_shape, shardings)

    def as_abstract(leaf, sharding):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sharding)

    return (jax.tree_util.tree_map(as_abstract, param_shape, shardings),
            jax.tree_util.tree_map(as_abstract, opt_shape, opt_shardings))


def _abstract_like(tree):
    """Concrete arrays → ShapeDtypeStructs (keeping shardings); abstract
    leaves pass through. Restore templates must not pin device buffers."""
    return jax.tree_util.tree_map(
        lambda x: x if isinstance(x, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(x.shape, x.dtype,
                                  sharding=getattr(x, "sharding", None)),
        tree)


def restore_checkpoint(path: str, params_like, opt_state_like) -> Tuple[int, Params, Any]:
    """Restore the latest step; shapes AND shardings follow the *_like trees.

    The templates may be concrete arrays or ShapeDtypeStructs (see
    ``abstract_train_state``); either way they are reduced to abstract
    arrays carrying their shardings before orbax runs, so orbax RESHARDS
    onto the current topology — restoring with the sharding recorded at
    save time would break the elastic-resume path (re-launch on a different
    slice shape after preemption) the moment the saved mesh's devices no
    longer exist. Prefer abstract templates on the resume path: a concrete
    template keeps its device buffers alive while orbax materializes the
    restored copy (~2× peak memory)."""
    import orbax.checkpoint as ocp

    template = {"params": _abstract_like(params_like),
                "opt_state": _abstract_like(opt_state_like)}
    restore_args = ocp.checkpoint_utils.construct_restore_args(template)
    with ocp.CheckpointManager(path) as manager:
        step = manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {path}")
        restored = manager.restore(
            step,
            args=ocp.args.PyTreeRestore(template, restore_args=restore_args),
        )
    return step, restored["params"], restored["opt_state"]


def train_loop(
    model_config: TransformerConfig,
    train_config: TrainConfig,
    mesh: Optional[Mesh] = None,
    num_steps: int = 10,
    seed: int = 0,
    log_every: int = 10,
    telemetry=None,
    sync_every: int = 1,
    batches=None,
    loss_fn: Callable = TransformerLM.loss,
) -> Dict[str, float]:
    """Minimal complete loop; returns final metrics. Batches come from the
    ``batches`` iterator when given (e.g. data.prefetch_to_device over token
    shards) and synthetic data otherwise — the self-contained path bench.py
    and the examples' smoke modes use.

    ``sync_every``: block on the device only every N steps. Per-step blocking
    costs the host→device dispatch gap every step (~25% on a tunneled v5e);
    real training loops enqueue steps back-to-back, which N>1 reproduces —
    the reported step time is then wall-clock over each N-step window."""
    key = jax.random.PRNGKey(seed)
    params, opt_state = init_train_state(key, model_config, train_config, mesh)
    step_fn = make_train_step(model_config, train_config, mesh,
                              loss_fn=loss_fn)
    window_times = []           # (per-step seconds, is_full_window)
    metrics_dev = None
    window_start = time.perf_counter()
    window_len = 0
    last_logged = 0
    for step_index in range(num_steps):
        if batches is not None:
            try:
                tokens = next(batches)
            except StopIteration:
                raise ValueError(
                    f"batches iterator exhausted at step {step_index} of "
                    f"{num_steps}") from None
        else:
            key, data_key = jax.random.split(key)
            tokens = synthetic_batch(data_key, train_config,
                                     model_config.vocab_size)
        params, opt_state, metrics_dev = step_fn(params, opt_state, tokens)
        window_len += 1
        if window_len >= sync_every or step_index == num_steps - 1:
            # sync via an actual device→host read: block_until_ready has
            # been observed returning early on tunneled TPU runtimes, which
            # silently turns timings into dispatch-only measurements — a
            # 4-byte loss transfer cannot complete before the step has
            loss_value = float(metrics_dev["loss"])
            now = time.perf_counter()
            per_step = (now - window_start) / window_len
            window_times.append((per_step, window_len >= sync_every))
            if telemetry is not None:
                telemetry.sample(step_time_s=per_step)
            # "log roughly every log_every steps", honored at sync points
            # (sync_every need not divide log_every)
            if log_every and (step_index + 1) - last_logged >= log_every:
                log.info("step %d loss=%.4f (%.1f ms)", step_index + 1,
                         loss_value, per_step * 1e3)
                last_logged = step_index + 1
            window_start = now
            window_len = 0
    metrics = {k: float(v) for k, v in metrics_dev.items()}
    step_time, rejected = _steady_step_time(window_times)
    metrics["rejected_windows"] = float(rejected)
    metrics["step_time_s"] = step_time
    metrics["steps_per_sec"] = 1.0 / step_time
    return metrics


def _steady_step_time(window_times) -> Tuple[float, int]:
    """(median steady per-step seconds, #windows rejected as stalls) from
    a list of (per-step seconds, is_full_window) timing windows.

    Drops the compile-laden first window and trailing partial windows (a
    short window re-pays the per-sync host gap the windowing exists to
    amortize), then rejects flake-stalled windows: a transient runtime
    stall (a dropped remote-compile connection being retried, a host
    hiccup) inflates one window 10-30x, and with only 2-3 windows the
    median itself is poisoned (BENCH_r03 recorded 4269 ms for a 274 ms
    step this way). A window more than 3x the fastest window is a stall,
    not a measurement. If the fastest window is itself bogus-fast (skipped
    device sync), the resulting implausible step time trips the caller's
    re-measure guard (bench.py sub-5ms check)."""
    steady = [t for t, full in window_times[1:] if full] \
        or [t for t, _ in window_times[1:]] \
        or [t for t, _ in window_times]
    floor = min(steady)
    kept = [t for t in steady if t <= 3.0 * floor]
    return sorted(kept)[len(kept) // 2], len(steady) - len(kept)
